"""Inference predictor facade (VERDICT r2 item 9; reference:
AnalysisPredictor inference/api/analysis_predictor.h:105 scoped to the
TPU-sensible subset): Config/create_predictor handle API over jit.save'd
STABLEHLO, plus the LLM serving path — save → load in a FRESH process →
paged-KV generate() equality vs the in-process rollout for GPT and Llama.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import (Config, LLMPredictor, Predictor,
                                  create_predictor)
from paddle_tpu.static import InputSpec


def _np(x):
    return np.asarray(x._value)


class TestPredictorFacade:
    def _save_model(self, tmp_path):
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        net.eval()
        prefix = str(tmp_path / "m")
        paddle.jit.save(net, prefix, input_spec=[InputSpec([None, 4])])
        return net, prefix

    def test_handle_api_matches_eager(self, tmp_path):
        net, prefix = self._save_model(tmp_path)
        pred = create_predictor(Config(prefix))
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, _np(net(paddle.to_tensor(x))),
                                   atol=1e-5)

    def test_direct_run_api(self, tmp_path):
        net, prefix = self._save_model(tmp_path)
        pred = Predictor(Config(prefix + ".pdmodel"))
        x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        (out,) = pred.run([x])
        np.testing.assert_allclose(out, _np(net(paddle.to_tensor(x))),
                                   atol=1e-5)

    def test_missing_model_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            Predictor(Config(str(tmp_path / "nope")))

    def test_dynamic_batch(self, tmp_path):
        net, prefix = self._save_model(tmp_path)
        pred = create_predictor(Config(prefix))
        for b in (1, 5):
            x = np.random.randn(b, 4).astype(np.float32)
            (out,) = pred.run([x])
            assert out.shape == (b, 2)


_FRESH_GEN = r"""
import sys
sys.path.insert(0, "/root/repo")
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from paddle_tpu.inference import create_llm_predictor
pred = create_llm_predictor(sys.argv[1])
ids = np.load(sys.argv[2])
out = pred.generate(ids, max_new_tokens=5, temperature=0.0)
np.save(sys.argv[3], np.asarray(out))
"""


class TestLLMServing:
    def _fresh_process_generate(self, tmp_path, family, cfg, params, ids):
        pred = LLMPredictor(family, cfg, params)
        mdir = str(tmp_path / f"{family}_model")
        pred.save(mdir)
        np.save(str(tmp_path / "ids.npy"), ids)
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        out_path = str(tmp_path / "out.npy")
        r = subprocess.run(
            [sys.executable, "-c", _FRESH_GEN, mdir,
             str(tmp_path / "ids.npy"), out_path],
            env=env, capture_output=True, text=True, timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        return np.load(out_path)

    @pytest.mark.slow
    def test_gpt_fresh_process_generate_equality(self, tmp_path):
        from paddle_tpu.models.generation import gpt_generate
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
        from paddle_tpu import parallel as dist
        from paddle_tpu.parallel.topology import HybridTopology, set_topology
        cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=64)
        dist.init_topology()
        _, init_fn = build_gpt_train_step(cfg, None, num_microbatches=1)
        params = init_fn(0)["params"]
        set_topology(HybridTopology())
        ids = np.random.RandomState(0).integers(0, 97, (2, 8)) \
            if hasattr(np.random.RandomState(0), "integers") else \
            np.random.RandomState(0).randint(0, 97, (2, 8))
        ids = np.asarray(ids, np.int32)
        want = np.asarray(gpt_generate(params, cfg, ids, max_new_tokens=5,
                                       temperature=0.0))
        got = self._fresh_process_generate(tmp_path, "gpt", cfg, params,
                                           ids)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.slow
    def test_llama_fresh_process_generate_equality(self, tmp_path):
        from paddle_tpu.models.generation import llama_generate
        from paddle_tpu.models.llama import (LlamaConfig,
                                             build_llama_train_step)
        from paddle_tpu import parallel as dist
        from paddle_tpu.parallel.topology import HybridTopology, set_topology
        cfg = LlamaConfig(vocab_size=97, hidden_size=32, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          max_position_embeddings=64)
        dist.init_topology()
        _, init_fn = build_llama_train_step(cfg, None, num_microbatches=1)
        params = init_fn(0)["params"]
        set_topology(HybridTopology())
        ids = np.asarray(
            np.random.RandomState(1).randint(0, 97, (1, 6)), np.int32)
        want = np.asarray(llama_generate(params, cfg, ids,
                                         max_new_tokens=5, temperature=0.0))
        got = self._fresh_process_generate(tmp_path, "llama", cfg, params,
                                           ids)
        np.testing.assert_array_equal(got, want)
