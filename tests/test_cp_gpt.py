"""GPT hybrid step with explicit context parallelism (ring / Ulysses) must
match the single-device (no-CP) loss bit-for-bit in math terms."""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as pt
from paddle_tpu import parallel as dist
from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step


def _run(cp_mode, sep, pp=1, num_microbatches=1):
    topo = dist.init_topology(dp=1, mp=1, pp=pp, sep=sep, sharding=1)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64)
    step_fn, init_fn = build_gpt_train_step(
        cfg, topo, num_microbatches=num_microbatches, cp_mode=cp_mode)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (4, 64)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    losses = []
    for _ in range(3):
        state, loss = step_fn(state, ids, labels)
        losses.append(float(np.asarray(jax.device_get(loss))))
    return losses


@pytest.mark.parametrize("cp_mode", ["ring", "ulysses"])
def test_gpt_cp_matches_no_cp(cp_mode):
    base = _run(None, 1)
    cp = _run(cp_mode, 4)
    np.testing.assert_allclose(cp, base, rtol=2e-4, atol=1e-5)
    assert all(np.isfinite(base))
    # loss should decrease over 3 steps of Adam on the same batch
    assert base[-1] < base[0]


@pytest.mark.parametrize("cp_mode", ["ring", "ulysses"])
def test_gpt_cp_with_pipeline_matches_baseline(cp_mode):
    """pp2×sep2: the CP specs inside the pipeline shard_map must preserve
    the exact loss of the un-parallelized model."""
    base = _run(None, 1, pp=2, num_microbatches=2)
    cp = _run(cp_mode, 2, pp=2, num_microbatches=2)
    np.testing.assert_allclose(cp, base, rtol=2e-4, atol=1e-5)


def test_bad_cp_mode_raises():
    topo = dist.init_topology(dp=1, sep=1)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=32)
    with pytest.raises(ValueError, match="cp_mode"):
        build_gpt_train_step(cfg, topo, cp_mode="ulises")


def test_gpt_zigzag_cp_matches_no_cp():
    """Zigzag (load-balanced) CP: feed ids/labels permuted by
    zigzag_permutation; positions/attention restore ORIGINAL order
    internally, so the loss must equal the un-permuted no-CP run (token
    losses are permutation-invariant)."""
    from paddle_tpu.parallel.context_parallel import zigzag_permutation
    base = _run(None, 1)

    topo = dist.init_topology(dp=1, mp=1, pp=1, sep=4, sharding=1)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64)
    step_fn, init_fn = build_gpt_train_step(cfg, topo, num_microbatches=1,
                                            cp_mode="zigzag")
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (4, 64)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    perm = zigzag_permutation(64, 4)
    losses = []
    for _ in range(3):
        state, loss = step_fn(state, ids[:, perm], labels[:, perm])
        losses.append(float(np.asarray(jax.device_get(loss))))
    np.testing.assert_allclose(losses, base, rtol=2e-4, atol=1e-5)


def test_llama_zigzag_cp_matches_no_cp():
    """Same pin for the Llama builder (rope tables gathered at the
    zigzag blocks' original positions)."""
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step
    from paddle_tpu.parallel.context_parallel import zigzag_permutation

    def run(cp_mode, sep, permute):
        topo = dist.init_topology(dp=1, mp=1, pp=1, sep=sep, sharding=1)
        cfg = llama_tiny()
        step_fn, init_fn = build_llama_train_step(
            cfg, topo, num_microbatches=1, cp_mode=cp_mode)
        state = init_fn(0)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (4, 64)).astype(np.int64)
        labels = np.roll(ids, -1, axis=1)
        if permute:
            perm = zigzag_permutation(64, sep)
            ids, labels = ids[:, perm], labels[:, perm]
        out = []
        for _ in range(3):
            state, loss = step_fn(state, ids, labels)
            out.append(float(np.asarray(jax.device_get(loss))))
        return out

    base = run(None, 1, False)
    zz = run("zigzag", 4, True)
    np.testing.assert_allclose(zz, base, rtol=2e-4, atol=1e-5)
