"""Sharding-completion pass + cost model (reference:
auto_parallel/static/completion.py + static/cost/; VERDICT r2 'no
sharding-completion pass, no cost model' partial row)."""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import static
from paddle_tpu.parallel.completion import (complete_program,
                                            estimate_plan_cost,
                                            estimate_reshard_cost)
from paddle_tpu.parallel.spmd_rules import TensorDistAttr as DA


def _record_mlp():
    pt.enable_static()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [32, 16], "float32")
        lin1 = nn.Linear(16, 64)
        lin2 = nn.Linear(64, 8)
        h = lin1(x)
        h = pt.relu(h)
        out = lin2(h)
        sm = pt.softmax(out)
    pt.disable_static()
    return main, x, out, sm, lin1, lin2


class TestCompletion:
    def test_batch_shard_propagates_through_mlp(self):
        main, x, out, sm, lin1, lin2 = _record_mlp()
        plan = complete_program(
            main, {"x": DA(["dp", None])}, mesh_shape={"dp": 8})
        # every activation stays batch-sharded on dp
        assert plan.attrs[out.name].dims_mapping == ["dp", None]
        assert plan.attrs[sm.name].dims_mapping == ["dp", None]
        # replicated weights + dp-sharded batch need NO reshards
        assert plan.reshards == [], plan.summary()
        assert plan.total_comm_bytes() == 0

    def test_column_parallel_weight_shards_activation(self):
        main, x, out, sm, lin1, lin2 = _record_mlp()
        plan = complete_program(
            main, {"x": DA(["dp", None])},
            param_attrs={lin1.weight.name: DA([None, "mp"])},
            mesh_shape={"dp": 4, "mp": 2})
        # col-parallel first linear -> activation sharded [dp, mp]
        first_lin = [n for n in plan.attrs if n.startswith("linear")][0]
        assert plan.attrs[first_lin].dims_mapping == ["dp", "mp"]

    def test_row_parallel_contracted_dim_needs_reshard(self):
        main, x, out, sm, lin1, lin2 = _record_mlp()
        plan = complete_program(
            main, {"x": DA(["dp", None])},
            param_attrs={lin2.weight.name: DA(["mp", None])},
            mesh_shape={"dp": 4, "mp": 2})
        # second matmul contracts over mp -> its input must reshard to
        # k-sharded OR the output is partial; the pass records the edge
        kinds = {r.kind for r in plan.reshards}
        assert kinds & {"r_to_s", "s_to_s", "p_to_r"}, plan.summary()

    def test_softmax_forces_replicated_class_dim(self):
        pt.enable_static()
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [8, 32], "float32")
            sm = pt.softmax(x)
        pt.disable_static()
        plan = complete_program(main, {"x": DA([None, "mp"])},
                                mesh_shape={"mp": 8})
        # class-dim shard must reshard away before softmax
        assert any(r.kind == "s_to_r" for r in plan.reshards), \
            plan.summary()
        assert plan.attrs[sm.name].dims_mapping == [None, None]

    def test_plan_summary_and_cost(self):
        main, x, out, sm, lin1, lin2 = _record_mlp()
        plan = complete_program(main, {"x": DA([None, "mp"])},
                                mesh_shape={"mp": 8})
        s = plan.summary()
        assert "vars annotated" in s
        assert estimate_plan_cost(plan) >= 0.0


class TestReshardCostModel:
    def test_allgather_cost(self):
        # ring all-gather moves (n-1)/n of the full tensor
        assert estimate_reshard_cost(800, "s_to_r", 8) == 700

    def test_allreduce_twice_allgather(self):
        assert estimate_reshard_cost(800, "p_to_r", 8) == 1400

    def test_slice_free(self):
        assert estimate_reshard_cost(800, "r_to_s", 8) == 0

    def test_alltoall_cheapest_collective(self):
        a2a = estimate_reshard_cost(800, "s_to_s", 8)
        ag = estimate_reshard_cost(800, "s_to_r", 8)
        assert 0 < a2a < ag
