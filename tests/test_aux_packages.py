"""Tests for sparse / geometric / quantization / text / audio packages
(reference test suites: test/legacy_test sparse+geometric op tests,
test/quantization, paddle.audio tests compare to librosa — we compare to
direct numpy math)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import audio, geometric, quantization, sparse
from paddle_tpu.text.viterbi_decode import viterbi_decode


class TestSparse:
    def setup_method(self, _):
        self.dense = np.array([[0, 2.0, 0, 4.0],
                               [1.0, 0, 0, 0],
                               [0, 0, 3.0, 0]], np.float32)
        idx = np.array(np.nonzero(self.dense))
        vals = self.dense[tuple(idx)]
        self.coo = sparse.sparse_coo_tensor(idx, vals, self.dense.shape)

    def test_coo_roundtrip(self):
        np.testing.assert_array_equal(self.coo.to_dense().numpy(),
                                      self.dense)
        assert self.coo.nnz == 4

    def test_csr_roundtrip(self):
        csr = self.coo.to_sparse_csr()
        np.testing.assert_array_equal(csr.to_dense().numpy(), self.dense)
        np.testing.assert_array_equal(csr.crows().numpy(), [0, 2, 3, 4])
        back = csr.to_sparse_coo()
        np.testing.assert_array_equal(back.to_dense().numpy(), self.dense)

    def test_csr_direct(self):
        csr = sparse.sparse_csr_tensor([0, 2, 3, 4], [1, 3, 0, 2],
                                       [2.0, 4.0, 1.0, 3.0], [3, 4])
        np.testing.assert_array_equal(csr.to_dense().numpy(), self.dense)

    def test_matmul_and_mv(self):
        rng = np.random.default_rng(0)
        d = rng.standard_normal((4, 5)).astype(np.float32)
        out = sparse.matmul(self.coo, pt.to_tensor(d))
        np.testing.assert_allclose(out.numpy(), self.dense @ d, rtol=1e-5)
        v = rng.standard_normal(4).astype(np.float32)
        np.testing.assert_allclose(sparse.mv(self.coo,
                                             pt.to_tensor(v)).numpy(),
                                   self.dense @ v, rtol=1e-5)

    def test_add_subtract_multiply(self):
        s = sparse.add(self.coo, self.coo)
        np.testing.assert_array_equal(s.to_dense().numpy(), 2 * self.dense)
        z = sparse.subtract(self.coo, self.coo)
        np.testing.assert_array_equal(z.to_dense().numpy(),
                                      np.zeros_like(self.dense))
        m = sparse.multiply(self.coo, self.coo)
        np.testing.assert_array_equal(m.to_dense().numpy(),
                                      self.dense * self.dense)

    def test_unary_ops(self):
        s = sparse.square(self.coo)
        np.testing.assert_allclose(s.to_dense().numpy(),
                                   self.dense ** 2, rtol=1e-6)
        t = sparse.tanh(self.coo)
        np.testing.assert_allclose(t.to_dense().numpy(),
                                   np.tanh(self.dense), rtol=1e-6)

    def test_masked_matmul(self):
        rng = np.random.default_rng(1)
        a = rng.standard_normal((3, 6)).astype(np.float32)
        b = rng.standard_normal((6, 4)).astype(np.float32)
        out = sparse.masked_matmul(pt.to_tensor(a), pt.to_tensor(b),
                                   self.coo)
        expect = (a @ b) * (self.dense != 0)
        np.testing.assert_allclose(out.to_dense().numpy(), expect,
                                   rtol=1e-4, atol=1e-5)

    def test_softmax_rows(self):
        sm = sparse.softmax(self.coo.to_sparse_csr())
        d = sm.to_dense().numpy()
        # row 0 has two nonzeros -> softmax over [2,4]
        e = np.exp([2.0 - 4.0, 0.0])
        np.testing.assert_allclose(d[0, [1, 3]], e / e.sum(), rtol=1e-5)
        np.testing.assert_allclose(d[1, 0], 1.0, rtol=1e-6)


class TestGeometric:
    def test_send_u_recv(self):
        x = pt.to_tensor(np.array([[1.0], [2.0], [4.0]], np.float32))
        src = pt.to_tensor(np.array([0, 1, 2, 0], np.int64))
        dst = pt.to_tensor(np.array([1, 2, 1, 0], np.int64))
        out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
        np.testing.assert_allclose(out.numpy(), [[1.0], [5.0], [2.0]])
        out = geometric.send_u_recv(x, src, dst, reduce_op="max")
        np.testing.assert_allclose(out.numpy(), [[1.0], [4.0], [2.0]])
        out = geometric.send_u_recv(x, src, dst, reduce_op="mean")
        np.testing.assert_allclose(out.numpy(), [[1.0], [2.5], [2.0]])

    def test_send_ue_recv_and_uv(self):
        x = pt.to_tensor(np.array([[1.0], [2.0]], np.float32))
        e = pt.to_tensor(np.array([[10.0], [20.0]], np.float32))
        src = pt.to_tensor(np.array([0, 1], np.int64))
        dst = pt.to_tensor(np.array([1, 0], np.int64))
        out = geometric.send_ue_recv(x, e, src, dst, "add", "sum")
        np.testing.assert_allclose(out.numpy(), [[22.0], [11.0]])
        uv = geometric.send_uv(x, x, src, dst, "mul")
        np.testing.assert_allclose(uv.numpy(), [[2.0], [2.0]])

    def test_segment_ops(self):
        data = pt.to_tensor(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
        ids = pt.to_tensor(np.array([0, 0, 1, 1], np.int64))
        np.testing.assert_allclose(
            geometric.segment_sum(data, ids).numpy(), [3.0, 7.0])
        np.testing.assert_allclose(
            geometric.segment_mean(data, ids).numpy(), [1.5, 3.5])
        np.testing.assert_allclose(
            geometric.segment_min(data, ids).numpy(), [1.0, 3.0])
        np.testing.assert_allclose(
            geometric.segment_max(data, ids).numpy(), [2.0, 4.0])

    def test_grad_through_send_u_recv(self):
        x = pt.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
        src = pt.to_tensor(np.array([0, 1], np.int64))
        dst = pt.to_tensor(np.array([1, 2], np.int64))
        out = geometric.send_u_recv(x, src, dst)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[1, 1], [1, 1], [0, 0]])

    def test_reindex_and_sampling(self):
        x = pt.to_tensor(np.array([5, 9], np.int64))
        neighbors = pt.to_tensor(np.array([9, 7, 5, 3], np.int64))
        count = pt.to_tensor(np.array([2, 2], np.int32))
        src, dst, nodes = geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(nodes.numpy(), [5, 9, 7, 3])
        np.testing.assert_array_equal(src.numpy(), [1, 2, 0, 3])
        np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1])
        # CSC graph: node0 <- {1,2}, node1 <- {0}
        row = pt.to_tensor(np.array([1, 2, 0], np.int64))
        colptr = pt.to_tensor(np.array([0, 2, 3], np.int64))
        nb, cnt = geometric.sample_neighbors(row, colptr,
                                             pt.to_tensor(
                                                 np.array([0, 1],
                                                          np.int64)),
                                             sample_size=1)
        assert cnt.numpy().tolist() == [1, 1]


class TestQuantization:
    def test_quant_dequant_values(self):
        x = pt.to_tensor(np.array([-1.0, -0.5, 0.0, 0.5, 1.0], np.float32))
        scale = pt.to_tensor(np.float32(1.0))
        out = quantization.quant_dequant(x, scale, 8).numpy()
        np.testing.assert_allclose(out, np.round(
            np.array([-1, -0.5, 0, 0.5, 1]) * 127) / 127, atol=1e-6)

    def test_ste_gradient(self):
        x = pt.to_tensor(np.array([-2.0, 0.3, 0.9], np.float32),
                         stop_gradient=False)
        scale = pt.to_tensor(np.float32(1.0))
        out = quantization.quant_dequant(x, scale, 8)
        out.sum().backward()
        # STE: unit grad inside [-scale, scale], zero outside
        np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0])

    def test_qat_wrap_and_convert(self):
        from paddle_tpu import nn
        from paddle_tpu.quantization import (
            FakeQuanterWithAbsMaxObserver, QAT, QuantConfig)
        pt.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        cfg = QuantConfig(
            activation=lambda: FakeQuanterWithAbsMaxObserver(),
            weight=lambda: FakeQuanterWithAbsMaxObserver())
        qat = QAT(cfg)
        qnet = qat.quantize(net)
        x = pt.to_tensor(np.random.default_rng(0).standard_normal(
            (3, 4)).astype(np.float32))
        y = qnet(x)
        assert tuple(y.shape) == (3, 2)
        # converted model runs without wrappers
        deploy = qat.convert(qnet)
        y2 = deploy(x)
        assert tuple(y2.shape) == (3, 2)


class TestViterbi:
    def test_matches_bruteforce(self):
        rng = np.random.default_rng(0)
        B, T, N = 2, 4, 3
        pot = rng.standard_normal((B, T, N)).astype(np.float32)
        trans = rng.standard_normal((N, N)).astype(np.float32)
        lengths = np.array([4, 4], np.int64)
        scores, paths = viterbi_decode(
            pt.to_tensor(pot), pt.to_tensor(trans),
            pt.to_tensor(lengths), include_bos_eos_tag=False)
        # brute force
        import itertools
        for b in range(B):
            best, best_path = -1e30, None
            for p in itertools.product(range(N), repeat=T):
                s = pot[b, 0, p[0]]
                for t in range(1, T):
                    s += trans[p[t - 1], p[t]] + pot[b, t, p[t]]
                if s > best:
                    best, best_path = s, p
            assert scores.numpy()[b] == pytest.approx(best, rel=1e-5)
            assert tuple(paths.numpy()[b]) == best_path


class TestAudio:
    def test_window_and_fbank(self):
        w = audio.functional.get_window("hann", 16).numpy()
        np.testing.assert_allclose(w, np.hanning(17)[:16], atol=1e-6)
        fb = audio.functional.compute_fbank_matrix(16000, 512,
                                                   n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()

    def test_spectrogram_shapes(self):
        rng = np.random.default_rng(0)
        x = pt.to_tensor(rng.standard_normal((2, 2048)).astype(np.float32))
        spec = audio.Spectrogram(n_fft=256, hop_length=128)(x)
        assert spec.shape[1] == 129
        mel = audio.MelSpectrogram(sr=8000, n_fft=256, hop_length=128,
                                   n_mels=32)(x)
        assert mel.shape[1] == 32
        logmel = audio.LogMelSpectrogram(sr=8000, n_fft=256,
                                         hop_length=128, n_mels=32)(x)
        assert np.isfinite(logmel.numpy()).all()
        mfcc = audio.MFCC(sr=8000, n_mfcc=13, n_fft=256, hop_length=128,
                          n_mels=32)(x)
        assert mfcc.shape[1] == 13

    def test_power_to_db(self):
        s = pt.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = audio.functional.power_to_db(s, top_db=None).numpy()
        np.testing.assert_allclose(db, [0.0, 10.0, 20.0], atol=1e-5)


class TestTextDatasets:
    def test_uci_housing_from_file(self, tmp_path):
        rng = np.random.default_rng(0)
        data = rng.standard_normal((50, 14)).astype(np.float32)
        f = tmp_path / "housing.data"
        np.savetxt(f, data)
        from paddle_tpu.text import UCIHousing
        train = UCIHousing(data_file=str(f), mode="train")
        test = UCIHousing(data_file=str(f), mode="test")
        assert len(train) == 40 and len(test) == 10
        x, y = train[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_missing_file_raises(self):
        from paddle_tpu.text import Imdb
        with pytest.raises(RuntimeError, match="data_file"):
            Imdb()


class TestSparseOpTail:
    """Sparse op tail vs reference sparse_ops.yaml (51 ops)."""

    def _coo(self, dense):
        import jax.numpy as jnp
        from jax.experimental import sparse as jsparse
        from paddle_tpu.sparse import SparseCooTensor
        return SparseCooTensor(jsparse.BCOO.fromdense(jnp.asarray(dense)))

    def test_unary_tail_and_scale(self):
        import numpy as np
        import paddle_tpu.sparse as sp
        d = np.array([[0.0, 0.5], [-0.25, 0.0]], np.float32)
        x = self._coo(d)
        np.testing.assert_allclose(
            np.asarray(sp.leaky_relu(x, 0.1).to_dense()._value),
            np.where(d >= 0, d, d * 0.1), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sp.scale(x, 3.0).to_dense()._value), d * 3,
            rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sp.relu6(self._coo(d * 20)).to_dense()._value),
            np.clip(d * 20, 0, 6), rtol=1e-6)

    def test_transpose_reshape_slice(self):
        import numpy as np
        import paddle_tpu.sparse as sp
        rng = np.random.default_rng(0)
        d = np.where(rng.uniform(size=(3, 4)) > 0.5,
                     rng.normal(size=(3, 4)), 0.0).astype(np.float32)
        x = self._coo(d)
        np.testing.assert_allclose(
            np.asarray(sp.transpose(x, [1, 0]).to_dense()._value), d.T)
        np.testing.assert_allclose(
            np.asarray(sp.reshape(x, (4, 3)).to_dense()._value),
            d.reshape(4, 3))
        np.testing.assert_allclose(
            np.asarray(sp.slice(x, [0, 1], [1, 1], [3, 3])
                       .to_dense()._value), d[1:3, 1:3])

    def test_mask_as_and_addmm(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.sparse as sp
        rng = np.random.default_rng(1)
        dense = rng.normal(size=(3, 3)).astype(np.float32)
        pattern = np.array([[1.0, 0, 0], [0, 1.0, 0], [0, 0, 1.0]],
                           np.float32)
        m = sp.mask_as(pt.Tensor(dense), self._coo(pattern))
        got = np.asarray(m.to_dense()._value)
        np.testing.assert_allclose(got, dense * (pattern != 0))
        a = rng.normal(size=(3, 2)).astype(np.float32)
        inp = rng.normal(size=(3, 2)).astype(np.float32)
        out = sp.addmm(pt.Tensor(inp), self._coo(dense), pt.Tensor(a),
                       beta=0.5, alpha=2.0)
        np.testing.assert_allclose(np.asarray(out._value),
                                   0.5 * inp + 2.0 * dense @ a, rtol=1e-5)

    def test_sparse_conv3d_matches_dense(self):
        import jax, numpy as np
        import paddle_tpu.sparse as sp
        rng = np.random.default_rng(2)
        d = np.where(rng.uniform(size=(1, 4, 4, 4, 2)) > 0.7,
                     rng.normal(size=(1, 4, 4, 4, 2)), 0.0).astype(
            np.float32)
        w = rng.normal(size=(2, 2, 2, 2, 3)).astype(np.float32)
        out = sp.conv3d(self._coo(d), w)
        ref = jax.lax.conv_general_dilated(
            d, w, (1, 1, 1), [(0, 0)] * 3,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        np.testing.assert_allclose(np.asarray(out.to_dense()._value),
                                   np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_sparse_batch_norm_and_maxpool(self):
        import numpy as np
        import paddle_tpu.sparse as sp
        rng = np.random.default_rng(3)
        d = np.where(rng.uniform(size=(1, 4, 4, 4, 3)) > 0.5,
                     rng.normal(size=(1, 4, 4, 4, 3)), 0.0).astype(
            np.float32)
        x = self._coo(d)
        y, rm, rv = sp.batch_norm_(x, np.zeros(3, np.float32),
                                   np.ones(3, np.float32))
        vals = np.asarray(y._bcoo.data)
        np.testing.assert_allclose(vals.mean(0), 0.0, atol=1e-5)
        mp = sp.max_pool3d(x, 2, 2)
        assert mp.to_dense()._value.shape == (1, 2, 2, 2, 3)

    def test_fused_attention_sparse_mask(self):
        import numpy as np
        import paddle_tpu as pt
        import paddle_tpu.sparse as sp
        rng = np.random.default_rng(4)
        B, H, T, D = 1, 1, 4, 8
        q = rng.normal(size=(B, H, T, D)).astype(np.float32)
        mask = np.tril(np.ones((T, T), np.float32))
        out = sp.fused_attention(pt.Tensor(q), pt.Tensor(q), pt.Tensor(q),
                                 self._coo(mask))
        # equals dense causal attention
        logits = (q[0, 0] @ q[0, 0].T) / np.sqrt(D)
        logits = np.where(mask != 0, logits, np.finfo(np.float32).min)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(out._value)[0, 0], p @ q[0, 0],
                                   rtol=2e-5, atol=2e-5)

    def test_values_indices_full_like(self):
        import numpy as np
        import paddle_tpu.sparse as sp
        d = np.array([[1.0, 0.0], [0.0, 2.0]], np.float32)
        x = self._coo(d)
        assert np.asarray(sp.values(x)._value).shape == (2,)
        assert np.asarray(sp.indices(x)._value).shape == (2, 2)
        fl = sp.full_like(x, 5.0)
        np.testing.assert_allclose(np.asarray(fl._bcoo.data), 5.0)


class TestTensorArrayAndMonitor:
    def test_tensor_array_api(self):
        import numpy as np
        import paddle_tpu as pt
        arr = pt.create_array()
        pt.array_write(np.ones(3, np.float32), 0, arr)
        pt.array_write(np.full(3, 2.0, np.float32), 1, arr)
        assert int(np.asarray(pt.array_length(arr)._value)) == 2
        np.testing.assert_allclose(
            np.asarray(pt.array_read(arr, 1)._value), 2.0)
        st = arr.stack()
        assert np.asarray(st._value).shape == (2, 3)
        arr.write(0, np.zeros(3, np.float32))   # overwrite
        np.testing.assert_allclose(np.asarray(arr.read(0)._value), 0.0)

    def test_collective_monitor_records(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu import parallel as dist
        from paddle_tpu.parallel.collective import (CollectiveMonitor,
                                                    all_reduce)
        from paddle_tpu.parallel.topology import (HybridTopology,
                                                  set_topology)
        dist.init_topology(dp=2)
        try:
            with CollectiveMonitor(warn_after=1e9) as mon:
                out = all_reduce(pt.Tensor(np.ones(4, np.float32)))
            assert len(mon.events) == 1
            name, axis, sec = mon.events[0]
            assert sec >= 0
            assert name.startswith("all_reduce")
            assert any(k.startswith("all_reduce") for k in mon.summary())
        finally:
            set_topology(HybridTopology())


class TestStringTensor:
    def test_surface(self):
        import numpy as np
        import paddle_tpu as pt
        st = pt.to_string_tensor(["Hello", "World"])
        assert st.shape == [2] and st.dtype == "pstring"
        assert st[0] == "Hello"
        assert st.lower().tolist() == ["hello", "world"]
        assert list(st) == ["Hello", "World"]
        eq = st == pt.to_string_tensor(["Hello", "x"])
        np.testing.assert_array_equal(eq, [True, False])


class TestTopLevelParity:
    def test_reference_top_level_names_all_present(self):
        import os
        import re
        import pytest
        import paddle_tpu as pt
        path = "/root/reference/python/paddle/__init__.py"
        if not os.path.exists(path):
            pytest.skip("reference tree not mounted")
        ref_init = open(path).read()
        m = re.search(r"__all__ = \[(.*?)\]", ref_init, re.S)
        names = re.findall(r"'([a-zA-Z_][a-zA-Z0-9_]*)'", m.group(1))
        missing = [n for n in names if not hasattr(pt, n)]
        assert not missing, missing

    def test_inplace_free_functions(self):
        import numpy as np
        import paddle_tpu as pt
        x = pt.Tensor(np.array([[4.0, 9.0]], np.float32))
        out = pt.sqrt_(x)
        assert out is x
        np.testing.assert_allclose(np.asarray(x._value), [[2.0, 3.0]])
        pt.transpose_(x, [1, 0])
        assert np.asarray(x._value).shape == (2, 1)
        pt.uniform_(x, 0.0, 1.0)
        v = np.asarray(x._value)
        assert ((v >= 0) & (v <= 1)).all()

    def test_new_tensor_ops(self):
        import numpy as np
        import paddle_tpu as pt
        a = np.ones((2, 2), np.float32)
        b = np.full((3, 3), 2.0, np.float32)
        bd = np.asarray(pt.block_diag([pt.Tensor(a), pt.Tensor(b)])._value)
        assert bd.shape == (5, 5) and bd[0, 0] == 1 and bd[4, 4] == 2
        cp = np.asarray(pt.cartesian_prod(
            [pt.Tensor(np.arange(2)), pt.Tensor(np.arange(3))])._value)
        assert cp.shape == (6, 2)
        ts = pt.tensor_split(pt.Tensor(np.arange(7)), 3)
        assert [len(np.asarray(t._value)) for t in ts] == [3, 2, 2]
        x = pt.Tensor(np.zeros((4, 4), np.float32))
        ds = np.asarray(pt.diagonal_scatter(
            x, pt.Tensor(np.ones(4, np.float32)))._value)
        np.testing.assert_allclose(np.diag(ds), 1.0)
        ss = np.asarray(pt.select_scatter(
            x, pt.Tensor(np.full(4, 7.0, np.float32)), 0, 1)._value)
        np.testing.assert_allclose(ss[1], 7.0)
        uf = np.asarray(pt.unflatten(pt.Tensor(np.zeros((2, 6))), 1,
                                     (2, -1))._value)
        assert uf.shape == (2, 2, 3)
        pd = np.asarray(pt.pdist(pt.Tensor(np.eye(3, dtype=np.float32)))
                        ._value)
        np.testing.assert_allclose(pd, np.sqrt(2.0), rtol=1e-6)

    def test_misc_utilities(self):
        import numpy as np
        import paddle_tpu as pt
        assert pt.is_tensor(pt.Tensor(np.ones(1)))
        assert pt.is_floating_point(pt.Tensor(np.ones(1, np.float32)))
        assert not pt.is_integer(pt.Tensor(np.ones(1, np.float32)))
        with pt.LazyGuard():
            pass
        p = pt.create_parameter((3, 4))
        assert np.asarray(p._value).shape == (3, 4)
        reader = pt.batch(lambda: iter(range(5)), 2)
        assert [len(b) for b in reader()] == [2, 2, 1]
        st = pt.get_cuda_rng_state()
        pt.set_cuda_rng_state(st)
        pt.check_shape(pt.Tensor(np.ones((2, 3))), (2, -1))


def test_memory_stats_api():
    """Device memory counters (reference phi/core/memory/stats.cc)."""
    import paddle_tpu as p
    st = p.memory_stats()
    assert set(st) >= {"memory.allocated.current", "memory.allocated.peak",
                       "memory.limit"}
    assert p.memory_allocated() >= 0
    assert p.max_memory_allocated() >= p.memory_allocated() or \
        p.max_memory_allocated() == 0
