"""End-to-end request tracing (ISSUE 20): span timelines across
wire → router → engine with p99 latency-budget attribution.

Load-bearing contracts:

* every terminal request state (FINISHED / REJECTED / CANCELLED /
  TIMED_OUT) yields a rooted span tree — unique monotonically-ordered
  span ids, valid parents, every span inside ``[0, duration]``;
* a request that survives a mid-stream replica kill keeps ONE
  trace_id: the ``re_place`` span and the post-replay engine spans
  land on the original trace, and the finished trace is exemplar-
  captured as ``replayed``;
* the ISSUE 20 acceptance scenario — an SLO-violating request under
  injected chaos (KV-pool exhaustion + a replica kill from
  tests/faults.py) — produces a flight dump whose span tree attributes
  the TTFT overrun to the queueing/replay phases, not to compute;
* disabled-mode tracing allocates nothing on the hot path (the
  MetricsRegistry bar from test_observability.py);
* the tracing module and every instrumented serve file carry ZERO
  tracelint/locklint findings, and both ledgers stay EMPTY.
"""

import gc
import json
import os
import sys
import time

import jax
import numpy as np
import pytest

from paddle_tpu import parallel as dist
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models.llama import build_llama_train_step, llama_tiny
from paddle_tpu.observability import (FlightRecorder, MemorySink,
                                      MetricsRegistry, REGISTRY)
from paddle_tpu.observability.tracing import (TRACER, SpanTracer, Trace,
                                              attribution, export_chrome,
                                              write_spans_jsonl)
from paddle_tpu.parallel.topology import HybridTopology, set_topology
from paddle_tpu.serving import (AdmissionConfig, EngineRouter,
                                HttpServingServer, LoadGenConfig,
                                PoissonLoadGenerator, RequestState,
                                RetryPolicy, ServingFrontend)

import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import trace_report  # noqa: E402

rng = np.random.default_rng(20)

TERMINAL = {"FINISHED", "REJECTED", "CANCELLED", "TIMED_OUT"}


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny()
    topo = dist.init_topology(devices=jax.devices()[:1])
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    return cfg, params


@pytest.fixture(autouse=True)
def _tracer_isolation():
    """The process-wide TRACER must come out of every test the way it
    went in: disabled, empty, default SLOs (mirrors the REGISTRY
    isolation in test_observability.py)."""
    yield
    TRACER.disable()
    TRACER.reset()
    TRACER.configure(slo_ttft_s=None, slo_tpot_s=None)
    REGISTRY.disable()
    for s in REGISTRY.sinks:
        REGISTRY.remove_sink(s)


def _engine(model, **kw):
    cfg, params = model
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("prefill_buckets", (8,))
    return ContinuousBatchingEngine(cfg, params, **kw)


def _router(model, n=2, **kw):
    cfg, params = model
    geom = dict(max_batch=2, block_size=8, num_blocks=64,
                prefill_buckets=(8,))
    geom.update(kw)

    def factory():
        return ContinuousBatchingEngine(cfg, params, **geom)

    return EngineRouter([factory] * n,
                        policy=RetryPolicy(backoff_base_s=0.0),
                        sleep=lambda s: None)


def _prompt(model, n):
    return rng.integers(0, model[0].vocab_size, (n,)).astype(np.int32)


def _drain(fe, timeout_s=120.0):
    t0 = time.monotonic()
    while fe.step():
        if time.monotonic() - t0 > timeout_s:
            raise AssertionError("frontend never drained")


def _assert_well_formed(tr: Trace):
    """The structural pin: rooted, id-monotonic, time-bounded tree."""
    assert tr.finished, tr.trace_id
    assert tr.state in TERMINAL, tr.state
    dur = tr.duration_s
    assert dur is not None and dur >= 0.0
    spans = tr.snapshot()
    ids = [s.span_id for s in spans]
    assert ids == sorted(ids) and len(ids) == len(set(ids)), ids
    known = {0} | set(ids)
    eps = 1e-6
    for s in spans:
        assert s.parent in known and s.parent < s.span_id, \
            (tr.trace_id, s.name, s.parent, s.span_id)
        assert -eps <= s.t0 <= s.t1 <= dur + eps, \
            (tr.trace_id, s.name, s.t0, s.t1, dur)
    assert tr.dropped == 0
    d = tr.to_dict()
    assert d["trace_id"] == tr.trace_id and len(d["spans"]) == len(spans)


# ---------------------------------------------------------------------
# span-tree structure across every terminal state
# ---------------------------------------------------------------------
def test_every_terminal_state_yields_wellformed_tree(model):
    """Two overload runs — one behind a tiny admission cap (sheds via
    REJECTED), one behind a queue-time budget (sheds via TIMED_OUT),
    both with mid-stream cancels — leave one well-formed span tree per
    request across ALL FOUR terminal states, each reachable through
    the finished ring."""
    TRACER.enable()
    TRACER.reset()
    fe = ServingFrontend(_engine(model, num_blocks=48),
                         admission=AdmissionConfig(max_queue_len=4))
    rep = PoissonLoadGenerator(fe, LoadGenConfig(
        n_requests=24, rate_rps=500.0, seed=7, prompt_len=(3, 8),
        max_new_tokens=(4, 10), sampled_fraction=0.25,
        cancel_fraction=0.2, cancel_after_tokens=2,
        slo_ttft_s=60.0, slo_tpot_s=30.0)).run()
    assert rep.rejected > 0 and rep.finished > 0 and rep.cancelled > 0
    fe2 = ServingFrontend(_engine(model, num_blocks=48))
    rep2 = PoissonLoadGenerator(fe2, LoadGenConfig(
        n_requests=30, rate_rps=500.0, seed=11, prompt_len=(4, 10),
        max_new_tokens=(8, 16), cancel_fraction=0.1,
        max_queue_time_s=0.1, slo_ttft_s=60.0, slo_tpot_s=30.0)).run()
    assert rep2.timed_out >= 1 and rep2.finished >= 1
    done = TRACER.done_traces()
    assert len(done) == rep.n_requests + rep2.n_requests
    states = set()
    for tr in done:
        _assert_well_formed(tr)
        states.add(tr.state)
        names = [s.name for s in tr.snapshot()]
        meta = tr.meta
        assert meta["prompt_tokens"] >= 1
        if tr.state == "REJECTED":
            assert "reason" in meta and "prefill" not in names
        if tr.state == "FINISHED":
            assert meta["ttft_s"] > 0.0
            assert "queue_wait" in names and "prefill" in names
            assert "first_token" in names
        if tr.state == "TIMED_OUT":
            assert "reason" in meta
    assert states == TERMINAL, states
    assert len({tr.trace_id for tr in done}) == len(done)
    # the finished ring resolves every trace after the fact — by
    # trace_id always; by rid too, though the two frontends both
    # number from rid 0, so rid lookup resolves SOME trace with that
    # rid (newest wins, per the lookup contract).  Only REJECTED
    # requests never reached an engine and so carry no rid.
    for tr in done:
        assert TRACER.lookup(trace_id=tr.trace_id) is tr
        if tr.rid is not None:
            assert TRACER.lookup(rid=tr.rid).rid == tr.rid
        else:
            assert tr.state == "REJECTED"
    # attribution rides the report when the tracer is on; it covers
    # the requests that produced a first token (TTFT exists)
    for r in (rep, rep2):
        assert r.attribution is not None
        assert r.attribution["n_traced"] >= r.finished >= 1
        assert "queue_wait" in r.attribution["ttft"]


def test_preempt_restore_spans_on_one_trace(model):
    """An explicit preempt/restore cycle leaves spill + queue_wait +
    restore spans (in that order) on the preempted request's trace."""
    TRACER.enable()
    TRACER.reset()
    eng = _engine(model, max_batch=1)
    fe = ServingFrontend(eng)
    h = fe.submit(_prompt(model, 8), 8)
    fe.step()
    assert eng.active_requests == 1
    eng.preempt(next(s for s in range(eng.B)
                     if eng.slots[s] is not None))
    _drain(fe)
    assert h.state is RequestState.FINISHED
    tr = h.trace
    _assert_well_formed(tr)
    names = [s.name for s in tr.snapshot()]
    i_spill = names.index("preempt_spill")
    i_rest = names.index("preempt_restore")
    assert i_spill < i_rest
    assert "queue_wait" in names[i_spill:i_rest]
    spill = tr.snapshot()[i_spill]
    assert spill.attrs["committed"] >= 1


def test_sampled_and_spec_requests_trace_too(model):
    """Sampled decode traces like greedy; a speculating engine emits
    spec_decode_step spans with committed-token counts."""
    from paddle_tpu.spec_decode import SpecDecodeConfig
    cfg, params = model
    TRACER.enable()
    TRACER.reset()
    eng = ContinuousBatchingEngine(
        cfg, params, max_batch=2, block_size=8, num_blocks=64,
        prefill_buckets=(8,),
        spec_config=SpecDecodeConfig(draft_cfg=cfg, draft_params=params,
                                     k=3, window=12))
    fe = ServingFrontend(eng)
    h1 = fe.submit(_prompt(model, 8), 6)
    h2 = fe.submit(_prompt(model, 6), 6, temperature=0.8, top_k=8,
                   seed=5)
    _drain(fe)
    assert h1.state is RequestState.FINISHED
    assert h2.state is RequestState.FINISHED
    for h in (h1, h2):
        _assert_well_formed(h.trace)
        names = [s.name for s in h.trace.snapshot()]
        assert "spec_decode_step" in names, names
    committed = sum(s.attrs["committed"]
                    for s in h1.trace.snapshot()
                    if s.name == "spec_decode_step")
    # prefill itself emits the first token; spec steps commit the rest
    assert committed == h1.n_streamed - 1


# ---------------------------------------------------------------------
# replay links: one trace_id across replica death
# ---------------------------------------------------------------------
def test_replica_kill_keeps_one_trace_with_replay_spans(model):
    """The ISSUE 20 replay-link pin: a request whose replica dies
    mid-stream keeps its original trace_id; the re-placement and the
    post-replay engine spans land on the SAME tree, the finished trace
    is marked replayed, and the exemplar capture fires."""
    TRACER.enable()
    TRACER.reset()
    reg = MetricsRegistry(enabled=True)
    sink = MemorySink()
    reg.add_sink(sink)
    router = _router(model, n=2)
    fe = ServingFrontend(router, registry=reg)
    h = fe.submit(_prompt(model, 9), 10)
    tid0 = h.trace.trace_id
    it = iter(h)
    got = [next(it), next(it)]
    router.kill_replica(router._placements[h.req_id].replica, "chaos")
    got.extend(it)
    assert h.state is RequestState.FINISHED
    assert len(got) == 10
    tr = h.trace
    assert tr.trace_id == tid0
    _assert_well_formed(tr)
    assert tr.meta["replayed"] is True
    assert tr.meta["exemplar"] == "replayed"
    names = [s.name for s in tr.snapshot()]
    i_move = names.index("re_place")
    mv = tr.snapshot()[i_move]
    assert mv.attrs["from_replica"] != mv.attrs["to_replica"]
    assert mv.attrs["committed"] >= 2
    # engine spans continue on the same tree after the move
    assert "decode_step" in names[i_move:], names
    # both placements' decisions are on the tree
    assert names.count("placement") >= 2
    # exemplar capture: the full span tree rode the registry event
    ex = [r for r in sink.records
          if r.get("kind") == "trace"
          and r.get("action") == "slo_exemplar"]
    assert any(r["trace"]["trace_id"] == tid0
               and r["reason"] == "replayed" for r in ex), ex


def test_crash_replay_links_supervised_engine(model):
    """Single-replica analogue: a supervised engine crash mid-stream
    replays onto a rebuilt engine; the crash_replay span lands on the
    original trace."""
    from paddle_tpu.serving.resilience import (RetryPolicy as RP,
                                               SupervisedEngine)
    TRACER.enable()
    TRACER.reset()
    sup = SupervisedEngine(lambda: _engine(model),
                           policy=RP(backoff_base_s=0.0),
                           sleep=lambda s: None)
    fe = ServingFrontend(sup)
    h = fe.submit(_prompt(model, 8), 8)
    it = iter(h)
    got = [next(it), next(it)]
    with faults.fail_step_n(sup.engine, n=1):
        got.extend(it)
    assert h.state is RequestState.FINISHED
    tr = h.trace
    _assert_well_formed(tr)
    assert tr.meta["replayed"] is True
    names = [s.name for s in tr.snapshot()]
    i_rp = names.index("crash_replay")
    assert tr.snapshot()[i_rp].attrs["committed"] >= 2
    assert "decode_step" in names[i_rp:], names


# ---------------------------------------------------------------------
# the ISSUE 20 acceptance scenario
# ---------------------------------------------------------------------
def test_chaos_slo_miss_flight_dump_attributes_ttft(model, tmp_path):
    """An SLO-violating request under injected chaos — KV-pool
    exhaustion stalling admission plus a replica kill mid-run
    (tests/faults.py) — is exemplar-captured into the FlightRecorder
    ring, and the dumped span tree attributes the TTFT overrun to the
    queueing/replay phases (queue_wait dominates; compute does not)."""
    reg = MetricsRegistry(enabled=True)
    fr = FlightRecorder(capacity=512)
    reg.add_sink(fr)
    router = _router(model, n=2, max_batch=1)
    fe = ServingFrontend(router, registry=reg)
    # compile-warm both replicas so XLA compile time cannot pollute
    # the attribution below
    warm = [fe.submit(_prompt(model, 8), 2) for _ in range(4)]
    _drain(fe)
    assert all(w.state is RequestState.FINISHED for w in warm)

    TRACER.enable()
    TRACER.reset()
    TRACER.configure(slo_ttft_s=1e-4, slo_tpot_s=30.0)
    busy = [fe.submit(_prompt(model, 8), 16) for _ in range(2)]
    for _ in range(2):
        fe.step()
    # chaos 1: exhaust one replica's KV pool so admission stalls and
    # head-of-line requests queue
    victim = router._placements[busy[0].req_id].replica
    eng = router._replicas[victim].sup.engine
    with faults.exhaust_kv_pool(eng, leave=1):
        h = fe.submit(_prompt(model, 8), 4)
        for _ in range(3):
            fe.step()
        # chaos 2: kill the starved replica mid-run — its live request
        # re-places and replays on the survivor
        router.kill_replica(victim, "chaos")
        _drain(fe)
    assert h.state is RequestState.FINISHED
    tr = h.trace
    _assert_well_formed(tr)
    assert tr.meta["ttft_s"] > 1e-4          # the SLO was violated
    assert tr.meta["exemplar"] in ("slo_ttft", "replayed")

    # the flight dump carries the full span tree
    path = fr.dump("slo miss under chaos",
                   str(tmp_path / "flight.json"))
    dump = json.load(open(path))
    exemplars = [r for r in dump["records"]
                 if r.get("kind") == "trace"
                 and r.get("action") == "slo_exemplar"]
    mine = [r for r in exemplars
            if r["trace"]["trace_id"] == tr.trace_id]
    assert mine, [r["trace"]["trace_id"] for r in exemplars]
    td = mine[0]["trace"]

    # attribution from the DUMP (the offline tool's view): the TTFT
    # overrun belongs to queueing/replay, not prefill/decode compute
    att = trace_report.attribution([td])
    ttft = att["ttft"]
    assert "queue_wait" in ttft, ttft
    chaos_s = sum(d["sum"] for k, d in ttft.items()
                  if k in ("queue_wait", "re_place", "prefix_replay",
                           "crash_replay", "preempt_restore"))
    compute_s = sum(d["sum"] for k, d in ttft.items()
                    if k in ("prefill", "decode_step",
                             "spec_decode_step"))
    assert chaos_s > compute_s, att
    assert chaos_s > 0.5 * td["meta"]["ttft_s"], att
    # the killed replica's request was exemplar-captured as replayed
    # with the re_place span on ITS original trace
    replayed = [r for r in exemplars if r["reason"] == "replayed"]
    assert any("re_place" in [s["name"] for s in r["trace"]["spans"]]
               for r in replayed), replayed
    _drain(fe)


# ---------------------------------------------------------------------
# wire layer: /v1/trace, headers, /metrics freshness
# ---------------------------------------------------------------------
def _get(port, path):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30.0)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def test_http_trace_endpoint_and_headers(model):
    """GET /v1/trace/<key> resolves the server rid, the client
    request_id, AND the trace_id; the SSE response carries X-Trace-Id
    and the done event carries trace_id."""
    import http.client
    from paddle_tpu.serving.http import iter_sse
    TRACER.enable()
    TRACER.reset()
    fe = ServingFrontend(_engine(model))
    srv = HttpServingServer(fe, heartbeat_s=0.1)
    with srv:
        payload = {"prompt_ids": _prompt(model, 6).tolist(),
                   "max_new_tokens": 4, "request_id": "client-abc"}
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=60.0)
        conn.request("POST", "/v1/generate", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        tid = resp.getheader("X-Trace-Id")
        rid = resp.getheader("X-Request-Id")
        assert tid
        done = None
        for event, data in iter_sse(resp):
            if event != "token":
                done = (event, data)
                break
        conn.close()
        assert done is not None and done[0] == "done"
        assert done[1]["trace_id"] == tid
        # all three key spaces resolve to the same trace
        for key in (rid, "client-abc", tid):
            status, body, _ = _get(srv.port, f"/v1/trace/{key}")
            assert status == 200, (key, body)
            d = json.loads(body)
            assert d["trace_id"] == tid
            assert d["state"] == "FINISHED"
            assert any(s["name"] == "prefill" for s in d["spans"])
        status, body, _ = _get(srv.port, "/v1/trace/nope")
        assert status == 404


def test_http_trace_endpoint_404_when_disabled(model):
    fe = ServingFrontend(_engine(model))
    srv = HttpServingServer(fe)
    with srv:
        status, body, _ = _get(srv.port, "/v1/trace/0")
        assert status == 404
        assert b"disabled" in body


def test_metrics_scrape_publishes_fresh_gauges(model):
    """The /metrics staleness fix: an idle server (driver parked, zero
    scheduler iterations) still serves CURRENT engine gauges because
    the handler publishes on scrape."""
    reg = MetricsRegistry(enabled=True)
    fe = ServingFrontend(_engine(model, num_blocks=64), registry=reg)
    srv = HttpServingServer(fe)
    with srv:
        status, body, _ = _get(srv.port, "/metrics")
        assert status == 200
        text = body.decode()
        # these gauges are ONLY set by _publish(); with no traffic the
        # driver never steps, so their presence proves the scrape path
        assert "paddle_tpu_serve_kv_free_blocks 64" in text, text[:800]
        assert "paddle_tpu_serve_queue_depth 0" in text


# ---------------------------------------------------------------------
# export + offline report
# ---------------------------------------------------------------------
def _traced_run(model, n=6):
    TRACER.enable()
    TRACER.reset()
    fe = ServingFrontend(_engine(model, num_blocks=48))
    PoissonLoadGenerator(fe, LoadGenConfig(
        n_requests=n, rate_rps=200.0, seed=3, prompt_len=(3, 8),
        max_new_tokens=(3, 6), sampled_fraction=0.25,
        slo_ttft_s=60.0, slo_tpot_s=30.0)).run()
    return TRACER.done_traces()


def test_chrome_export_and_jsonl_roundtrip(model, tmp_path):
    done = _traced_run(model)
    jp = str(tmp_path / "traces.jsonl")
    cp = str(tmp_path / "traces_chrome.json")
    write_spans_jsonl(done, jp)
    export_chrome(done, cp)
    lines = [json.loads(ln) for ln in open(jp)]
    assert len(lines) == len(done)
    assert all("spans" in d and "trace_id" in d for d in lines)
    chrome = json.load(open(cp))
    evs = chrome["traceEvents"]
    assert chrome["displayTimeUnit"] == "ms"
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) >= len(done)              # one root X per trace
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    names = {e["name"] for e in xs}
    assert "prefill" in names and "queue_wait" in names
    # perfetto needs the thread metadata rows to label lanes
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               for e in evs)


def test_trace_report_tool(model, tmp_path, capsys):
    """tools/trace_report.py renders the attribution table and a
    per-trace waterfall from the JSONL dump (the tier-1 smoke)."""
    done = _traced_run(model)
    jp = str(tmp_path / "traces.jsonl")
    write_spans_jsonl(done, jp)
    assert trace_report.main([jp]) == 0
    out = capsys.readouterr().out
    assert "TTFT attribution" in out
    assert "queue_wait" in out and "prefill" in out
    assert trace_report.main([jp, "--trace", done[0].trace_id]) == 0
    out = capsys.readouterr().out
    assert done[0].trace_id in out
    assert "prefill" in out
    # offline attribution agrees with the live one on phase totals
    live = attribution(done)
    offline = trace_report.attribution([t.to_dict() for t in done])
    assert set(offline["ttft"]) == set(live["ttft"])
    for k in live["ttft"]:
        assert offline["ttft"][k]["sum"] == pytest.approx(
            live["ttft"][k]["sum"], abs=2e-4)
    # empty / unknown inputs fail loudly, not silently
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert trace_report.main([empty]) == 1
    assert trace_report.main([jp, "--trace", "no-such-trace"]) == 1


def test_training_twin_records_steps(tmp_path):
    """Model.fit's telemetry hook lands train_step spans on the
    process-wide training trace (the serve-path trace's training
    twin); ElasticTrainer reshape lands a reshape span (exercised by
    the chaos runs in test_parallel_elastic)."""
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.io.dataset import TensorDataset
    TRACER.enable()
    TRACER.reset()
    pt.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(16, 8), nn.ReLU(),
                        nn.Linear(8, 4))
    m = pt.Model(net)
    m.prepare(
        optimizer=pt.optimizer.Adam(1e-2, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss())
    data = np.random.default_rng(0)
    x = data.normal(size=(32, 16)).astype(np.float32)
    y = data.integers(0, 4, size=(32,)).astype(np.int64)
    m.fit(TensorDataset([x, y]), batch_size=16, epochs=2, verbose=0,
          shuffle=False, observe=str(tmp_path / "tele"))
    tt = TRACER.train_trace()
    steps = [s for s in tt.snapshot() if s.name == "train_step"]
    assert len(steps) == 4                    # 2 epochs x 2 batches
    for s in steps:
        assert s.t1 >= s.t0 >= 0.0
        assert "loss" in s.attrs and s.attrs["skipped"] is False
        assert s.attrs["step"] >= 1


# ---------------------------------------------------------------------
# overhead: disabled mode is free
# ---------------------------------------------------------------------
class TestDisabledOverhead:
    def test_disabled_begin_is_none_and_records_nothing(self):
        t = SpanTracer(enabled=False)
        assert t.begin(rid=1) is None
        assert t.current() is None
        with t.activating(None):
            assert t.current() is None
        t.finish(None, "FINISHED")
        assert t.done_traces() == []
        assert t.lookup(rid=1) is None

    def test_disabled_serve_path_allocates_nothing(self):
        """The ISSUE 20 bar, mirroring the MetricsRegistry test: with
        tracing off, the per-request begin/activate/finish path and the
        per-step current() probe allocate nothing."""
        t = SpanTracer(enabled=False)

        def one_request():
            tr = t.begin(rid=1)
            with t.activating(tr):
                t.current()
                t.current()
            t.finish(tr, "FINISHED")

        for _ in range(2000):                 # warm freelists/caches
            one_request()
        gc.collect()
        before = sys.getallocatedblocks()
        for _ in range(2000):
            one_request()
        gc.collect()
        delta = sys.getallocatedblocks() - before
        assert delta <= 8, f"disabled tracing leaked {delta} blocks"

    def test_disabled_fleet_serve_runs_without_traces(self, model):
        assert not TRACER.enabled
        fe = ServingFrontend(_engine(model))
        h = fe.submit(_prompt(model, 6), 3)
        _drain(fe)
        assert h.state is RequestState.FINISHED
        assert h.trace is None
        assert TRACER.done_traces() == []


# ---------------------------------------------------------------------
# span cap + thread safety of the Trace itself
# ---------------------------------------------------------------------
def test_span_ring_bounded_and_drop_counted():
    tr = Trace("t-1", max_spans=8)
    for i in range(20):
        tr.add("s", 0.0, 1.0)
    assert len(tr.snapshot()) == 8
    assert tr.dropped == 12
    assert tr.to_dict()["dropped_spans"] == 12


def test_trace_thread_safety():
    import threading
    tr = Trace("t-2", max_spans=100_000)
    n_threads, per_thread = 8, 2000

    def work():
        for _ in range(per_thread):
            tr.add("s", 0.0, 1.0)

    ts = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    spans = tr.snapshot()
    assert len(spans) == n_threads * per_thread
    ids = [s.span_id for s in spans]
    assert len(set(ids)) == len(ids)          # no duplicate ids


# ---------------------------------------------------------------------
# static analysis: the tracing surface carries zero findings
# ---------------------------------------------------------------------
INSTRUMENTED = (
    "paddle_tpu/observability/tracing.py",
    "paddle_tpu/inference/serving.py",
    "paddle_tpu/serving/frontend.py",
    "paddle_tpu/serving/resilience.py",
    "paddle_tpu/serving/fleet.py",
    "paddle_tpu/serving/http.py",
    "paddle_tpu/serving/loadgen.py",
)


def test_tracing_has_zero_findings():
    """The ISSUE 20 lint pin: the tracing module and every instrumented
    serve file carry ZERO tracelint (TL) and locklint (LK) findings,
    and both committed ledgers stay EMPTY — tracing never added a
    silent broad except, a host-sync in traced code, or
    blocking-under-lock."""
    from paddle_tpu.analysis import baseline as baseline_mod
    from paddle_tpu.analysis import core
    from paddle_tpu.analysis.cli import default_paths
    select = {r.id for r in core.all_rules()
              if r.id.startswith(("TL", "LK"))}
    live = [f for f in core.run(default_paths(), select=select)
            if f.path in INSTRUMENTED]
    assert live == [], [f.format() for f in live]
    assert baseline_mod.load() == {}                       # tracelint
    assert baseline_mod.load(baseline_mod.locklint_path()) == {}
