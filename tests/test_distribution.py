"""paddle.distribution parity tests — numeric checks vs scipy.stats
(the reference's test strategy: test/distribution/test_distribution_*.py
compare against scipy) plus Monte-Carlo KL validation."""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as pt
from paddle_tpu import distribution as D

RNG = np.random.default_rng(0)


def _mc_kl(p, q, n=200_000):
    """Monte-Carlo KL(p||q) from p samples."""
    x = p.sample((n,))
    lp = p.log_prob(x).numpy()
    lq = q.log_prob(x).numpy()
    return float(np.mean(lp - lq))


class TestScalarDists:
    @pytest.mark.parametrize("dist,sp,params", [
        (D.Normal, st.norm, {"loc": 1.5, "scale": 2.0}),
        (D.Laplace, st.laplace, {"loc": -0.5, "scale": 1.5}),
        (D.Gumbel, st.gumbel_r, {"loc": 0.3, "scale": 0.8}),
        (D.Cauchy, st.cauchy, {"loc": 0.1, "scale": 1.2}),
    ])
    def test_logprob_entropy_cdf(self, dist, sp, params):
        d = dist(**params)
        frozen = sp(loc=params["loc"], scale=params["scale"])
        xs = np.linspace(-3, 4, 11).astype(np.float32)
        np.testing.assert_allclose(d.log_prob(pt.to_tensor(xs)).numpy(),
                                   frozen.logpdf(xs), rtol=1e-4, atol=1e-5)
        if dist is not D.Cauchy:
            np.testing.assert_allclose(float(d.entropy().numpy()),
                                       frozen.entropy(), rtol=1e-5)
        np.testing.assert_allclose(d.cdf(pt.to_tensor(xs)).numpy(),
                                   frozen.cdf(xs), rtol=1e-4, atol=1e-5)

    def test_normal_icdf_sampling(self):
        pt.seed(0)
        d = D.Normal(2.0, 3.0)
        u = np.array([0.1, 0.5, 0.9], np.float32)
        np.testing.assert_allclose(d.icdf(pt.to_tensor(u)).numpy(),
                                   st.norm(2, 3).ppf(u), rtol=1e-4)
        s = d.sample((50_000,)).numpy()
        assert abs(s.mean() - 2.0) < 0.05 and abs(s.std() - 3.0) < 0.05

    def test_uniform(self):
        d = D.Uniform(-1.0, 3.0)
        xs = np.array([-0.5, 0.0, 2.5], np.float32)
        np.testing.assert_allclose(d.log_prob(pt.to_tensor(xs)).numpy(),
                                   st.uniform(-1, 4).logpdf(xs), rtol=1e-5)
        assert float(d.entropy().numpy()) == pytest.approx(np.log(4.0))

    @pytest.mark.parametrize("dist,sp,params", [
        (D.Beta, st.beta, {"alpha": 2.0, "beta": 3.0}),
        (D.Gamma, st.gamma, {"concentration": 2.5, "rate": 1.5}),
        (D.Exponential, st.expon, {"rate": 2.0}),
        (D.Chi2, st.chi2, {"df": 4.0}),
        (D.StudentT, st.t, {"df": 5.0, "loc": 0.5, "scale": 1.2}),
        (D.LogNormal, st.lognorm, {"loc": 0.2, "scale": 0.7}),
    ])
    def test_positive_dists(self, dist, sp, params):
        d = dist(**params)
        xs = np.array([0.1, 0.4, 0.9, 1.7], np.float32)
        if dist is D.Beta:
            frozen = sp(params["alpha"], params["beta"])
            xs = np.array([0.1, 0.4, 0.6, 0.9], np.float32)
        elif dist is D.Gamma:
            frozen = sp(params["concentration"],
                        scale=1 / params["rate"])
        elif dist is D.Exponential:
            frozen = sp(scale=1 / params["rate"])
        elif dist is D.Chi2:
            frozen = sp(params["df"])
        elif dist is D.StudentT:
            frozen = sp(params["df"], loc=params["loc"],
                        scale=params["scale"])
        else:
            frozen = sp(params["scale"], scale=np.exp(params["loc"]))
        np.testing.assert_allclose(d.log_prob(pt.to_tensor(xs)).numpy(),
                                   frozen.logpdf(xs), rtol=2e-4, atol=1e-4)
        np.testing.assert_allclose(float(np.asarray(d.entropy().numpy())),
                                   frozen.entropy(), rtol=1e-3)

    def test_mean_variance(self):
        for d, m, v in [
            (D.Beta(2.0, 3.0), 0.4, 0.04),
            (D.Gamma(2.0, 4.0), 0.5, 0.125),
            (D.Gumbel(0.0, 1.0), 0.5772156, np.pi ** 2 / 6),
        ]:
            assert float(d.mean.numpy()) == pytest.approx(m, rel=1e-4)
            assert float(d.variance.numpy()) == pytest.approx(v, rel=1e-4)


class TestDiscrete:
    def test_bernoulli(self):
        d = D.Bernoulli(0.3)
        frozen = st.bernoulli(0.3)
        for k in (0.0, 1.0):
            assert float(d.log_prob(pt.to_tensor(np.float32(k))).numpy()) \
                == pytest.approx(frozen.logpmf(k), rel=1e-4)
        assert float(d.entropy().numpy()) == pytest.approx(
            frozen.entropy(), rel=1e-4)

    def test_categorical(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        d = D.Categorical(logits)
        np.testing.assert_allclose(d.probs.numpy(), [0.2, 0.3, 0.5],
                                   rtol=1e-5)
        lp = d.log_prob(pt.to_tensor(np.array([0, 2], np.int64))).numpy()
        np.testing.assert_allclose(lp, np.log([0.2, 0.5]), rtol=1e-5)
        pt.seed(1)
        s = d.sample((30_000,)).numpy()
        freq = np.bincount(s.astype(int), minlength=3) / 30_000
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)

    def test_poisson(self):
        d = D.Poisson(3.0)
        frozen = st.poisson(3.0)
        ks = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(d.log_prob(pt.to_tensor(ks)).numpy(),
                                   frozen.logpmf(ks), rtol=1e-4, atol=1e-5)
        assert float(d.entropy().numpy()) == pytest.approx(
            frozen.entropy(), rel=1e-3)

    def test_geometric(self):
        d = D.Geometric(0.25)
        frozen = st.geom(0.25, loc=-1)  # scipy geom counts trials; shift
        ks = np.arange(6, dtype=np.float32)
        np.testing.assert_allclose(d.log_prob(pt.to_tensor(ks)).numpy(),
                                   frozen.logpmf(ks), rtol=1e-4)

    def test_binomial_multinomial(self):
        d = D.Binomial(10.0, 0.4)
        frozen = st.binom(10, 0.4)
        ks = np.arange(11, dtype=np.float32)
        np.testing.assert_allclose(d.log_prob(pt.to_tensor(ks)).numpy(),
                                   frozen.logpmf(ks), rtol=1e-4, atol=1e-4)
        assert float(d.entropy().numpy()) == pytest.approx(
            frozen.entropy(), rel=1e-3)
        m = D.Multinomial(5, np.array([0.3, 0.7], np.float32))
        val = np.array([2.0, 3.0], np.float32)
        assert float(m.log_prob(pt.to_tensor(val)).numpy()) == pytest.approx(
            st.multinomial(5, [0.3, 0.7]).logpmf(val), rel=1e-4)
        pt.seed(2)
        s = m.sample((2000,)).numpy()
        assert s.shape == (2000, 2)
        np.testing.assert_allclose(s.sum(-1), 5.0)
        assert abs(s[:, 0].mean() - 1.5) < 0.1


class TestMultivariate:
    def test_dirichlet(self):
        c = np.array([2.0, 3.0, 4.0], np.float32)
        d = D.Dirichlet(c)
        frozen = st.dirichlet(c)
        x = np.array([0.2, 0.3, 0.5], np.float32)
        assert float(d.log_prob(pt.to_tensor(x)).numpy()) == pytest.approx(
            frozen.logpdf(x), rel=1e-4)
        assert float(d.entropy().numpy()) == pytest.approx(
            frozen.entropy(), rel=1e-3)
        np.testing.assert_allclose(d.mean.numpy(), frozen.mean(), rtol=1e-5)


class TestKL:
    @pytest.mark.parametrize("p,q", [
        (D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)),
        (D.Beta(2.0, 3.0), D.Beta(4.0, 2.0)),
        (D.Gamma(2.0, 1.0), D.Gamma(3.0, 2.0)),
        (D.Laplace(0.0, 1.0), D.Laplace(0.5, 2.0)),
        (D.Gumbel(0.0, 1.0), D.Gumbel(0.5, 1.5)),
        (D.Geometric(0.3), D.Geometric(0.5)),
    ])
    def test_kl_vs_monte_carlo(self, p, q):
        pt.seed(3)
        kl = float(np.asarray(D.kl_divergence(p, q).numpy()))
        mc = _mc_kl(p, q)
        assert kl == pytest.approx(mc, rel=0.08, abs=0.01)

    def test_kl_categorical_bernoulli_dirichlet(self):
        p = D.Categorical(np.log(np.array([0.3, 0.7], np.float32)))
        q = D.Categorical(np.log(np.array([0.5, 0.5], np.float32)))
        expect = 0.3 * np.log(0.3 / 0.5) + 0.7 * np.log(0.7 / 0.5)
        assert float(D.kl_divergence(p, q).numpy()) == pytest.approx(
            expect, rel=1e-5)
        b1, b2 = D.Bernoulli(0.2), D.Bernoulli(0.6)
        expect = 0.2 * np.log(0.2 / 0.6) + 0.8 * np.log(0.8 / 0.4)
        assert float(D.kl_divergence(b1, b2).numpy()) == pytest.approx(
            expect, rel=1e-5)
        d1 = D.Dirichlet(np.array([2.0, 3.0], np.float32))
        d2 = D.Dirichlet(np.array([3.0, 2.0], np.float32))
        pt.seed(4)
        assert float(D.kl_divergence(d1, d2).numpy()) == pytest.approx(
            _mc_kl(d1, d2), rel=0.05, abs=0.01)


class TestTransforms:
    def test_affine_exp_chain(self):
        t = D.ChainTransform([D.AffineTransform(1.0, 2.0),
                              D.ExpTransform()])
        x = np.array([0.0, 0.5], np.float32)
        y = t.forward(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(y, np.exp(1 + 2 * x), rtol=1e-5)
        np.testing.assert_allclose(t.inverse(pt.to_tensor(y)).numpy(), x,
                                   rtol=1e-5, atol=1e-6)
        # ldj = log|2| + (1+2x)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(pt.to_tensor(x)).numpy(),
            np.log(2) + 1 + 2 * x, rtol=1e-5)

    def test_sigmoid_tanh(self):
        for tr, fwd in [(D.SigmoidTransform(), lambda v: 1 / (1 + np.exp(-v))),
                        (D.TanhTransform(), np.tanh)]:
            x = np.array([-1.0, 0.3, 1.2], np.float32)
            y = tr.forward(pt.to_tensor(x)).numpy()
            np.testing.assert_allclose(y, fwd(x), rtol=1e-5)
            np.testing.assert_allclose(tr.inverse(pt.to_tensor(y)).numpy(),
                                       x, rtol=1e-4, atol=1e-5)
            # ldj finite-diff check
            eps = 1e-3
            num = (fwd(x + eps) - fwd(x - eps)) / (2 * eps)
            np.testing.assert_allclose(
                tr.forward_log_det_jacobian(pt.to_tensor(x)).numpy(),
                np.log(num), atol=1e-3)

    def test_stickbreaking_roundtrip(self):
        t = D.StickBreakingTransform()
        x = np.array([0.3, -0.2, 0.5], np.float32)
        y = t.forward(pt.to_tensor(x)).numpy()
        assert y.shape == (4,)
        np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-5)
        np.testing.assert_allclose(t.inverse(pt.to_tensor(y)).numpy(), x,
                                   rtol=1e-4, atol=1e-5)

    def test_transformed_distribution_lognormal(self):
        pt.seed(5)
        base = D.Normal(0.2, 0.7)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ln = D.LogNormal(0.2, 0.7)
        xs = np.array([0.5, 1.0, 2.0], np.float32)
        np.testing.assert_allclose(td.log_prob(pt.to_tensor(xs)).numpy(),
                                   ln.log_prob(pt.to_tensor(xs)).numpy(),
                                   rtol=1e-5)

    def test_independent(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == (3,) and ind.event_shape == (4,)
        x = RNG.standard_normal((3, 4)).astype(np.float32)
        np.testing.assert_allclose(
            ind.log_prob(pt.to_tensor(x)).numpy(),
            base.log_prob(pt.to_tensor(x)).numpy().sum(-1), rtol=1e-5)

    def test_reshape_stack(self):
        t = D.ReshapeTransform((4,), (2, 2))
        x = np.arange(4, dtype=np.float32)
        y = t.forward(pt.to_tensor(x)).numpy()
        assert y.shape == (2, 2)
        st_ = D.StackTransform([D.ExpTransform(),
                                D.AffineTransform(0.0, 2.0)], axis=0)
        x2 = np.stack([x, x])
        y2 = st_.forward(pt.to_tensor(x2)).numpy()
        np.testing.assert_allclose(y2[0], np.exp(x), rtol=1e-5)
        np.testing.assert_allclose(y2[1], 2 * x, rtol=1e-5)
