"""Dy2static control-flow conversion (VERDICT r3 item 2).

Reference routes: jit/dy2static/program_translator.py (AST) and
jit/sot/translate.py:30 (bytecode + graph break).  Here: one AST pass with
runtime-dispatched helpers (paddle_tpu/jit/dy2static.py) + eager fallback.
"""

import warnings

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.jit as jit
from paddle_tpu.jit.dy2static import convert_control_flow


def _n(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def ten(x, dtype="float32"):
    return pt.to_tensor(np.asarray(x, dtype))


class TestTensorIf:
    def test_both_branches(self):
        @jit.to_static
        def f(x):
            if x.mean() > 0:
                y = x * 2
            else:
                y = x - 1
            return y

        np.testing.assert_allclose(_n(f(ten([1.0, 2.0]))), [2, 4])
        np.testing.assert_allclose(_n(f(ten([-1.0, -2.0]))), [-2, -3])

    def test_elif_chain(self):
        @jit.to_static
        def f(x):
            if x.mean() > 10:
                y = x + 100
            elif x.mean() > 0:
                y = x + 10
            else:
                y = x
            return y

        np.testing.assert_allclose(_n(f(ten([20.0]))), [120])
        np.testing.assert_allclose(_n(f(ten([1.0]))), [11])
        np.testing.assert_allclose(_n(f(ten([-1.0]))), [-1])

    def test_no_else(self):
        @jit.to_static
        def f(x):
            y = x + 1
            if x.sum() > 0:
                y = y * 3
            return y

        np.testing.assert_allclose(_n(f(ten([1.0]))), [6])
        np.testing.assert_allclose(_n(f(ten([-5.0]))), [-4])

    def test_python_condition_untouched(self):
        @jit.to_static
        def f(x, flag=True):
            if flag:
                return x + 1
            return x - 1

        np.testing.assert_allclose(_n(f(ten([1.0]))), [2])

    def test_augassign_in_branch(self):
        @jit.to_static
        def f(x):
            acc = x * 0
            if x.max() > 0:
                acc += x
            return acc

        np.testing.assert_allclose(_n(f(ten([3.0]))), [3])


class TestTensorWhile:
    def test_geometric(self):
        @jit.to_static
        def f(x):
            while x.sum() < 100:
                x = x * 2
            return x

        assert float(f(ten([1.0])).sum()) == 128

    def test_counter_carry(self):
        @jit.to_static
        def f(x):
            n = x * 0
            while n.sum() < 5:
                n = n + 1
                x = x + 10
            return x, n

        x, n = f(ten([0.0]))
        assert float(x.sum()) == 50 and float(n.sum()) == 5

    def test_while_with_if_inside(self):
        @jit.to_static
        def f(x):
            while x.sum() < 50:
                if x.mean() > 4:
                    x = x + 10
                else:
                    x = x * 3
            return x

        assert float(f(ten([1.0])).sum()) == 59


class TestTensorFor:
    def test_for_range_tensor(self):
        @jit.to_static
        def f(x, n):
            acc = x
            for i in range(n):
                acc = acc + i
            return acc

        assert float(f(ten([0.0]), ten(4, "int32")).sum()) == 6

    def test_for_over_tensor_rows(self):
        @jit.to_static
        def f(m):
            acc = m[0] * 0
            for row in m:
                acc = acc + row
            return acc

        out = f(ten([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]))
        np.testing.assert_allclose(_n(out), [9, 12])

    def test_python_range_untouched(self):
        @jit.to_static
        def f(x):
            for i in range(3):
                x = x + i
            return x

        assert float(f(ten([0.0])).sum()) == 3

    def test_loop_var_bound_after_loop(self):
        # plain Python leaves the last value of the loop var bound
        @jit.to_static
        def f(x, n):
            for i in range(n):
                x = x + 1
            return x * i

        out = f(ten([0.0]), ten(3, "int32"))
        assert float(out.sum()) == 6.0      # (0+3) * i==2

    def test_mismatched_branch_structure_falls_back(self):
        # int-vs-tensor branch outputs can't lower to lax.cond; the
        # ConversionFallback path must re-run eagerly, not crash
        @jit.to_static
        def f(x):
            y = 0
            if x.sum() > 0:
                y = x
            return y

        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            out = f(ten([1.0, 2.0]))
        np.testing.assert_allclose(_n(out), [1, 2])


class TestBoolOps:
    def test_and_or_not(self):
        @jit.to_static
        def f(x):
            if (x.mean() > 0) and (x.max() < 10):
                y = x + 1
            elif not (x.min() > -100) or (x.sum() > 1000):
                y = x - 1
            else:
                y = x * 0
            return y

        np.testing.assert_allclose(_n(f(ten([1.0, 2.0]))), [2, 3])
        np.testing.assert_allclose(_n(f(ten([50.0]))), [0])

    def test_python_bool_lazy(self):
        calls = []

        def probe():
            calls.append(1)
            return True

        @jit.to_static
        def f(x, flag=False):
            if flag and probe():
                return x + 1
            return x

        f(ten([1.0]))
        assert calls == []      # rhs never evaluated: laziness preserved


class TestGraphBreakFallback:
    def test_early_return_falls_back(self):
        @jit.to_static
        def f(x):
            if x.mean() > 0:
                return x * 10
            return x

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = f(ten([1.0, 2.0]))
        np.testing.assert_allclose(_n(out), [10, 20])
        assert any("graph break" in str(x.message) for x in w)

    def test_full_graph_raises(self):
        @jit.to_static(full_graph=True)
        def f(x):
            if x.mean() > 0:
                return x * 10
            return x

        with pytest.raises(Exception):
            f(ten([1.0]))


class TestModelEquivalence:
    """VERDICT done-criterion: a dygraph model with data-dependent branch
    AND loop matches eager under to_static."""

    def _make(self):
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 8)
                self.fc2 = nn.Linear(8, 8)

            def forward(self, x):
                h = self.fc1(x)
                # data-dependent branch
                if h.mean() > 0:
                    h = pt.nn.functional.relu(h)
                else:
                    h = h * 0.5
                # data-dependent loop: normalize until small
                while h.abs().sum() > 4.0:
                    h = h * 0.5
                return self.fc2(h)

        return Net()

    def test_eager_vs_static(self):
        pt.seed(0)
        net = self._make()
        x = ten(np.random.default_rng(0).standard_normal((4, 8)))
        eager = _n(net(x))
        snet = jit.to_static(net)
        static = _n(snet(x))
        np.testing.assert_allclose(eager, static, rtol=2e-5, atol=2e-5)

    def test_second_call_uses_cache(self):
        net = self._make()
        snet = jit.to_static(net)
        x = ten(np.random.default_rng(1).standard_normal((4, 8)))
        a = _n(snet(x))
        b = _n(snet(x))
        np.testing.assert_allclose(a, b)


class TestConverterMechanics:
    def test_no_source_returns_original(self):
        fn = eval("lambda x: x + 1")
        assert convert_control_flow(fn) is fn

    def test_conversion_cached(self):
        def f(x):
            if x.sum() > 0:
                y = x
            else:
                y = -x
            return y

        assert convert_control_flow(f) is convert_control_flow(f)

    def test_closure_preserved(self):
        scale = 3.0

        def f(x):
            if x.sum() > 0:
                y = x * scale
            else:
                y = x
            return y

        g = jit.to_static(f)
        np.testing.assert_allclose(_n(g(ten([2.0]))), [6])

    def test_pure_python_function_not_transformed(self):
        def f(a, b):
            return a + b

        assert convert_control_flow(f) is f


class TestBreakContinue:
    """Reference break_continue_transformer semantics: break/continue in
    tensor loops lower to guard flags (break -> loop-exit carry, continue
    -> per-iteration guard); for-loops with break graph-break to eager."""

    def test_while_break(self):
        @jit.to_static
        def f(x):
            while x.sum() < 1000:
                x = x * 2
                if x.max() > 50:
                    break
            return x

        assert float(f(ten([1.0])).sum()) == 64.0

    def test_while_continue(self):
        @jit.to_static
        def f(x):
            n = x * 0
            s = x * 0
            while n.sum() < 6:
                n = n + 1
                if (n.sum() % 2) > 0:
                    continue
                s = s + n
            return s

        assert float(f(ten([0.0])).sum()) == 12.0

    def test_break_mid_body_skips_rest(self):
        @jit.to_static
        def f(x):
            total = x * 0
            while total.sum() < 100:
                total = total + 10
                if total.sum() >= 30:
                    break
                total = total + 1
            return total

        assert float(f(ten([0.0])).sum()) == 32.0

    def test_for_continue(self):
        @jit.to_static
        def f(x, k):
            acc = x
            for i in range(k):
                if (i % 2) == 0:
                    continue
                acc = acc + i
            return acc

        assert float(f(ten([0.0]), ten(6, "int32")).sum()) == 9.0

    def test_for_range_break_compiles(self):
        # range-for with break rewrites to an index WHILE whose break
        # lowering joins the loop condition — no graph break
        @jit.to_static
        def f(x, k):
            acc = x
            for i in range(k):
                if i >= 2:
                    break
                acc = acc + 10
            return acc

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = f(ten([0.0]), ten(5, "int32"))
        assert not any("graph break" in str(x.message) for x in w)
        assert float(out.sum()) == 20.0

    def test_for_range_break_and_continue_mixed(self):
        @jit.to_static
        def f(x, n):
            s = x
            for i in range(n):
                if (i % 2) == 0:
                    continue
                s = s + i
                if s.sum() > 6:
                    break
            return s

        assert float(f(ten([0.0]), ten(100, "int32")).sum()) == 9.0

    def test_for_range_two_arg_break(self):
        @jit.to_static
        def f(x, a, b):
            s = x
            for i in range(a, b):
                s = s + i
                if s.sum() > 12:
                    break
            return s

        out = f(ten([0.0]), ten(3, "int32"), ten(100, "int32"))
        assert float(out.sum()) == 18.0

    def test_for_iter_break_falls_back(self):
        # break over a TENSOR iterable still graph-breaks (no index form)
        @jit.to_static
        def f(m):
            acc = m[0] * 0
            for row in m:
                acc = acc + row
                if acc.sum() > 3:
                    break
            return acc

        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            out = f(ten([[1.0], [2.0], [3.0], [4.0]]))
        assert float(out.sum()) == 6.0

    def test_python_loop_break_untouched(self):
        @jit.to_static
        def f(x):
            for i in range(10):
                if i == 3:
                    break
                x = x + 1
            return x

        assert float(f(ten([0.0])).sum()) == 3.0

    def test_unlowerable_after_break_restores(self):
        # review finding: a `del` after lowering must RESTORE the loop
        # (graph-break), not leave a half-lowered body referencing flags
        @jit.to_static
        def f(x):
            while x.sum() < 10:
                t = x * 2
                if t.max() > 5:
                    break
                del t
                x = x + 1
            return x

        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            out = f(ten([1.0]))
        assert float(out.sum()) >= 1.0      # correct eager semantics

    def test_unlowerable_for_continue_restores(self):
        @jit.to_static
        def f(x):
            acc = x
            for i in range(6):
                t = acc * 2
                if i % 2 == 0:
                    continue
                del t
                acc = acc + i
            return acc

        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            out = f(ten([0.0]))
        assert float(out.sum()) == 9.0


class TestClosureDefaults:
    def test_loop_local_closure_defaults_survive_conversion(self):
        # slow-lane regression: default-arg EXPRESSIONS referencing
        # enclosing loop variables must not re-evaluate in the exec
        # namespace at conversion time
        payload = [np.ones((2, 2), "float32")]
        pos = [0]

        def traced_fn(*ts, _args=payload, _tpos=pos):
            full = list(_args)
            for i, t in zip(_tpos, ts):
                full[i] = t
            return pt.zeros_like(full[0])

        out = jit.to_static(traced_fn)(ten(payload[0]))
        np.testing.assert_allclose(_n(out), 0)

    def test_defaults_still_work_when_omitted(self):
        def f(x, scale=3.0):
            if x.sum() > 0:
                y = x * scale
            else:
                y = x
            return y

        g = jit.to_static(f)
        np.testing.assert_allclose(_n(g(ten([2.0]))), [6.0])

    def test_prebound_target_survives_zero_trip_break_loop(self):
        # review finding: `i = 7; for i in range(0): ...` must keep i==7
        @jit.to_static
        def f(x, n):
            i = 7
            for i in range(n):
                x = x + 1
                if x.sum() > 100:
                    break
            return x * i

        out = f(ten([1.0]), ten(0, "int32"))
        assert float(out.sum()) == 7.0
