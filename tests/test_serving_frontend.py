"""Streaming serving front-end (ISSUE 7): request lifecycle, streaming
delivery, SLO-aware admission, deadlines, and the Poisson loadgen.

Load-bearing contracts (tier-1 — this is the serve path the "millions
of users" pillar is judged on):

* tokens received through the stream are BIT-IDENTICAL to the batch
  ``run_to_completion()`` results for the same request (greedy and
  sampled), and they arrive while the request is RUNNING — not at
  retire;
* a slow consumer backpressures a bounded stream without dropping or
  reordering tokens;
* deadline expiry mid-decode frees the engine slot (and its refcounted
  KV pages) within one scheduler iteration;
* admission control rejects instead of queueing unboundedly;
* a seeded loadgen run with cancellations and timeouts drains with
  ZERO leaked KV blocks.
"""

import threading
import time

import jax
import numpy as np
import pytest

from paddle_tpu import parallel as dist
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models.llama import build_llama_train_step, llama_tiny
from paddle_tpu.observability import MemorySink, REGISTRY
from paddle_tpu.parallel.topology import HybridTopology, set_topology
from paddle_tpu.serving import (AdmissionConfig, LoadGenConfig,
                                PoissonLoadGenerator, RequestAborted,
                                RequestRejected, RequestState,
                                ServingFrontend)

rng = np.random.default_rng(0)


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny()
    topo = dist.init_topology(devices=jax.devices()[:1])
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    return cfg, params


def _engine(model, **kw):
    cfg, params = model
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("prefill_buckets", (8,))
    return ContinuousBatchingEngine(cfg, params, **kw)


def _prompt(model, n):
    return rng.integers(0, model[0].vocab_size, (n,)).astype(np.int32)


def _assert_no_leaks(eng):
    rep = eng.kv_leak_report()
    assert rep["leaked"] == 0 and rep["unaccounted"] == 0, rep


# ---------------------------------------------------------------------
# streaming semantics
# ---------------------------------------------------------------------
def test_stream_bit_identical_to_batch(model):
    """Streamed token ids == batch run_to_completion ids, for greedy AND
    sampled requests with the same seeds."""
    prompts = [_prompt(model, n) for n in (5, 9, 3)]
    kwargs = [dict(), dict(temperature=0.8, top_k=20, seed=7), dict()]

    ref_eng = _engine(model)
    rids = [ref_eng.add_request(p, 6, **kw)
            for p, kw in zip(prompts, kwargs)]
    ref = ref_eng.run_to_completion()

    fe = ServingFrontend(_engine(model))
    handles = [fe.submit(p, 6, **kw) for p, kw in zip(prompts, kwargs)]
    streamed = [list(h) for h in handles]   # iteration drives the pump
    for h, toks, rid, p in zip(handles, streamed, rids, prompts):
        assert h.state is RequestState.FINISHED
        full = np.concatenate([p, np.asarray(toks, np.int32)])
        np.testing.assert_array_equal(full, ref[rid])
        np.testing.assert_array_equal(h.result(), ref[rid])
    _assert_no_leaks(fe.engine)


def test_tokens_stream_before_retire(model):
    """Tokens must be observable while the request is still RUNNING —
    delivery per engine step, not a result dump at retirement."""
    fe = ServingFrontend(_engine(model))
    h = fe.submit(_prompt(model, 5), 8)
    fe.step()
    assert h.state is RequestState.RUNNING
    assert h.n_streamed >= 1          # prefill's first token, streamed
    seen_running = h.n_streamed
    h.result()
    assert h.state is RequestState.FINISHED
    assert h.n_streamed == 8 and seen_running < 8


def test_slow_consumer_backpressure_no_drop_no_reorder(model):
    """Bounded stream + threaded driver: a consumer slower than the
    producer blocks the producer (recorded backpressure wait) and still
    receives every token in order."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        fe = ServingFrontend(_engine(model), stream_capacity=2,
                             backpressure_timeout_s=30.0)
        fe.start()
        try:
            p = _prompt(model, 6)
            h = fe.submit(p, 12)
            got = []
            for tok in h:
                time.sleep(0.03)               # slower than decode
                got.append(tok)
        finally:
            fe.stop()
        solo = _engine(model, max_batch=1)
        rid = solo.add_request(p, 12)
        want = solo.run_to_completion()[rid]
        np.testing.assert_array_equal(np.asarray(got, np.int32),
                                      want[len(p):])
        assert h.backpressure_wait_s > 0.0
        hist = REGISTRY.get("serve.backpressure_wait_secs")
        assert hist is not None and hist.count >= 1
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_cancel_mid_stream_frees_blocks(model):
    """handle.cancel() mid-decode frees the slot + refcounted pages at
    once; the batchmate's output is unaffected (still bit-identical to
    its solo run)."""
    pa, pb = _prompt(model, 5), _prompt(model, 9)
    solo = _engine(model, max_batch=1)
    rid = solo.add_request(pb, 6)
    want = solo.run_to_completion()[rid]

    fe = ServingFrontend(_engine(model))
    ha = fe.submit(pa, 40)
    hb = fe.submit(pb, 6)
    fe.step()
    fe.step()
    assert ha.n_streamed >= 2
    assert ha.cancel()
    assert not ha.cancel()                     # idempotent-false
    assert ha.state is RequestState.CANCELLED
    assert fe.engine.active_requests == 1      # only hb keeps a slot
    fe.run_until_drained(timeout_s=120)
    np.testing.assert_array_equal(hb.result(), want)
    with pytest.raises(RequestAborted):
        ha.result()
    _assert_no_leaks(fe.engine)


# ---------------------------------------------------------------------
# deadlines / shedding
# ---------------------------------------------------------------------
def test_deadline_mid_decode_frees_slot_within_one_step(model):
    now = [0.0]
    fe = ServingFrontend(_engine(model), clock=lambda: now[0])
    h = fe.submit(_prompt(model, 5), 50, deadline_s=10.0)
    fe.step()
    fe.step()
    assert h.state is RequestState.RUNNING and h.n_streamed >= 1
    before = h.n_streamed
    now[0] = 11.0
    fe.step()                                   # ONE iteration
    assert h.state is RequestState.TIMED_OUT
    assert h.reason == "deadline"
    assert fe.engine.active_requests == 0       # slot freed
    assert h.n_streamed >= before               # partial stream kept
    _assert_no_leaks(fe.engine)


def test_max_queue_time_sheds_waiting_request(model):
    """A request that cannot get a slot within its queue budget is shed
    as TIMED_OUT without ever running; the running request finishes."""
    now = [0.0]
    fe = ServingFrontend(_engine(model, max_batch=1),
                         clock=lambda: now[0])
    h1 = fe.submit(_prompt(model, 5), 30)
    h2 = fe.submit(_prompt(model, 4), 4, max_queue_time_s=5.0)
    fe.step()
    assert h2.state is RequestState.QUEUED and h2.n_streamed == 0
    now[0] = 6.0
    fe.step()
    assert h2.state is RequestState.TIMED_OUT
    assert h2.reason == "max_queue_time"
    assert h1.state is RequestState.RUNNING     # untouched
    h1.cancel()
    _assert_no_leaks(fe.engine)


# ---------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------
def test_admission_rejects_when_queue_full(model):
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        fe = ServingFrontend(
            _engine(model, max_batch=1),
            admission=AdmissionConfig(max_queue_len=1))
        h1 = fe.submit(_prompt(model, 5), 20)   # will occupy the slot
        fe.step()
        h2 = fe.submit(_prompt(model, 5), 4)    # waits (1 queued)
        h3 = fe.submit(_prompt(model, 5), 4)    # over max_queue_len
        assert h3.state is RequestState.REJECTED
        assert "queue full" in h3.reason
        with pytest.raises(RequestRejected):
            h3.result()
        with pytest.raises(RequestRejected):
            next(iter(h3))
        assert REGISTRY.get("serve.rejected_total").value == 1
        assert h2.state is not RequestState.REJECTED
        fe.close()                               # cancels h1/h2
        _assert_no_leaks(fe.engine)
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_admission_rejects_on_kv_demand(model):
    fe = ServingFrontend(
        _engine(model, num_blocks=8),
        admission=AdmissionConfig(kv_demand_factor=1.0))
    h1 = fe.submit(_prompt(model, 8), 24)       # 4 of 8 blocks
    h2 = fe.submit(_prompt(model, 8), 24)       # 8 of 8: at the cap
    h3 = fe.submit(_prompt(model, 8), 8)        # over 1.0x demand
    assert h3.state is RequestState.REJECTED
    assert "kv pool saturated" in h3.reason
    assert h1.state is not RequestState.REJECTED
    assert h2.state is not RequestState.REJECTED
    fe.close()
    _assert_no_leaks(fe.engine)


def test_impossible_request_is_rejected_not_raised(model):
    """A request no drain could ever admit (more pages than the pool)
    is load-shedding territory for a front door: REJECTED handle, not
    an exception mid-traffic.  Malformed requests still raise."""
    fe = ServingFrontend(_engine(model, num_blocks=4, max_batch=1))
    h = fe.submit(np.zeros(24, np.int32), 24)
    assert h.state is RequestState.REJECTED
    with pytest.raises(ValueError):
        fe.submit(np.zeros(0, np.int32), 4)     # empty prompt: a bug
    with pytest.raises(ValueError):
        fe.submit(np.zeros(4, np.int32), 0)     # zero budget: a bug


# ---------------------------------------------------------------------
# telemetry + crash behavior
# ---------------------------------------------------------------------
def test_serve_telemetry_gauges_and_events(model):
    REGISTRY.reset()
    REGISTRY.enable()
    sink = MemorySink()
    REGISTRY.add_sink(sink)
    try:
        fe = ServingFrontend(_engine(model))
        h = fe.submit(_prompt(model, 5), 4)
        fe.run_until_drained(timeout_s=120)
        assert h.state is RequestState.FINISHED
        assert REGISTRY.get("serve.submitted_total").value == 1
        assert REGISTRY.get("serve.finished_total").value == 1
        assert REGISTRY.get("serve.tokens_streamed_total").value == 4
        assert REGISTRY.get("serve.ttft_secs").count == 1
        occ = REGISTRY.get("serve.batch_occupancy")
        util = REGISTRY.get("serve.kv_utilization")
        assert occ is not None and occ.value == 0.0     # drained
        assert util is not None and 0.0 <= util.value <= 1.0
        actions = [r.get("action") for r in sink.records
                   if r.get("kind") == "serve"]
        for expected in ("submit", "first_token", "finish"):
            assert expected in actions, actions
    finally:
        REGISTRY.remove_sink(sink)
        REGISTRY.disable()
        REGISTRY.reset()


def test_engine_crash_aborts_streams(model):
    """An engine failure mid-pump surfaces on the frontend AND
    terminates every live handle — consumers never hang on a dead
    scheduler."""
    fe = ServingFrontend(_engine(model))
    h = fe.submit(_prompt(model, 5), 8)
    fe.step()

    def boom():
        raise RuntimeError("injected engine failure")

    fe.engine.step = boom
    with pytest.raises(RuntimeError, match="injected"):
        fe.step()
    assert fe.error is not None
    assert h.state is RequestState.CANCELLED
    assert "frontend crashed" in h.reason
    with pytest.raises(RequestAborted):
        h.result()


# ---------------------------------------------------------------------
# loadgen smoke (the CI acceptance scenario)
# ---------------------------------------------------------------------
def _run_loadgen(model, seed=3):
    fe = ServingFrontend(
        _engine(model, num_blocks=48),
        admission=AdmissionConfig(max_queue_len=64))
    gen = PoissonLoadGenerator(fe, LoadGenConfig(
        n_requests=24, rate_rps=300.0, seed=seed, prompt_len=(3, 10),
        max_new_tokens=(3, 8), sampled_fraction=0.25,
        cancel_fraction=0.2, cancel_after_tokens=2,
        slo_ttft_s=60.0, slo_tpot_s=30.0))
    return fe, gen.run()


def test_loadgen_smoke_deterministic_no_leaks(model):
    """Fixed-seed Poisson run with mid-stream cancellations: nonzero
    streamed tokens, every request reaches a terminal state, zero
    leaked KV blocks after drain, and the report carries the percentile
    fields the bench row publishes."""
    fe, rep = _run_loadgen(model)
    assert rep.total_streamed_tokens > 0
    assert (rep.finished + rep.rejected + rep.cancelled
            + rep.timed_out) == rep.n_requests
    assert rep.finished > 0 and rep.cancelled > 0
    assert rep.kv_leaks["leaked"] == 0
    assert rep.kv_leaks["unaccounted"] == 0
    assert fe.engine.active_requests == 0 and fe.engine.queue_depth == 0
    assert rep.ttft_s is not None
    for key in ("p50", "p95", "p99"):
        assert rep.ttft_s[key] > 0.0
    assert rep.tokens_per_s > 0.0
    assert rep.goodput_rps > 0.0          # generous SLOs: all good
    d = rep.to_dict()
    assert d["kv_leaked_blocks"] == 0 and "goodput_rps" in d


def test_loadgen_with_timeouts_drains_clean(model):
    """ISSUE 7 acceptance: a run where load shedding actually fires —
    queue-time budgets kill waiting requests mid-traffic — still drains
    with zero leaked KV blocks and every request terminal."""
    fe = ServingFrontend(_engine(model, num_blocks=48))
    gen = PoissonLoadGenerator(fe, LoadGenConfig(
        n_requests=30, rate_rps=500.0, seed=11, prompt_len=(4, 10),
        max_new_tokens=(8, 16), cancel_fraction=0.1,
        max_queue_time_s=0.1, slo_ttft_s=60.0, slo_tpot_s=30.0))
    rep = gen.run()
    assert (rep.finished + rep.rejected + rep.cancelled
            + rep.timed_out) == rep.n_requests
    assert rep.finished >= 1          # head of line always runs
    assert rep.timed_out >= 1         # a 500 rps burst on 2 slots sheds
    assert rep.kv_leaks["leaked"] == 0
    assert rep.kv_leaks["unaccounted"] == 0
    assert fe.engine.active_requests == 0 and fe.engine.queue_depth == 0


def test_loadgen_outputs_reproducible(model):
    """Same seed twice: the same requests finish with the same token
    ids (wall-clock shifts scheduling, but the engine pins per-request
    results independent of batch composition)."""
    _, rep1 = _run_loadgen(model, seed=5)
    _, rep2 = _run_loadgen(model, seed=5)
    states1 = [r["state"] for r in rep1.per_request]
    states2 = [r["state"] for r in rep2.per_request]
    assert states1 == states2
    for r1, r2 in zip(rep1.per_request, rep2.per_request):
        if r1["state"] == "FINISHED" and r2["state"] == "FINISHED":
            assert r1["n_tokens"] == r2["n_tokens"]
