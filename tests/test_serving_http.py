"""HTTP/SSE network front door (ISSUE 13): wire bit-identity, typed
status mapping, disconnect-safe streaming, slow-client isolation,
idempotent retry, graceful shutdown, and wire-level chaos.

Load-bearing contracts:

* token streams fetched over HTTP/SSE are BIT-IDENTICAL to the
  in-process ``ServingFrontend`` streams for the same seeds — greedy,
  sampled, and across a mid-stream replica kill observed through the
  socket (the PR 12 re-placement machinery, now proven at the wire);
* a broken/closed client socket cancels its request and frees the
  decode slot + refcounted KV pages (disconnect storms drain at
  ``kv_leaked_blocks == 0``);
* one stalled reader is isolated by the per-connection write deadline
  and never blocks the driver thread or its batchmates;
* a retry with the same ``request_id`` attaches to the live stream and
  replays the committed prefix instead of double-submitting;
* graceful shutdown under load drains in-flight streams, 503s new
  work with ``Retry-After``, and exits with a zero-leak report;
* the loadgen's wire transport offers the IDENTICAL seeded request
  sequence as its in-process mode, so wire chaos results are
  comparable to the fleet-chaos baselines.
"""

import http.client
import json
import signal
import tempfile
import threading
import time

import jax
import numpy as np
import pytest

from paddle_tpu import parallel as dist
from paddle_tpu.aot.serve import export_engine, warm_engine_factory
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models.llama import build_llama_train_step, llama_tiny
from paddle_tpu.observability import REGISTRY
from paddle_tpu.parallel.topology import HybridTopology, set_topology
from paddle_tpu.serving import (AdmissionConfig, EngineRouter,
                                HttpServingServer, LoadGenConfig,
                                PoissonLoadGenerator, RetryPolicy,
                                ServingFrontend)
from paddle_tpu.serving.http import HttpTransport, iter_sse

import faults

rng = np.random.default_rng(0)

# one geometry for the whole module so the AOT artifacts (exported
# once) warm-start every engine — tests pay deserialization, not
# tracing
GEOM = dict(max_batch=2, block_size=8, num_blocks=64,
            prefill_buckets=(8,))


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny()
    topo = dist.init_topology(devices=jax.devices()[:1])
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    return cfg, params


@pytest.fixture(scope="module")
def aot_dir(model):
    cfg, params = model
    d = tempfile.mkdtemp(prefix="http_aot_")
    export_engine(ContinuousBatchingEngine(cfg, params, **GEOM), d)
    return d


def _engine(model, aot_dir=None, **kw):
    cfg, params = model
    geom = dict(GEOM)
    geom.update(kw)
    return ContinuousBatchingEngine(cfg, params, aot_dir=aot_dir, **geom)


def _prompt(model, n):
    return rng.integers(0, model[0].vocab_size, (n,)).astype(np.int32)


def _assert_no_leaks(eng):
    rep = eng.kv_leak_report()
    assert rep["leaked"] == 0 and rep["unaccounted"] == 0, rep


def _post(port, path, payload, timeout=60.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    return conn, conn.getresponse()


def _get_json(port, path, timeout=10.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}"), \
            dict(resp.getheaders())
    finally:
        conn.close()


def _sse_collect(port, payload, timeout=120.0):
    """POST a streaming generate and collect ``(tokens_in_order,
    terminal_event, terminal_payload)`` from the SSE stream."""
    conn, resp = _post(port, "/v1/generate", payload, timeout)
    try:
        assert resp.status == 200, resp.read()
        toks = {}
        for event, data in iter_sse(resp):
            if event == "token":
                toks[data["i"]] = data["t"]
            else:
                return ([toks[i] for i in sorted(toks)], event, data)
        return ([toks[i] for i in sorted(toks)], "eof", {})
    finally:
        conn.close()


def _wait(pred, timeout_s=10.0, msg="condition"):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout_s:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.01)


def _counter(name):
    m = REGISTRY.get(name)
    return 0 if m is None else (m.value or 0)


# ---------------------------------------------------------------------
# wire bit-identity
# ---------------------------------------------------------------------
def test_wire_stream_bit_identical_to_inprocess(model, aot_dir):
    """Greedy AND sampled token streams over HTTP/SSE == the in-process
    frontend streams (== the batch engine results) for the same
    seeds."""
    prompts = [_prompt(model, n) for n in (5, 9)]
    kwargs = [dict(), dict(temperature=0.8, top_k=20, seed=7)]

    ref_eng = _engine(model, aot_dir)
    rids = [ref_eng.add_request(p, 6, **kw)
            for p, kw in zip(prompts, kwargs)]
    ref = ref_eng.run_to_completion()

    fe = ServingFrontend(_engine(model, aot_dir))
    srv = HttpServingServer(fe, heartbeat_s=0.1)
    with srv:
        results = []
        for p, kw in zip(prompts, kwargs):
            payload = {"prompt_ids": p.tolist(), "max_new_tokens": 6}
            payload.update(kw)
            results.append(_sse_collect(srv.port, payload))
        for (toks, event, data), rid, p in zip(results, rids, prompts):
            assert event == "done" and data["state"] == "FINISHED"
            full = np.concatenate([p, np.asarray(toks, np.int32)])
            np.testing.assert_array_equal(full, ref[rid])
            # the terminal event carries the same full ids
            np.testing.assert_array_equal(np.asarray(data["ids"]),
                                          ref[rid])
        _assert_no_leaks(fe.engine)


def test_wire_nonstream_json_mode(model, aot_dir):
    p = _prompt(model, 7)
    ref_eng = _engine(model, aot_dir)
    rid = ref_eng.add_request(p, 5)
    ref = ref_eng.run_to_completion()[rid]

    fe = ServingFrontend(_engine(model, aot_dir))
    with HttpServingServer(fe) as srv:
        conn, resp = _post(srv.port, "/v1/generate",
                           {"prompt_ids": p.tolist(),
                            "max_new_tokens": 5, "stream": False})
        try:
            assert resp.status == 200
            body = json.loads(resp.read())
        finally:
            conn.close()
        assert body["state"] == "FINISHED"
        np.testing.assert_array_equal(np.asarray(body["ids"]), ref)


def test_wire_bit_identity_across_replica_kill(model, aot_dir):
    """The PR 12 invariant observed through a socket: a replica dies
    mid-stream, the router re-places and replays from the committed
    prefix, and the SSE client sees ONE gap-free stream whose tokens
    are bit-identical to an unkilled run — greedy and sampled."""
    prompts = [_prompt(model, n) for n in (5, 8)]
    kwargs = [dict(), dict(temperature=0.8, top_k=20, seed=11)]

    ref_eng = _engine(model, aot_dir)
    rids = [ref_eng.add_request(p, 8, **kw)
            for p, kw in zip(prompts, kwargs)]
    ref = ref_eng.run_to_completion()

    factory = warm_engine_factory(model[0], model[1], aot_dir=aot_dir,
                                  **GEOM)
    router = EngineRouter([factory, factory],
                          policy=RetryPolicy(backoff_base_s=0.0),
                          sleep=lambda s: None)
    fe = ServingFrontend(router)
    srv = HttpServingServer(fe, heartbeat_s=0.05)
    with srv:
        streams = [{} for _ in prompts]
        done = [None, None]

        def consume(idx, payload):
            conn, resp = _post(srv.port, "/v1/generate", payload, 120.0)
            try:
                assert resp.status == 200
                for event, data in iter_sse(resp):
                    if event == "token":
                        assert data["i"] not in streams[idx], \
                            "duplicated token index on the wire"
                        streams[idx][data["i"]] = data["t"]
                    else:
                        done[idx] = (event, data)
                        return
            finally:
                conn.close()

        threads = []
        for i, (p, kw) in enumerate(zip(prompts, kwargs)):
            payload = {"prompt_ids": p.tolist(), "max_new_tokens": 8}
            payload.update(kw)
            t = threading.Thread(target=consume, args=(i, payload),
                                 daemon=True)
            t.start()
            threads.append(t)

        # wait until both streams have committed tokens, then kill the
        # replica actually running request 0 — mid-stream, through the
        # server's locked chaos hook
        _wait(lambda: all(len(s) >= 2 for s in streams), 60.0,
              "2 tokens on both wire streams")

        def kill(engine):
            victim = next(pl.replica
                          for pl in engine._placements.values())
            engine.kill_replica(victim, "wire chaos kill")
            return victim

        victim = srv.chaos(kill)
        for t in threads:
            t.join(timeout=120.0)
            assert not t.is_alive()
        assert router.stats["deaths"] == 1 and victim in (0, 1)
        for i, (p, rid) in enumerate(zip(prompts, rids)):
            event, data = done[i]
            assert event == "done", done[i]
            toks = [streams[i][j] for j in sorted(streams[i])]
            assert sorted(streams[i]) == list(range(len(toks))), \
                "token indices must be gap-free"
            np.testing.assert_array_equal(
                np.concatenate([p, np.asarray(toks, np.int32)]),
                ref[rid])
        _assert_no_leaks(router)


# ---------------------------------------------------------------------
# typed status mapping
# ---------------------------------------------------------------------
def test_malformed_requests_are_400(model, aot_dir):
    fe = ServingFrontend(_engine(model, aot_dir))
    with HttpServingServer(fe) as srv:
        cases = [
            b"{not json",
            json.dumps({"max_new_tokens": 4}).encode(),
            json.dumps({"prompt_ids": [], "max_new_tokens": 4}).encode(),
            json.dumps({"prompt_ids": [1, "a"],
                        "max_new_tokens": 4}).encode(),
            json.dumps({"prompt_ids": [1, 2],
                        "max_new_tokens": 0}).encode(),
            json.dumps({"prompt_ids": [1, 2], "max_new_tokens": 4,
                        "temperature": "hot"}).encode(),
        ]
        for raw in cases:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=10)
            try:
                conn.request("POST", "/v1/generate", raw,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 400, (raw, resp.status)
                assert "error" in json.loads(resp.read())
            finally:
                conn.close()
        # unknown path
        conn, resp = _post(srv.port, "/v1/nope", {})
        assert resp.status == 404
        resp.read()
        conn.close()


def test_overload_maps_to_429_with_retry_after(model, aot_dir):
    fe = ServingFrontend(
        _engine(model, aot_dir, max_batch=1),
        admission=AdmissionConfig(max_queue_len=1))
    with HttpServingServer(fe) as srv:
        # occupy the slot + the queue
        c1, r1 = _post(srv.port, "/v1/generate",
                       {"prompt_ids": _prompt(model, 5).tolist(),
                        "max_new_tokens": 40})
        assert r1.status == 200
        _wait(lambda: fe.engine.active_requests == 1, 30.0,
              "first request scheduled")
        c2, r2 = _post(srv.port, "/v1/generate",
                       {"prompt_ids": _prompt(model, 5).tolist(),
                        "max_new_tokens": 4})
        assert r2.status == 200
        conn, resp = _post(srv.port, "/v1/generate",
                           {"prompt_ids": _prompt(model, 5).tolist(),
                            "max_new_tokens": 4, "stream": False})
        try:
            assert resp.status == 429
            assert resp.getheader("Retry-After") is not None
            body = json.loads(resp.read())
            assert body["state"] == "REJECTED"
            assert "queue full" in body["error"]
        finally:
            conn.close()
        for c in (c1, c2):
            c.close()
        _assert_no_leaks(fe.engine)


def test_deadline_maps_to_408_and_queue_shed_to_503(model, aot_dir):
    eng = _engine(model, aot_dir, max_batch=1)
    # slow the decode so the deadline deterministically expires
    # mid-stream rather than racing a fast drain
    slow = faults.slow_steps(eng, 0.01, n=10 ** 6)
    slow.__enter__()
    fe = ServingFrontend(eng)
    with HttpServingServer(fe) as srv:
        # a request whose deadline expires mid-decode → 408 (JSON mode)
        conn, resp = _post(srv.port, "/v1/generate",
                           {"prompt_ids": _prompt(model, 5).tolist(),
                            "max_new_tokens": 100,
                            "deadline_s": 0.15, "stream": False})
        try:
            assert resp.status == 408
            body = json.loads(resp.read())
            assert body["state"] == "TIMED_OUT"
            assert body["reason"] == "deadline"
        finally:
            conn.close()
        # a request that cannot be seated inside its queue budget is
        # shed — load shedding is 503 + Retry-After.  Stealing the
        # whole KV pool (under the scheduler lock) makes "cannot seat"
        # deterministic
        stolen = srv.chaos(
            lambda eng: eng.alloc.acquire(eng.alloc.free_blocks))
        try:
            conn, resp = _post(srv.port, "/v1/generate",
                               {"prompt_ids": _prompt(model, 5).tolist(),
                                "max_new_tokens": 4, "stream": False,
                                "max_queue_time_s": 0.1})
            try:
                assert resp.status == 503
                assert resp.getheader("Retry-After") is not None
                assert json.loads(resp.read())["state"] == "TIMED_OUT"
            finally:
                conn.close()
        finally:
            srv.chaos(lambda eng: eng.alloc.release(stolen))
    slow.__exit__(None, None, None)


def test_cancel_endpoint_maps_to_499(model, aot_dir):
    fe = ServingFrontend(_engine(model, aot_dir))
    with HttpServingServer(fe) as srv:
        got = {}

        def blocking():
            conn, resp = _post(srv.port, "/v1/generate",
                               {"prompt_ids": _prompt(model, 5).tolist(),
                                "max_new_tokens": 100,
                                "request_id": "cancel-me",
                                "stream": False}, timeout=120.0)
            try:
                got["status"] = resp.status
                got["body"] = json.loads(resp.read())
            finally:
                conn.close()

        t = threading.Thread(target=blocking, daemon=True)
        t.start()
        _wait(lambda: fe.live_requests == 1, 30.0, "request live")
        conn, resp = _post(srv.port, "/v1/cancel",
                           {"request_id": "cancel-me"})
        assert resp.status == 200
        assert json.loads(resp.read())["cancelled"] is True
        conn.close()
        t.join(timeout=30.0)
        assert got["status"] == 499
        assert got["body"]["state"] == "CANCELLED"
        # unknown id is found=False, not an error
        conn, resp = _post(srv.port, "/v1/cancel",
                           {"request_id": "never-existed"})
        assert json.loads(resp.read()) == {"cancelled": False,
                                           "found": False}
        conn.close()
        _assert_no_leaks(fe.engine)


def test_fleet_exhausted_maps_to_503(model, aot_dir):
    factory = warm_engine_factory(model[0], model[1], aot_dir=aot_dir,
                                  **GEOM)
    router = EngineRouter([factory],
                          policy=RetryPolicy(backoff_base_s=0.0),
                          sleep=lambda s: None)
    fe = ServingFrontend(router)
    with HttpServingServer(fe) as srv:
        status, body, _ = _get_json(srv.port, "/readyz")
        assert status == 200 and body["ready"] is True
        assert body["health_census"]["HEALTHY"] == 1
        srv.chaos(lambda r: r.kill_replica(0, "chaos"))
        conn, resp = _post(srv.port, "/v1/generate",
                           {"prompt_ids": _prompt(model, 5).tolist(),
                            "max_new_tokens": 4, "stream": False})
        try:
            assert resp.status == 503
            assert resp.getheader("Retry-After") is not None
        finally:
            conn.close()
        status, body, headers = _get_json(srv.port, "/readyz")
        assert status == 503 and body["ready"] is False
        assert body["health_census"]["DEAD"] == 1


# ---------------------------------------------------------------------
# health / ready / metrics endpoints
# ---------------------------------------------------------------------
def test_health_ready_metrics_endpoints(model, aot_dir):
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        fe = ServingFrontend(_engine(model, aot_dir))
        with HttpServingServer(fe, heartbeat_s=0.1) as srv:
            status, body, _ = _get_json(srv.port, "/healthz")
            assert status == 200 and body["status"] == "ok"
            status, body, _ = _get_json(srv.port, "/readyz")
            assert status == 200 and body["ready"] is True
            toks, event, _ = _sse_collect(
                srv.port, {"prompt_ids": _prompt(model, 5).tolist(),
                           "max_new_tokens": 4})
            assert event == "done" and len(toks) == 4
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=10)
            try:
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                assert resp.status == 200
                text = resp.read().decode()
            finally:
                conn.close()
            # the Prometheus dump carries the serve.http.* family
            assert "serve_http_connections_total" in text
            assert "serve_submitted_total" in text
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


# ---------------------------------------------------------------------
# disconnect propagation + storms
# ---------------------------------------------------------------------
def test_disconnect_mid_stream_cancels_and_frees(model, aot_dir):
    """A client that vanishes mid-stream cancels its request — slot and
    refcounted KV pages free — while the batchmate's stream stays
    bit-identical to its solo run."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        pb = _prompt(model, 9)
        solo = _engine(model, aot_dir, max_batch=1)
        rid = solo.add_request(pb, 6)
        want = solo.run_to_completion()[rid]

        fe = ServingFrontend(_engine(model, aot_dir))
        with HttpServingServer(fe, heartbeat_s=0.02,
                               retry_grace_s=0.0) as srv:
            mate = {}

            def consume_mate():
                mate["r"] = _sse_collect(
                    srv.port, {"prompt_ids": pb.tolist(),
                               "max_new_tokens": 6})

            t = threading.Thread(target=consume_mate, daemon=True)
            t.start()
            toks = faults.http_disconnect_mid_stream(
                "127.0.0.1", srv.port,
                {"prompt_ids": _prompt(model, 5).tolist(),
                 "max_new_tokens": 100},
                after_tokens=2, rst=True)
            assert len(toks) == 2
            # the abandoned request must cancel and free its slot
            _wait(lambda: fe.live_requests <= 1, 15.0,
                  "disconnected request cancelled")
            t.join(timeout=60.0)
            mate_toks, event, _ = mate["r"]
            assert event == "done"
            np.testing.assert_array_equal(
                np.concatenate([pb, np.asarray(mate_toks, np.int32)]),
                want)
            _wait(lambda: fe.live_requests == 0, 15.0, "drained")
            assert fe.engine.active_requests == 0
            _assert_no_leaks(fe.engine)
            assert _counter(
                "serve.http.disconnect_cancels_total") >= 1
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_disconnect_storm_drains_with_zero_leaks(model, aot_dir):
    """A storm of connect-stream-vanish clients (FIN and RST mixed)
    plus surviving requests: every abandoned request cancels, the
    survivors' streams stay correct, and the pool drains to zero leaked
    blocks."""
    fe = ServingFrontend(_engine(model, aot_dir),
                         admission=AdmissionConfig(max_queue_len=64))
    with HttpServingServer(fe, heartbeat_s=0.02,
                           retry_grace_s=0.0) as srv:
        p = _prompt(model, 6)
        ref_eng = _engine(model, aot_dir, max_batch=1)
        rid = ref_eng.add_request(p, 6)
        want = ref_eng.run_to_completion()[rid]

        survivors = []
        surv_lock = threading.Lock()

        def survivor():
            r = _sse_collect(srv.port, {"prompt_ids": p.tolist(),
                                        "max_new_tokens": 6})
            with surv_lock:
                survivors.append(r)

        threads = [threading.Thread(target=survivor, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        for i in range(10):
            faults.http_disconnect_mid_stream(
                "127.0.0.1", srv.port,
                {"prompt_ids": _prompt(model, 4).tolist(),
                 "max_new_tokens": 100},
                after_tokens=1, rst=bool(i % 2))
        for t in threads:
            t.join(timeout=120.0)
            assert not t.is_alive()
        _wait(lambda: fe.live_requests == 0, 30.0,
              "storm requests all cancelled")
        assert fe.engine.active_requests == 0
        assert fe.engine.queue_depth == 0
        _assert_no_leaks(fe.engine)
        for toks, event, _ in survivors:
            assert event == "done"
            np.testing.assert_array_equal(
                np.concatenate([p, np.asarray(toks, np.int32)]), want)


def test_connect_then_abandon_flood_is_harmless(model, aot_dir):
    """Connections that send nothing (or a partial request line) and
    vanish must not submit anything, wedge handler threads, or take
    the listener down."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        fe = ServingFrontend(_engine(model, aot_dir))
        with HttpServingServer(fe, io_timeout_s=0.5) as srv:
            opened = faults.connect_then_abandon_flood(
                "127.0.0.1", srv.port, n=20)
            assert opened == 20
            # the server still answers, nothing was ever submitted
            status, body, _ = _get_json(srv.port, "/healthz")
            assert status == 200
            toks, event, _ = _sse_collect(
                srv.port, {"prompt_ids": _prompt(model, 5).tolist(),
                           "max_new_tokens": 4})
            assert event == "done" and len(toks) == 4
            assert REGISTRY.get("serve.submitted_total").value == 1
            _wait(lambda: (_counter(
                "serve.http.active_connections")) <= 1,
                15.0, "flood connections shed")
            _assert_no_leaks(fe.engine)
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_partial_line_writes_parse_fine(model, aot_dir):
    """A client that dribbles the request bytes mid-line is just a slow
    client: the request parses and streams normally."""
    p = _prompt(model, 5)
    ref_eng = _engine(model, aot_dir, max_batch=1)
    rid = ref_eng.add_request(p, 4)
    want = ref_eng.run_to_completion()[rid]
    fe = ServingFrontend(_engine(model, aot_dir))
    with HttpServingServer(fe) as srv:
        status, raw = faults.http_partial_line_writes(
            "127.0.0.1", srv.port,
            {"prompt_ids": p.tolist(), "max_new_tokens": 4})
        assert status == 200
        toks = [json.loads(line.split(b":", 1)[1])["t"]
                for line in raw.split(b"\n")
                if line.startswith(b"data:") and b'"t"' in line]
        np.testing.assert_array_equal(
            np.concatenate([p, np.asarray(toks, np.int32)]), want)
        _assert_no_leaks(fe.engine)


# ---------------------------------------------------------------------
# slow-client isolation
# ---------------------------------------------------------------------
def test_stalled_reader_isolated_from_batchmates(model, aot_dir):
    """A reader that stops draining its socket (closed TCP window)
    times out on the per-connection write deadline and is cancelled;
    the driver thread and the batchmate never notice."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        pb = _prompt(model, 9)
        solo = _engine(model, aot_dir, max_batch=1)
        rid = solo.add_request(pb, 6)
        want = solo.run_to_completion()[rid]

        fe = ServingFrontend(_engine(model, aot_dir),
                             stream_capacity=4,
                             backpressure_timeout_s=0.2)
        with HttpServingServer(fe, heartbeat_s=0.02,
                               heartbeat_pad_bytes=4096,
                               event_pad_bytes=4096,
                               io_timeout_s=0.5,
                               retry_grace_s=0.0,
                               sndbuf_bytes=4096) as srv:
            stalled = faults.http_stalled_reader(
                "127.0.0.1", srv.port,
                {"prompt_ids": _prompt(model, 5).tolist(),
                 "max_new_tokens": 100}, rcvbuf=1024)
            try:
                # batchmate streams to completion while the stall is live
                toks, event, _ = _sse_collect(
                    srv.port, {"prompt_ids": pb.tolist(),
                               "max_new_tokens": 6})
                assert event == "done"
                np.testing.assert_array_equal(
                    np.concatenate([pb, np.asarray(toks, np.int32)]),
                    want)
                # the stalled stream hits the write deadline → cancelled
                _wait(lambda: fe.live_requests == 0, 30.0,
                      "stalled request isolated")
                assert _counter(
                    "serve.http.write_stall_timeouts_total") >= 1
            finally:
                stalled.close()
            _assert_no_leaks(fe.engine)
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


# ---------------------------------------------------------------------
# idempotent retry / dedup window
# ---------------------------------------------------------------------
def test_retry_attaches_and_replays_committed_prefix(model, aot_dir):
    """A retry with the same request_id after a mid-stream disconnect
    attaches to the LIVE stream: the committed prefix replays from
    index 0 and the stream continues — one engine submission total,
    bit-identical to the uninterrupted run."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        p = _prompt(model, 6)
        ref_eng = _engine(model, aot_dir, max_batch=1)
        rid = ref_eng.add_request(p, 10)
        want = ref_eng.run_to_completion()[rid]

        fe = ServingFrontend(_engine(model, aot_dir))
        with HttpServingServer(fe, heartbeat_s=0.02,
                               retry_grace_s=10.0) as srv:
            payload = {"prompt_ids": p.tolist(), "max_new_tokens": 10,
                       "request_id": "retry-1"}
            first = faults.http_disconnect_mid_stream(
                "127.0.0.1", srv.port, payload, after_tokens=2)
            assert len(first) == 2
            # retry: replays tokens 0..n then continues to done
            toks, event, data = _sse_collect(srv.port, payload)
            assert event == "done"
            np.testing.assert_array_equal(
                np.concatenate([p, np.asarray(toks, np.int32)]), want)
            assert toks[:2] == first          # committed prefix replayed
            assert REGISTRY.get("serve.submitted_total").value == 1
            assert _counter("serve.http.dedup_hits_total") == 1
            _assert_no_leaks(fe.engine)
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_retry_after_finish_replays_terminal_result(model, aot_dir):
    """A duplicate of an already-FINISHED identified request inside the
    dedup window replays the whole stream + terminal result without
    resubmitting."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        p = _prompt(model, 5)
        fe = ServingFrontend(_engine(model, aot_dir))
        with HttpServingServer(fe, dedup_window_s=30.0) as srv:
            payload = {"prompt_ids": p.tolist(), "max_new_tokens": 6,
                       "request_id": "dup-1"}
            toks1, ev1, data1 = _sse_collect(srv.port, payload)
            toks2, ev2, data2 = _sse_collect(srv.port, payload)
            assert ev1 == ev2 == "done"
            assert toks1 == toks2
            assert data1["ids"] == data2["ids"]
            assert REGISTRY.get("serve.submitted_total").value == 1
            assert _counter("serve.http.dedup_hits_total") == 1
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_abandoned_identified_request_cancels_after_grace(model,
                                                          aot_dir):
    """Identified disconnects get a retry grace window; when nothing
    re-attaches, the request cancels (freeing its slot + pages) and is
    counted abandoned."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        eng = _engine(model, aot_dir)
        # slow every decode step so the request is deterministically
        # still running when the grace timer fires
        slow = faults.slow_steps(eng, 0.02, n=10 ** 6)
        slow.__enter__()
        try:
            fe = ServingFrontend(eng)
            with HttpServingServer(fe, heartbeat_s=0.02,
                                   retry_grace_s=0.3) as srv:
                faults.http_disconnect_mid_stream(
                    "127.0.0.1", srv.port,
                    {"prompt_ids": _prompt(model, 5).tolist(),
                     "max_new_tokens": 100, "request_id": "ghost-1"},
                    after_tokens=1)
                # still generating inside the grace window
                time.sleep(0.05)
                assert fe.live_requests == 1
                _wait(lambda: fe.live_requests == 0, 30.0,
                      "grace expiry cancelled the request")
                assert _counter("serve.http.abandoned_total") == 1
                assert _counter("serve.finished_total") == 0
                _assert_no_leaks(fe.engine)
        finally:
            slow.__exit__(None, None, None)
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_retry_flood_single_submission(model, aot_dir):
    """Many concurrent retries of one request_id: exactly one engine
    submission, every reader gets the same bit-identical stream."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        p = _prompt(model, 6)
        fe = ServingFrontend(_engine(model, aot_dir))
        with HttpServingServer(fe, heartbeat_s=0.02) as srv:
            payload = {"prompt_ids": p.tolist(), "max_new_tokens": 8,
                       "request_id": "flood-1"}
            results = []
            lock = threading.Lock()

            def reader():
                r = _sse_collect(srv.port, payload)
                with lock:
                    results.append(r)

            threads = [threading.Thread(target=reader, daemon=True)
                       for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
                assert not t.is_alive()
            assert REGISTRY.get("serve.submitted_total").value == 1
            first = results[0]
            for toks, event, data in results:
                assert event == "done"
                assert toks == first[0]
                assert data["ids"] == first[2]["ids"]
            _assert_no_leaks(fe.engine)
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


# ---------------------------------------------------------------------
# graceful shutdown
# ---------------------------------------------------------------------
def test_graceful_shutdown_drains_under_load(model, aot_dir):
    """SIGTERM semantics: new work gets 503 + Retry-After, /readyz goes
    503, in-flight streams run to completion, and the report is
    zero-leak."""
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        p = _prompt(model, 6)
        ref_eng = _engine(model, aot_dir, max_batch=1)
        rid = ref_eng.add_request(p, 12)
        want = ref_eng.run_to_completion()[rid]

        fe = ServingFrontend(_engine(model, aot_dir))
        srv = HttpServingServer(fe, heartbeat_s=0.02,
                                drain_timeout_s=60.0).start()
        inflight = {}

        def consume():
            inflight["r"] = _sse_collect(
                srv.port, {"prompt_ids": p.tolist(),
                           "max_new_tokens": 12})

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        _wait(lambda: fe.live_requests == 1, 30.0, "stream live")
        report_box = {}

        def shutdown():
            report_box["r"] = srv.begin_shutdown(reason="test-sigterm")

        st = threading.Thread(target=shutdown, daemon=True)
        st.start()
        _wait(lambda: srv.draining, 10.0, "draining flag")
        # new work during the drain: 503 + Retry-After
        conn, resp = _post(srv.port, "/v1/generate",
                           {"prompt_ids": p.tolist(),
                            "max_new_tokens": 4, "stream": False})
        assert resp.status == 503
        assert resp.getheader("Retry-After") is not None
        resp.read()
        conn.close()
        status, body, _ = _get_json(srv.port, "/readyz")
        assert status == 503 and body["reason"] == "draining"
        st.join(timeout=120.0)
        t.join(timeout=120.0)
        assert not st.is_alive() and not t.is_alive()
        report = report_box["r"]
        # the in-flight stream completed through the drain, bit-identical
        toks, event, _ = inflight["r"]
        assert event == "done"
        np.testing.assert_array_equal(
            np.concatenate([p, np.asarray(toks, np.int32)]), want)
        assert report["drained_within_budget"] is True
        assert report["cancelled_at_deadline"] == 0
        assert report["kv_leaked_blocks"] == 0
        hist = REGISTRY.get("serve.http.shutdown_drain_secs")
        assert hist is not None and hist.count == 1
        srv._httpd.server_close()
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


def test_sigterm_triggers_graceful_shutdown(model, aot_dir):
    """The installed SIGTERM handler runs the same drain path (the CLI
    contract: `python -m paddle_tpu.serving.http` exits clean on
    SIGTERM with a zero-leak report)."""
    old_term = signal.getsignal(signal.SIGTERM)
    old_int = signal.getsignal(signal.SIGINT)
    fe = ServingFrontend(_engine(model, aot_dir))
    srv = HttpServingServer(fe, drain_timeout_s=30.0).start()
    try:
        srv.install_signal_handlers()
        toks, event, _ = _sse_collect(
            srv.port, {"prompt_ids": _prompt(model, 5).tolist(),
                       "max_new_tokens": 4})
        assert event == "done"
        signal.raise_signal(signal.SIGTERM)
        assert srv._drain_done.wait(timeout=60.0)
        report = srv._drain_report
        assert report["reason"] == "SIGTERM"
        assert report["kv_leaked_blocks"] == 0
        srv._httpd.server_close()
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)


# ---------------------------------------------------------------------
# loadgen over the wire
# ---------------------------------------------------------------------
def test_loadgen_wire_transport_matches_inprocess_sequence(model,
                                                           aot_dir):
    """ISSUE 13 satellite: the same seed produces the IDENTICAL
    submitted request sequence — content, budgets, sampling, cancel
    plan — over the wire as in-process, so wire chaos numbers are
    comparable to the PR 12 fleet-chaos baselines."""
    lg = LoadGenConfig(
        n_requests=12, rate_rps=200.0, seed=17, prompt_len=(3, 8),
        max_new_tokens=(3, 6), sampled_fraction=0.3,
        cancel_fraction=0.25, cancel_after_tokens=1,
        slo_ttft_s=60.0, slo_tpot_s=30.0)

    fe1 = ServingFrontend(_engine(model, aot_dir))
    gen1 = PoissonLoadGenerator(fe1, lg)
    rep1 = gen1.run()
    plan1 = gen1.plan()
    inproc_kwargs = [gen1.request_kwargs(pp) for pp in plan1]

    fe2 = ServingFrontend(_engine(model, aot_dir))
    with HttpServingServer(fe2, heartbeat_s=0.05,
                           retry_grace_s=0.0) as srv:
        tp = HttpTransport("127.0.0.1", srv.port, server=srv)
        gen2 = PoissonLoadGenerator(None, lg, transport=tp)
        rep2 = gen2.run()
        _wait(lambda: fe2.live_requests == 0, 30.0, "wire drained")

        assert len(tp.submitted) == len(inproc_kwargs) == lg.n_requests
        for sub, kw, pp in zip(tp.submitted, inproc_kwargs, plan1):
            assert sub["prompt_ids"] == \
                np.asarray(kw["prompt_ids"]).tolist()
            assert sub["max_new_tokens"] == kw["max_new_tokens"]
            assert sub.get("temperature", 0.0) == kw["temperature"]
            assert sub.get("top_k") == kw["top_k"]
            assert sub.get("seed", 0) == kw["seed"]
        # the cancel plan is part of the sequence contract
        assert [pp.cancel for pp in plan1] == \
            [pp.cancel for pp in gen2.plan()]
        # both runs drain with zero leaks and full terminal accounting
        for rep in (rep1, rep2):
            d = rep.to_dict()
            assert d["kv_leaked_blocks"] == 0
            assert (rep.finished + rep.rejected + rep.cancelled
                    + rep.timed_out) == lg.n_requests
        # every request that FINISHED on both transports emitted the
        # same number of tokens (the engine's per-request determinism
        # observed through the wire)
        for r1, r2 in zip(rep1.per_request, rep2.per_request):
            if r1["state"] == "FINISHED" and r2["state"] == "FINISHED":
                assert r1["n_tokens"] == r2["n_tokens"]
        _assert_no_leaks(fe2.engine)


def test_loadgen_wire_chaos_smoke(model, aot_dir):
    """Seeded wire traffic with mid-stream cancels + a disconnect storm
    riding the same server drains clean — the wire analogue of the
    fleet chaos smoke."""
    fe = ServingFrontend(_engine(model, aot_dir),
                         admission=AdmissionConfig(max_queue_len=64))
    with HttpServingServer(fe, heartbeat_s=0.02,
                           retry_grace_s=0.0) as srv:
        tp = HttpTransport("127.0.0.1", srv.port, server=srv)
        gen = PoissonLoadGenerator(None, LoadGenConfig(
            n_requests=10, rate_rps=300.0, seed=23, prompt_len=(3, 8),
            max_new_tokens=(3, 6), sampled_fraction=0.25,
            cancel_fraction=0.2, cancel_after_tokens=1,
            slo_ttft_s=60.0, slo_tpot_s=30.0), transport=tp)
        storm = threading.Thread(
            target=lambda: [faults.http_disconnect_mid_stream(
                "127.0.0.1", srv.port,
                {"prompt_ids": _prompt(model, 4).tolist(),
                 "max_new_tokens": 80}, after_tokens=1,
                rst=bool(i % 2)) for i in range(4)],
            daemon=True)
        storm.start()
        rep = gen.run()
        storm.join(timeout=60.0)
        _wait(lambda: fe.live_requests == 0, 30.0, "all drained")
        d = rep.to_dict()
        assert d["kv_leaked_blocks"] == 0
        assert rep.finished > 0
        assert (rep.finished + rep.rejected + rep.cancelled
                + rep.timed_out) == 10
        assert fe.engine.active_requests == 0
        assert fe.engine.queue_depth == 0
        _assert_no_leaks(fe.engine)
