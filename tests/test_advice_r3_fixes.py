"""Regressions for the round-3 advisor findings: npair_loss Beta=0.25,
static.nn.layer_norm multi-dim normalized shape, LarsMomentum
multi_precision master weights, matmul SPMD rule with rank-1 operands,
dist.spawn per-rank env."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.nn import functional as F


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def test_npair_loss_beta_quarter():
    # reference loss.py:401-415: l2loss = (mean_a + mean_p) * 0.25 * l2_reg
    rng = np.random.default_rng(0)
    a = rng.standard_normal((4, 8)).astype(np.float32)
    p = rng.standard_normal((4, 8)).astype(np.float32)
    lab = np.array([0, 1, 0, 1], np.int64)
    got = float(F.npair_loss(pt.Tensor(a), pt.Tensor(p), pt.Tensor(lab),
                             l2_reg=0.5))
    # numpy reference
    same = (lab[:, None] == lab[None, :]).astype(np.float32)
    tgt = same / same.sum(1, keepdims=True)
    sim = a @ p.T
    lp = sim - np.log(np.exp(sim).sum(1, keepdims=True))
    ce = np.mean((-tgt * lp).sum(1))
    l2 = (np.mean((a * a).sum(1)) + np.mean((p * p).sum(1))) * 0.25 * 0.5
    assert got == pytest.approx(ce + l2, rel=1e-4)


def test_static_layer_norm_multidim_axis():
    from paddle_tpu import static
    pt.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 3, 4, 5], "float32")
            out = static.nn.layer_norm(x, begin_norm_axis=1)
        exe = static.Executor()
        r = exe.run(prog,
                    feed={"x": np.random.default_rng(1).standard_normal(
                        (2, 3, 4, 5)).astype(np.float32)},
                    fetch_list=[out])
        assert r[0].shape == (2, 3, 4, 5)
        # per-sample normalization over all trailing dims
        flat = r[0].reshape(2, -1)
        np.testing.assert_allclose(flat.mean(1), 0.0, atol=1e-4)
    finally:
        pt.disable_static()


def test_lars_momentum_multi_precision_master_weight():
    from paddle_tpu.optimizer import LarsMomentum
    w = pt.Tensor(np.full((4,), 1.0, np.float16))
    w.stop_gradient = False
    opt = LarsMomentum(learning_rate=0.1, lars_coeff=0.01,
                       parameters=[w], multi_precision=True)
    for _ in range(3):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    name = next(iter(opt._state))
    s = opt._state[name]
    assert "master_weight" in s, "fp32 master must survive the update"
    # master tracks the fp16 param at fp32 precision
    np.testing.assert_allclose(_np(s["master_weight"]),
                               _np(w).astype(np.float32), atol=1e-2)


def test_matmul_rule_rank1_operands():
    from paddle_tpu.parallel.spmd_rules import matmul_rule, TensorDistAttr
    # vec @ mat: contracted axis sharded -> partial output, rank-1 out map
    xr, yr, out = matmul_rule(TensorDistAttr(["mp"]),
                              TensorDistAttr(["mp", None]))
    assert xr.dims_mapping == ["mp"]
    assert len(out.dims_mapping) == 1 and out.partial == {"mp"}
    # mat @ vec
    xr, yr, out = matmul_rule(TensorDistAttr([None, "mp"]),
                              TensorDistAttr(["mp"]))
    assert yr.dims_mapping == ["mp"]
    assert len(out.dims_mapping) == 1 and out.partial == {"mp"}
    # vec @ vec -> scalar (rank-0) mapping
    xr, yr, out = matmul_rule(TensorDistAttr(["mp"]),
                              TensorDistAttr(["mp"]))
    assert out.dims_mapping == [] and out.partial == {"mp"}


def _spawn_probe(path):
    import os
    with open(os.path.join(path,
                           f"rank{os.environ['PADDLE_TRAINER_ID']}"),
              "w") as f:
        f.write(os.environ["PADDLE_TRAINERS_NUM"])


def test_spawn_sets_rank_env(tmp_path):
    import paddle_tpu.distributed as dist
    dist.spawn(_spawn_probe, args=(str(tmp_path),), nprocs=2)
    assert sorted(p.name for p in tmp_path.iterdir()) == ["rank0", "rank1"]
    assert (tmp_path / "rank0").read_text() == "2"
