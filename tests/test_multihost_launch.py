"""Multi-host launcher + elastic (VERDICT r3 item 4).

Reference pattern: test_dist_base.py:952 — multi-host simulated as
multi-process controllers on one machine.  Each "host" is a
``paddle_tpu.distributed.launch`` PodController process; the rank-0 host
serves the rendezvous KV; workers are plain python scripts that record
their env (no jax needed — the launcher contract is env + process
management)."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


WORKER_OK = """
import json, os, sys, time
out = sys.argv[1]
rec = {k: os.environ.get(k) for k in (
    "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "PADDLE_NODE_RANK",
    "PADDLE_NNODES", "PADDLE_LOCAL_RANK", "PADDLE_JOB_EPOCH",
    "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")}
time.sleep(0.5)
with open(os.path.join(
        out, f"w{rec['PADDLE_JOB_EPOCH']}_{rec['PADDLE_TRAINER_ID']}.json"
        ), "w") as f:
    json.dump(rec, f)
"""

WORKER_FAIL_ONCE = WORKER_OK + """
# rank 3 dies in epoch 0 only — the restart must succeed in epoch 1
if rec["PADDLE_TRAINER_ID"] == "3" and rec["PADDLE_JOB_EPOCH"] == "0":
    sys.exit(17)
"""


def _launch_host(master, nnodes, nproc, script, out_dir, max_restart=0,
                 rank=None):
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--master", master, "--nnodes", str(nnodes),
           "--nproc_per_node", str(nproc),
           "--max_restart", str(max_restart),
           "--heartbeat_ttl", "3", "--rdzv_timeout", "60",
           script, out_dir]
    if rank is not None:
        cmd[5:5] = ["--rank", str(rank)]
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _write_script(tmp_path, body):
    p = tmp_path / "worker.py"
    p.write_text(body)
    return str(p)


class TestTwoHostLaunch:
    def test_2host_x_2proc_rendezvous(self, tmp_path):
        master = f"127.0.0.1:{_free_port()}"
        script = _write_script(tmp_path, WORKER_OK)
        out = tmp_path / "out"
        out.mkdir()
        hosts = [_launch_host(master, 2, 2, script, str(out))
                 for _ in range(2)]
        codes = [h.wait(timeout=90) for h in hosts]
        logs = [h.stdout.read() for h in hosts]
        assert codes == [0, 0], logs
        recs = sorted(out.glob("w0_*.json"))
        assert len(recs) == 4, (list(out.iterdir()), logs)
        seen = {}
        for r in recs:
            d = json.loads(r.read_text())
            seen[d["PADDLE_TRAINER_ID"]] = d
        # dense global ranks 0..3, world 4, two nodes x two locals
        assert sorted(seen) == ["0", "1", "2", "3"]
        assert all(d["PADDLE_TRAINERS_NUM"] == "4" for d in seen.values())
        assert all(d["JAX_NUM_PROCESSES"] == "4" for d in seen.values())
        assert {d["PADDLE_NODE_RANK"] for d in seen.values()} == \
            {"0", "1"}
        assert all(d["JAX_COORDINATOR_ADDRESS"] for d in seen.values())

    def test_failure_restart_recovers(self, tmp_path):
        master = f"127.0.0.1:{_free_port()}"
        script = _write_script(tmp_path, WORKER_FAIL_ONCE)
        out = tmp_path / "out"
        out.mkdir()
        hosts = [_launch_host(master, 2, 2, script, str(out),
                              max_restart=2) for _ in range(2)]
        codes = [h.wait(timeout=120) for h in hosts]
        logs = [h.stdout.read() for h in hosts]
        assert codes == [0, 0], logs
        # epoch 1 completed on all four ranks after the epoch-0 failure
        recs1 = sorted(out.glob("w1_*.json"))
        assert len(recs1) == 4, (list(out.iterdir()), logs)
        assert any("restart" in lg for lg in logs), logs

    def test_elastic_range_runs_with_min_hosts(self, tmp_path):
        # --nnodes 1:2 with only ONE host present: settles at 1 node
        master = f"127.0.0.1:{_free_port()}"
        script = _write_script(tmp_path, WORKER_OK)
        out = tmp_path / "out"
        out.mkdir()
        h = _launch_host(master, "1:2", 2, script, str(out))
        code = h.wait(timeout=90)
        assert code == 0, h.stdout.read()
        recs = sorted(out.glob("w0_*.json"))
        assert len(recs) == 2
        d = json.loads(recs[0].read_text())
        assert d["PADDLE_TRAINERS_NUM"] == "2"


class TestKVStore:
    def test_kv_ops(self):
        from paddle_tpu.distributed.launch.kv import (KVClient,
                                                      start_server)
        srv = start_server()
        kv = KVClient(f"127.0.0.1:{srv.port}")
        kv.set("a", {"x": 1})
        assert kv.get("a") == {"x": 1}
        assert kv.add("ctr") == 1 and kv.add("ctr") == 2
        assert kv.cas("epoch", None, 1) is True
        assert kv.cas("epoch", 0, 2) is False
        assert kv.cas("epoch", 1, 2) is True
        kv.set("lease/x", 1, ttl=0.3)
        assert "lease/x" in kv.list("lease/")
        time.sleep(0.4)
        assert "lease/x" not in kv.list("lease/")
        kv.close()
        srv.shutdown()

    def test_kv_lease_store(self):
        from paddle_tpu.distributed.elastic import KVLeaseStore
        from paddle_tpu.distributed.launch.kv import start_server
        srv = start_server()
        st = KVLeaseStore(f"127.0.0.1:{srv.port}", ttl=0.4)
        st.register("hostA")
        st.register("hostB")
        assert st.hosts() == ["hostA", "hostB"]
        time.sleep(0.5)
        st.register("hostA")            # only A renews its lease
        assert st.hosts() == ["hostA"]
        st.deregister("hostA")
        assert st.hosts() == []
        srv.shutdown()
