"""distributed.rpc (reference python/paddle/distributed/rpc — brpc agent
replaced with a socket agent; test model test/rpc/test_rpc_base.py)."""

import multiprocessing as mp
import os
import socket
import time

import numpy as np
import pytest

from paddle_tpu.distributed import rpc


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _square(x):
    return x * x


def _boom():
    raise ValueError("remote failure")


def _worker1(ep, q):
    try:
        rpc.init_rpc("worker1", rank=1, world_size=2, master_endpoint=ep)
        # worker1 calls back into worker0
        got = rpc.rpc_sync("worker0", _square, args=(7,))
        q.put(("ok", got))
        time.sleep(1.0)       # stay alive to serve worker0's calls
        rpc.shutdown()
    except Exception as e:
        q.put(("err", repr(e)))


class TestRpc:
    def test_two_worker_round_trip(self):
        ep = f"127.0.0.1:{_free_port()}"
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_worker1, args=(ep, q))
        p.start()
        try:
            rpc.init_rpc("worker0", rank=0, world_size=2,
                         master_endpoint=ep)
            infos = rpc.get_all_worker_infos()
            assert [w.name for w in infos] == ["worker0", "worker1"]
            # worker0 -> worker1 call
            out = rpc.rpc_sync("worker1", _square, args=(5,))
            assert out == 25
            fut = rpc.rpc_async("worker1", _square, args=(np.arange(3),))
            np.testing.assert_array_equal(fut.result(60), [0, 1, 4])
            # and worker1's call into us completed
            status, got = q.get(timeout=60)
            assert status == "ok" and got == 49
        finally:
            rpc.shutdown()
            p.join(timeout=30)
            if p.is_alive():
                p.kill()

    def test_remote_exception_propagates(self):
        ep = f"127.0.0.1:{_free_port()}"
        rpc.init_rpc("solo", rank=0, world_size=1, master_endpoint=ep)
        try:
            # like the reference, callables must be importable (pickled)
            with pytest.raises(ValueError, match="remote failure"):
                rpc.rpc_sync("solo", _boom)
        finally:
            rpc.shutdown()
