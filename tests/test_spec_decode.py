"""Speculative decoding subsystem (ISSUE 8): losslessness, rollback
accounting, AOT warm start, and serve-loop integration.

The load-bearing contracts (tier-1):

* greedy speculative decode emits BIT-IDENTICAL token streams to
  baseline greedy decode — through the engine batch API and through
  ``ServingFrontend`` (same seeds), for a good draft AND an adversarial
  one (the draft moves speed, never outputs);
* sampled speculative decode preserves the target distribution exactly
  (the rejection-sampling identity, pinned on the pure chain);
* rollback never moves the refcount pool: ``kv_leak_report`` is zero
  after rollback-heavy runs, including cancels mid-speculation;
* an AOT warm start of a speculating engine performs ZERO backend
  compiles and reproduces fresh-compile tokens bit-for-bit.
"""

import jax
import numpy as np
import pytest

from paddle_tpu import parallel as dist
from paddle_tpu.inference.serving import ContinuousBatchingEngine
from paddle_tpu.models.llama import build_llama_train_step, llama_tiny
from paddle_tpu.observability import CompileMonitor, REGISTRY
from paddle_tpu.parallel.topology import HybridTopology, set_topology
from paddle_tpu.serving import RequestState, ServingFrontend
from paddle_tpu.spec_decode import (SpecDecodeConfig, spec_sample_chain,
                                    warp_probs)
from paddle_tpu.spec_decode.sampling import position_rng

rng = np.random.default_rng(0)


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny()
    topo = dist.init_topology(devices=jax.devices()[:1])
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    # an ADVERSARIAL draft: same architecture, independent random init —
    # its proposals almost never match the target, so every accept-path
    # corner (0 accepted, corrections, full rollback) gets exercised
    _, init2 = build_llama_train_step(cfg, topo, num_microbatches=1)
    weak_draft = init2(1)["params"]
    set_topology(HybridTopology())
    return cfg, params, weak_draft


def _spec_cfg(model, self_draft=True, **kw):
    cfg, params, weak = model
    kw.setdefault("k", 3)
    kw.setdefault("window", 12)
    return SpecDecodeConfig(draft_cfg=cfg,
                            draft_params=params if self_draft else weak,
                            **kw)


def _engine(model, spec=None, **kw):
    cfg, params, _ = model
    kw.setdefault("max_batch", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    return ContinuousBatchingEngine(cfg, params, spec_config=spec, **kw)


def _prompts(model, ns=(5, 9, 3)):
    return [rng.integers(0, model[0].vocab_size, (n,)).astype(np.int32)
            for n in ns]


def _no_leaks(eng):
    rep = eng.kv_leak_report()
    assert rep["leaked"] == 0 and rep["unaccounted"] == 0, rep


# ---------------------------------------------------------------------
# losslessness: greedy is bit-identical
# ---------------------------------------------------------------------
@pytest.mark.parametrize("self_draft", [True, False])
def test_greedy_spec_bit_identical_to_baseline(model, self_draft):
    """The pinned contract, engine level: same token arrays with and
    without speculation, whether the draft is good (self-draft, high
    acceptance) or adversarial (random init, ~zero acceptance)."""
    prompts = _prompts(model)
    base_eng = _engine(model)
    rids = [base_eng.add_request(p, 6) for p in prompts]
    base = base_eng.run_to_completion()

    eng = _engine(model, spec=_spec_cfg(model, self_draft))
    rids2 = [eng.add_request(p, 6) for p in prompts]
    got = eng.run_to_completion()
    for r1, r2 in zip(rids, rids2):
        np.testing.assert_array_equal(base[r1], got[r2])
    stats = eng.spec_stats()
    assert stats["spec_steps"] > 0
    if self_draft:
        assert stats["acceptance_rate"] > 0.0
        assert stats["engine_steps_per_token"] < 1.0
    else:
        # baseline-equivalent cost, still correct
        assert stats["engine_steps_per_token"] == 1.0
    _no_leaks(eng)


def test_greedy_spec_bit_identical_through_frontend(model):
    """The ISSUE 8 pinned acceptance test: greedy streams through
    ``ServingFrontend`` are bit-identical with speculation on vs off,
    token by token (not just the final arrays), with eos cut-off
    honored mid-speculation."""
    prompts = _prompts(model, ns=(5, 9, 3, 7))
    fe_off = ServingFrontend(_engine(model))
    off = [list(fe_off.submit(p, 8)) for p in prompts]
    # pick an eos that actually appears mid-stream for one request, so
    # the spec commit loop's early stop is exercised against baseline
    eos = off[0][3]
    fe_off2 = ServingFrontend(_engine(model))
    off_eos = list(fe_off2.submit(prompts[0], 8, eos_token_id=eos))

    fe_on = ServingFrontend(_engine(model, spec=_spec_cfg(model)))
    on = [list(fe_on.submit(p, 8)) for p in prompts]
    assert on == off
    fe_on2 = ServingFrontend(_engine(model, spec=_spec_cfg(model)))
    on_eos = list(fe_on2.submit(prompts[0], 8, eos_token_id=eos))
    assert on_eos == off_eos
    assert on_eos[-1] == eos and eos not in on_eos[:-1]
    for fe in (fe_on, fe_on2):
        assert fe.engine.spec_stats()["spec_steps"] > 0
        _no_leaks(fe.engine)


def test_sampled_spec_matches_request_law_and_is_deterministic(model):
    """Sampled spec decode: per-request determinism by seed, divergence
    across seeds, and independence from batch composition (the engine's
    standing guarantee, now through the spec path)."""
    cfg, params, _ = model
    prompt = _prompts(model, ns=(6,))[0]

    def run(batchmates, seed):
        eng = _engine(model, spec=_spec_cfg(model))
        rid = eng.add_request(prompt, 6, temperature=0.8, top_k=20,
                              seed=seed)
        for bp in batchmates:
            eng.add_request(bp, 4)
        out = eng.run_to_completion()[rid]
        _no_leaks(eng)
        return out

    solo = run([], seed=7)
    np.testing.assert_array_equal(solo, run([], seed=7))
    mate = _prompts(model, ns=(9,))[0]
    np.testing.assert_array_equal(solo, run([mate], seed=7))
    assert not np.array_equal(solo, run([], seed=8))


# ---------------------------------------------------------------------
# rejection-sampling identity (the sampled-losslessness pin)
# ---------------------------------------------------------------------
def test_rejection_sampling_identity_one_hot_draft():
    """Greedy-draft (one-hot q) chain: the emitted first token follows
    EXACTLY the target law p, however wrong the proposal is.  This is
    the distribution-level half of the pinned acceptance criterion."""
    p = np.array([0.5, 0.25, 0.125, 0.0625, 0.0625])
    proposal = 3                       # a LOW-probability proposal
    counts = np.zeros(5)
    n = 20000
    for seed in range(n):
        emitted, _ = spec_sample_chain([p, p], [proposal], seed=seed,
                                       start_position=11)
        counts[emitted[0]] += 1
    tv = 0.5 * np.abs(counts / n - p).sum()
    assert tv < 0.02, (tv, counts / n)


def test_rejection_sampling_identity_full_q():
    """General-q rejection (the textbook identity): accept w.p.
    min(1, p/q), residual norm(max(p-q, 0)) — still exactly p."""
    p = np.array([0.1, 0.6, 0.1, 0.2])
    q = np.array([0.7, 0.1, 0.1, 0.1])   # badly mismatched draft law
    counts = np.zeros(4)
    n = 20000
    for seed in range(n):
        rg = position_rng(seed, 0)
        x = int(rg.choice(4, p=q))       # proposal ~ q
        emitted, _ = spec_sample_chain([p, p], [x], q_dists=[q],
                                       seed=seed, start_position=5)
        counts[emitted[0]] += 1
    tv = 0.5 * np.abs(counts / n - p).sum()
    assert tv < 0.02, (tv, counts / n)


def test_chain_acceptance_and_bonus_semantics():
    """Deterministic corners: a proposal with p(x)=1 always accepts and
    the bonus draws from the K+1-th dist; p(x)=0 always rejects with a
    residual that masks the proposal out."""
    sure = np.array([0.0, 1.0, 0.0])
    emitted, accepted = spec_sample_chain([sure, sure], [1], seed=3)
    assert accepted == 1 and emitted == [1, 1]
    p = np.array([0.5, 0.0, 0.5])
    for seed in range(32):
        emitted, accepted = spec_sample_chain([p, p], [1], seed=seed)
        assert accepted == 0 and len(emitted) == 1
        assert emitted[0] in (0, 2)      # residual masked the proposal


def test_warp_probs_matches_sampler_semantics():
    """warp_probs mirrors build_sampler's HF sequential-warper filters
    (the regression cases pinned on the jax sampler in
    test_serving_engine.py, replayed on the host law)."""
    logits = np.full((32,), -10.0, np.float32)
    logits[5], logits[9] = 4.0, 3.9
    p = warp_probs(logits, 1.0, 2, None)
    assert set(np.nonzero(p)[0]) == {5, 9}
    np.testing.assert_allclose(p.sum(), 1.0, rtol=1e-12)
    # sequential semantics: top-p over the top-k-FILTERED mass
    logits = np.zeros((32,), np.float32)
    logits[5], logits[9] = 8.0, 4.0
    p = warp_probs(logits, 1.0, 2, 0.95)
    assert set(np.nonzero(p)[0]) == {5}
    # temperature-only: plain softmax
    p = warp_probs(np.array([0.0, np.log(3.0)]), 1.0, None, None)
    np.testing.assert_allclose(p, [0.25, 0.75], atol=1e-12)


# ---------------------------------------------------------------------
# rollback + pool accounting (engine hardening)
# ---------------------------------------------------------------------
def test_cancel_mid_speculation_no_leak(model):
    """ISSUE 8 hardening: cancelling mid-speculation (the slot's KV
    already contains rolled-back tail writes) releases every page
    exactly once and the batchmate's stream is unaffected."""
    prompts = _prompts(model, ns=(5, 9))
    base_eng = _engine(model, max_batch=1)
    rid = base_eng.add_request(prompts[1], 8)
    want = base_eng.run_to_completion()[rid]

    eng = _engine(model, spec=_spec_cfg(model))
    a = eng.add_request(prompts[0], 40)
    b = eng.add_request(prompts[1], 8)
    eng.step()
    eng.step()
    assert eng.spec_stats()["spec_steps"] >= 1
    assert eng.cancel(a)                   # mid-speculation cancel
    _no_leaks(eng)
    out = eng.run_to_completion()
    np.testing.assert_array_equal(out[b], want)
    _no_leaks(eng)
    assert eng.alloc.free_blocks + len(eng.prefix_index) \
        == eng.alloc.num_blocks


def test_rollback_heavy_run_with_cancels_drains_clean(model):
    """The adversarial draft rejects nearly everything — every step is
    rollback-heavy — while cancels land mid-stream; after drain the
    refcount pool cross-check must be exactly clean."""
    from paddle_tpu.serving import LoadGenConfig, PoissonLoadGenerator
    eng = _engine(model, spec=_spec_cfg(model, self_draft=False),
                  num_blocks=48)
    fe = ServingFrontend(eng)
    rep = PoissonLoadGenerator(fe, LoadGenConfig(
        n_requests=12, rate_rps=300.0, seed=5, prompt_len=(3, 10),
        max_new_tokens=(3, 8), sampled_fraction=0.25,
        cancel_fraction=0.3, cancel_after_tokens=1,
        slo_ttft_s=60.0, slo_tpot_s=30.0)).run()
    assert rep.cancelled > 0 and rep.finished > 0
    assert rep.kv_leaks["leaked"] == 0
    assert rep.kv_leaks["unaccounted"] == 0
    stats = eng.spec_stats()
    assert stats["rollback_pages"] > 0     # speculation actually rolled back
    _no_leaks(eng)


def test_spec_disabled_knob_runs_baseline_path(model):
    """enabled=False is the incident rollback switch: construction
    succeeds, decode takes the baseline branch, stats say so."""
    prompts = _prompts(model)
    eng = _engine(model, spec=_spec_cfg(model, enabled=False))
    rids = [eng.add_request(p, 5) for p in prompts]
    got = eng.run_to_completion()
    base = _engine(model)
    rids2 = [base.add_request(p, 5) for p in prompts]
    want = base.run_to_completion()
    for r1, r2 in zip(rids, rids2):
        np.testing.assert_array_equal(want[r2], got[r1])
    stats = eng.spec_stats()
    assert stats["enabled"] is False and stats["spec_steps"] == 0
    assert stats["engine_steps_per_token"] == 1.0


def test_spec_config_validation(model):
    cfg, params, _ = model
    import dataclasses
    bad_vocab = dataclasses.replace(cfg, vocab_size=cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="vocab"):
        _engine(model, spec=SpecDecodeConfig(draft_cfg=bad_vocab,
                                             draft_params=params))
    bad_pos = dataclasses.replace(
        cfg, max_position_embeddings=cfg.max_position_embeddings // 2)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        _engine(model, spec=SpecDecodeConfig(draft_cfg=bad_pos,
                                             draft_params=params))
    with pytest.raises(ValueError, match="k must be"):
        SpecDecodeConfig(draft_cfg=cfg, draft_params=params, k=0)


# ---------------------------------------------------------------------
# serve telemetry
# ---------------------------------------------------------------------
def test_spec_metrics_reach_registry(model):
    REGISTRY.reset()
    REGISTRY.enable()
    try:
        fe = ServingFrontend(_engine(model, spec=_spec_cfg(model)))
        h = fe.submit(_prompts(model, ns=(5,))[0], 6)
        fe.run_until_drained(timeout_s=120)
        assert h.state is RequestState.FINISHED
        assert REGISTRY.get("serve.spec.steps_total").value >= 1
        assert REGISTRY.get("serve.spec.proposed_total").value >= 3
        acc = REGISTRY.get("serve.spec.acceptance_rate")
        spt = REGISTRY.get("serve.spec.steps_per_token")
        assert acc is not None and 0.0 <= acc.value <= 1.0
        assert spt is not None and 0.0 < spt.value <= 1.0
        assert REGISTRY.get("serve.spec.accepted_per_step").count >= 1
    finally:
        REGISTRY.disable()
        REGISTRY.reset()


# ---------------------------------------------------------------------
# AOT warm start (zero compiles, bit-identical)
# ---------------------------------------------------------------------
def test_spec_aot_warm_start_zero_compiles_bit_identical(model, tmp_path):
    from paddle_tpu.aot import export_engine
    prompts = _prompts(model)

    def mk(aot_dir=None):
        return _engine(model, spec=_spec_cfg(model),
                       prefill_buckets=(8,), aot_dir=aot_dir)

    aot_dir = str(tmp_path / "spec_aot")
    export_engine(mk(), aot_dir)

    fresh = mk()
    rids = [fresh.add_request(p, 6) for p in prompts]
    want = fresh.run_to_completion()

    monitor = CompileMonitor().install()
    try:
        warm = mk(aot_dir=aot_dir)
        rids2 = [warm.add_request(p, 6) for p in prompts]
        got = warm.run_to_completion()
    finally:
        monitor.uninstall()
    assert warm.aot_loaded, warm.aot_error
    assert monitor.n_compiles == 0, monitor.n_compiles
    for r1, r2 in zip(rids, rids2):
        np.testing.assert_array_equal(want[r1], got[r2])
    assert warm.spec_stats()["spec_steps"] > 0


def test_spec_engine_rejects_prespec_artifacts(model, tmp_path):
    """An artifact dir exported WITHOUT speculation must be a clean
    config-mismatch fallback for a speculating engine — never a
    half-warm start missing the draft/verify programs."""
    from paddle_tpu.aot import export_engine
    aot_dir = str(tmp_path / "nospec_aot")
    export_engine(_engine(model, prefill_buckets=(8,)), aot_dir)
    eng = _engine(model, spec=_spec_cfg(model), prefill_buckets=(8,),
                  aot_dir=aot_dir)
    assert not eng.aot_loaded
    assert eng.aot_error is not None
    # ... and it still serves correctly via fresh compiles
    p = _prompts(model, ns=(5,))[0]
    rid = eng.add_request(p, 4)
    out = eng.run_to_completion()[rid]
    base = _engine(model, prefill_buckets=(8,))
    rid2 = base.add_request(p, 4)
    np.testing.assert_array_equal(base.run_to_completion()[rid2], out)
