"""Runtime telemetry subsystem tests (ISSUE 5): registry thread-safety,
bounded histogram reservoirs, disabled-mode overhead, compile-counter
behaviour across a forced recompile, flight-recorder dump on an injected
``NonFiniteError``, and the end-to-end ``Model.fit(observe=True)``
acceptance path (JSONL stream with step / loss / tokens-per-second /
compile / checkpoint entries)."""

import glob
import json
import os
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.amp import GradScaler
from paddle_tpu.checkpoint import (AsyncCheckpointer, CheckpointManager,
                                   NonFiniteError, StepGuard)
from paddle_tpu.io.dataset import TensorDataset
from paddle_tpu.observability import (REGISTRY, CompileMonitor,
                                      FlightRecorder, JsonlSink,
                                      MemorySink, MetricsRegistry,
                                      TelemetrySession, estimate_mfu,
                                      peak_flops_per_chip,
                                      write_prometheus)


@pytest.fixture(autouse=True, scope="module")
def _no_persistent_compile_cache():
    """Same opt-out as test_fault_tolerance.py: this jax/XLA:CPU build
    mis-executes DONATED programs deserialized from the persistent
    compilation cache (Model's jitted step donates), and cached
    executables would also make the compile-counter assertions depend
    on warm-cache state."""
    import jax

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    jax.clear_caches()
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


@pytest.fixture(autouse=True)
def _default_registry_isolation():
    """The process-wide REGISTRY must come out of every test the way it
    went in: disabled and sink-free (instrument definitions may
    accumulate — they are keyed and idempotent)."""
    yield
    REGISTRY.disable()
    for s in REGISTRY.sinks:
        REGISTRY.remove_sink(s)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_counter_get_or_create_and_inc(self):
        reg = MetricsRegistry(enabled=True)
        c = reg.counter("a.total")
        assert reg.counter("a.total") is c
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5

    def test_kind_clash_raises(self):
        reg = MetricsRegistry(enabled=True)
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_gauge(self):
        reg = MetricsRegistry(enabled=True)
        g = reg.gauge("depth")
        assert g.value is None
        g.set(7)
        assert g.value == 7

    def test_histogram_stats(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("lat", unit="s")
        for v in [1.0, 2.0, 3.0, 4.0]:
            h.record(v)
        snap = h.snapshot()
        assert snap["count"] == 4 and snap["sum"] == 10.0
        assert snap["min"] == 1.0 and snap["max"] == 4.0
        assert snap["mean"] == 2.5
        assert 1.0 <= snap["p50"] <= 4.0
        assert h.percentile(100) == 4.0

    def test_histogram_reservoir_bounded(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("big", reservoir=16)
        for i in range(10_000):
            h.record(float(i))
        assert h.count == 10_000
        assert h.reservoir_len() <= 16          # memory stays bounded
        assert h.snapshot()["min"] == 0.0       # exact extremes kept
        assert h.snapshot()["max"] == 9999.0

    def test_counter_thread_safety(self):
        reg = MetricsRegistry(enabled=True)
        c = reg.counter("conc")
        n_threads, per_thread = 8, 5000

        def work():
            for _ in range(per_thread):
                c.inc()

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == n_threads * per_thread    # no lost increments

    def test_histogram_thread_safety(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("conc_h", reservoir=32)
        n_threads, per_thread = 8, 2000

        def work(k):
            for i in range(per_thread):
                h.record(float(k * per_thread + i))

        ts = [threading.Thread(target=work, args=(k,))
              for k in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count == n_threads * per_thread
        assert h.reservoir_len() <= 32

    def test_event_fanout_and_sink_management(self):
        reg = MetricsRegistry(enabled=True)
        a, b = MemorySink(), MemorySink()
        reg.add_sink(a)
        reg.add_sink(b)
        reg.event("step", step=1)
        reg.remove_sink(b)
        reg.event("step", step=2)
        assert [r["step"] for r in a.records] == [1, 2]
        assert [r["step"] for r in b.records] == [1]
        assert all("ts" in r for r in a.records)

    def test_prometheus_text(self, tmp_path):
        reg = MetricsRegistry(enabled=True)
        reg.counter("train.steps_total").inc(5)
        reg.gauge("io.queue_depth").set(2)
        reg.histogram("step_secs").record(0.25)
        text = reg.prometheus_text()
        assert "# TYPE paddle_tpu_train_steps_total counter" in text
        assert "paddle_tpu_train_steps_total 5" in text
        assert "paddle_tpu_io_queue_depth 2" in text
        assert 'paddle_tpu_step_secs{quantile="0.5"} 0.25' in text
        assert "paddle_tpu_step_secs_count 1" in text
        path = write_prometheus(reg, str(tmp_path / "deep" / "m.prom"))
        assert open(path).read() == text

    def test_jsonl_sink(self, tmp_path):
        reg = MetricsRegistry(enabled=True)
        sink = JsonlSink(str(tmp_path / "nested" / "m.jsonl"))
        reg.add_sink(sink)
        reg.event("step", step=1, loss=np.float32(0.5))  # numpy coerced
        sink.close()
        recs = [json.loads(ln) for ln in open(sink.path)]
        assert recs[0]["step"] == 1 and recs[0]["loss"] == 0.5


class TestDisabledOverhead:
    def test_disabled_records_nothing(self):
        reg = MetricsRegistry(enabled=False)
        sink = MemorySink()
        reg.add_sink(sink)
        c, g, h = reg.counter("c"), reg.gauge("g"), reg.histogram("h")
        c.inc()
        g.set(1.0)
        h.record(1.0)
        reg.event("step", step=1)
        assert c.value == 0 and g.value is None and h.count == 0
        assert sink.records == []

    def test_disabled_step_path_allocates_nothing(self):
        """The acceptance bar: disabled mode adds no per-step work —
        in particular no net allocations on the hot path."""
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("c")
        h = reg.histogram("h")
        for _ in range(32):                     # warm caches
            c.inc()
            h.record(1.0)
            reg.event("step", step=1)
        import gc
        gc.collect()
        before = sys.getallocatedblocks()
        for _ in range(2000):
            c.inc()
            h.record(1.0)
            reg.event("step", step=1)
        delta = sys.getallocatedblocks() - before
        assert delta <= 8, f"disabled telemetry leaked {delta} blocks"

    def test_model_has_no_telemetry_handle_by_default(self):
        m = pt.Model(nn.Linear(4, 2))
        assert m._telemetry is None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_ring_bounded_and_ordered(self):
        fr = FlightRecorder(capacity=8)
        for i in range(20):
            fr.record("step", step=i)
        assert len(fr) == 8
        assert [r["step"] for r in fr.last()] == list(range(12, 20))
        assert [r["step"] for r in fr.last(2)] == [18, 19]

    def test_dump_format_and_parent_dirs(self, tmp_path):
        reg = MetricsRegistry(enabled=True)
        reg.counter("train.steps_total").inc(3)
        fr = FlightRecorder(capacity=4, registry=reg)
        for i in range(6):
            fr.record("step", step=i, loss=0.1 * i)
        path = str(tmp_path / "a" / "b" / "dump.json")
        assert fr.dump("NonFiniteError: test", path=path) == path
        blob = json.load(open(path))
        assert blob["version"] == 1
        assert blob["reason"].startswith("NonFiniteError")
        assert blob["n_records"] == 4
        assert blob["records"][-1]["step"] == 5
        assert blob["metrics"]["train.steps_total"]["value"] == 3

    def test_dump_dedup(self, tmp_path):
        fr = FlightRecorder(capacity=4, directory=str(tmp_path))
        fr.record("step", step=1)
        key = id(object())
        assert fr.dump("first", dedup_key=key) is not None
        assert fr.dump("again", dedup_key=key) is None
        assert len(fr.dumps) == 1

    def test_dump_without_directory_is_noop(self):
        fr = FlightRecorder(capacity=4)
        fr.record("x")
        assert fr.dump("nowhere") is None

    def test_excepthook_chain_restores(self):
        fr = FlightRecorder(capacity=4)
        prev = sys.excepthook
        fr.install_excepthook()
        assert sys.excepthook is not prev
        fr.install_excepthook()                 # idempotent
        fr.uninstall_excepthook()
        assert sys.excepthook is prev


# ---------------------------------------------------------------------------
# compile monitor
# ---------------------------------------------------------------------------
class TestCompileMonitor:
    def test_counts_forced_recompile(self):
        import jax
        import jax.numpy as jnp

        reg = MetricsRegistry(enabled=True)
        sink = MemorySink()
        reg.add_sink(sink)
        mon = CompileMonitor(reg)
        mon.install()
        try:
            @jax.jit
            def f(x):
                return x * 3.0 + 1.0

            # build inputs OUTSIDE the label: jnp.ones is itself a
            # jitted computation and would be attributed to "f"
            x4 = jax.device_put(np.ones((4,), np.float32))
            x5 = jax.device_put(np.ones((5,), np.float32))
            with mon.label("f"):
                f(x4).block_until_ready()
            n1 = mon.per_label["f"]["compiles"]
            assert n1 >= 1
            with mon.label("f"):
                f(x4).block_until_ready()       # cached: no compile
            assert mon.per_label["f"]["compiles"] == n1
            with mon.label("f"):
                # new shape forces retrace + recompile
                f(x5).block_until_ready()
            n2 = mon.per_label["f"]["compiles"]
            assert n2 > n1
            assert mon.recompiles("f") == n2 - 1
            assert mon.compile_secs > 0
            assert mon.summary()["n_compiles"] >= 2
        finally:
            mon.uninstall()
        # registry got the same story
        assert reg.counter("jax.compile_total").value >= 2
        phases = {r["phase"] for r in sink.by_kind("compile")}
        assert {"trace", "lower", "compile"} <= phases
        assert any(r["fn"] == "f" for r in sink.by_kind("compile"))

    def test_uninstall_stops_counting(self):
        import jax
        import jax.numpy as jnp

        mon = CompileMonitor()
        mon.install()
        mon.uninstall()
        n0 = mon.n_compiles

        @jax.jit
        def g(x):
            return x - 2.0

        g(jnp.ones((3,))).block_until_ready()
        assert mon.n_compiles == n0


# ---------------------------------------------------------------------------
# step guard telemetry
# ---------------------------------------------------------------------------
class TestStepGuardMetrics:
    def test_skip_and_backoff_counted(self):
        reg = MetricsRegistry(enabled=True)
        sink = MemorySink()
        reg.add_sink(sink)
        scaler = GradScaler(init_loss_scaling=1024.0)
        guard = StepGuard(max_consecutive=10, scaler=scaler, metrics=reg)

        guard.record(True, step=5, loss=float("nan"))
        guard.record(True, step=6, loss=float("inf"))
        guard.record(False, step=7, loss=0.5)

        assert reg.counter("train.skipped_steps_total").value == 2
        assert reg.counter("train.scale_backoff_total").value == 2
        assert guard.total_backoffs == 2
        assert scaler.get_loss_scaling() == 256.0   # 1024 * 0.5 * 0.5
        skips = sink.by_kind("step_skip")
        assert [r["step"] for r in skips] == [5, 6]
        assert skips[-1]["consecutive"] == 2
        backoffs = sink.by_kind("scale_backoff")
        assert backoffs[0]["scale_before"] == 1024.0
        assert backoffs[0]["scale"] == 512.0
        assert reg.gauge("train.consecutive_skips").value == 2

    def test_terminal_raise_still_counts(self):
        reg = MetricsRegistry(enabled=True)
        guard = StepGuard(max_consecutive=2, metrics=reg)
        guard.record(True)
        with pytest.raises(NonFiniteError):
            guard.record(True)
        assert reg.counter("train.skipped_steps_total").value == 2

    def test_metrics_off_is_noop(self):
        guard = StepGuard(max_consecutive=10)
        guard.record(True)
        assert guard.total_skipped == 1         # accounting unaffected


# ---------------------------------------------------------------------------
# checkpoint telemetry
# ---------------------------------------------------------------------------
class TestCheckpointTelemetry:
    def _state(self):
        return {"w": pt.Tensor(np.arange(8.0, dtype=np.float32))}

    def test_manager_save_emits_latency(self, tmp_path):
        sink = MemorySink()
        REGISTRY.add_sink(sink)
        REGISTRY.enable()
        try:
            mgr = CheckpointManager(str(tmp_path), keep_last=2)
            mgr.save(self._state(), 7)
        finally:
            REGISTRY.disable()
            REGISTRY.remove_sink(sink)
        recs = sink.by_kind("checkpoint")
        assert len(recs) == 1
        r = recs[0]
        assert r["phase"] == "save" and r["step"] == 7
        assert r["save_secs"] >= 0 and r["verify_secs"] >= 0
        assert r["bytes"] > 0
        assert REGISTRY.histogram("checkpoint.save_secs").count >= 1

    def test_async_checkpointer_queue_metrics(self, tmp_path):
        sink = MemorySink()
        REGISTRY.add_sink(sink)
        REGISTRY.enable()
        try:
            ck = AsyncCheckpointer(CheckpointManager(str(tmp_path)))
            ck.save(self._state(), 1)
            assert ck.wait(30.0)
            ck.close()
        finally:
            REGISTRY.disable()
            REGISTRY.remove_sink(sink)
        assert REGISTRY.counter("checkpoint.async_saves_total").value >= 1
        assert REGISTRY.gauge("checkpoint.queue_depth").value == 0
        assert REGISTRY.histogram("checkpoint.snapshot_secs").count >= 1
        assert sink.by_kind("checkpoint")       # writer-thread event


# ---------------------------------------------------------------------------
# Model.fit(observe=True) — the acceptance path
# ---------------------------------------------------------------------------
def _make_model(max_skips=50):
    net = nn.Sequential(nn.Flatten(), nn.Linear(16, 8), nn.ReLU(),
                        nn.Linear(8, 4))
    m = pt.Model(net)
    m.prepare(
        optimizer=pt.optimizer.Adam(1e-2, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        max_consecutive_skips=max_skips)
    return m


def _dataset(n=64, nan_from=None):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 16)).astype(np.float32)
    if nan_from is not None:
        X[nan_from:] = np.nan
    Y = rng.integers(0, 4, size=(n,)).astype(np.int64)
    return TensorDataset([X, Y])


class TestFitTelemetry:
    def test_observe_true_produces_jsonl_stream(self, tmp_path):
        pt.seed(0)
        m = _make_model()
        tele_dir = str(tmp_path / "tele")
        m.fit(_dataset(), batch_size=16, epochs=2, verbose=0,
              shuffle=False, save_dir=str(tmp_path / "ckpt"),
              observe=True, observe_dir=tele_dir)

        recs = [json.loads(ln)
                for ln in open(os.path.join(tele_dir, "metrics.jsonl"))]
        kinds = {r["kind"] for r in recs}
        assert {"session", "step", "compile", "checkpoint"} <= kinds

        steps = [r for r in recs if r["kind"] == "step"]
        assert len(steps) == 8                  # 2 epochs x 4 batches
        assert [r["step"] for r in steps] == list(range(1, 9))
        for r in steps:
            assert np.isfinite(r["loss"])
            assert r["tokens_per_s"] > 0
            assert r["step_secs"] > 0
            assert "mfu" in r and r["skipped"] is False

        compiles = [r for r in recs if r["kind"] == "compile"]
        assert any(r["fn"] == "jit_train_step" for r in compiles)
        assert any(r["phase"] == "compile" for r in compiles)

        ckpts = [r for r in recs if r["kind"] == "checkpoint"]
        assert len(ckpts) == 2                  # one per epoch
        assert all(r["total_secs"] > 0 for r in ckpts)

        # prometheus dump written on close; session left no global state
        assert os.path.exists(os.path.join(tele_dir, "metrics.prom"))
        assert not REGISTRY.enabled
        assert m._telemetry is None

    def test_observe_path_shorthand(self, tmp_path):
        pt.seed(0)
        m = _make_model()
        tele_dir = str(tmp_path / "shorthand")
        m.fit(_dataset(32), batch_size=16, epochs=1, verbose=0,
              observe=tele_dir)
        assert os.path.exists(os.path.join(tele_dir, "metrics.jsonl"))

    def test_flight_dump_on_injected_nonfinite(self, tmp_path):
        """Acceptance: an injected non-finite loss produces a flight-
        recorder dump whose last record matches the failing step."""
        pt.seed(0)
        m = _make_model(max_skips=2)
        tele_dir = str(tmp_path / "tele")
        with pytest.raises(NonFiniteError):
            # first batch clean, every later batch poisoned with NaN
            m.fit(_dataset(64, nan_from=16), batch_size=16, epochs=1,
                  verbose=0, shuffle=False, observe=True,
                  observe_dir=tele_dir)

        dumps = glob.glob(os.path.join(tele_dir, "flightrec-*.json"))
        assert len(dumps) == 1
        blob = json.load(open(dumps[0]))
        assert "NonFiniteError" in blob["reason"]
        records = blob["records"]
        # last record is the failing step's skip event: the guard
        # emitted it immediately before raising
        last = records[-1]
        assert last["kind"] == "step_skip"
        assert last["consecutive"] == 2
        assert not np.isfinite(last["loss"])
        # the clean step 1 and the first skip are in the ring too
        assert any(r["kind"] == "step" and r["step"] == 1
                   for r in records)
        assert blob["metrics"]["train.skipped_steps_total"]["value"] == 2
        # session tore down despite the raise
        assert not REGISTRY.enabled
        assert m._telemetry is None

    def test_observe_off_does_no_telemetry(self, tmp_path):
        pt.seed(0)
        m = _make_model()
        m.fit(_dataset(32), batch_size=16, epochs=1, verbose=0)
        assert m._telemetry is None
        assert not os.path.exists("telemetry")


class TestHw:
    def test_peak_flops_table(self):
        class Dev:
            device_kind = "TPU v4"
            platform = "tpu"
        assert peak_flops_per_chip(Dev()) == 275e12
        Dev.device_kind = "cpu"
        Dev.platform = "cpu"
        assert peak_flops_per_chip(Dev()) == 1e12

    def test_estimate_mfu(self):
        # 1e4 tokens/s * 6 * 1e9 params = 6e13 FLOP/s on a 197e12 chip
        mfu = estimate_mfu(1e4, int(1e9), peak_flops=197e12)
        assert abs(mfu - 6e13 / 197e12) < 1e-9
        assert estimate_mfu(1e4, 0, peak_flops=197e12) == 0.0


class TestTelemetrySessionLifecycle:
    def test_nested_sessions_restore_enabled_state(self, tmp_path):
        with TelemetrySession(str(tmp_path / "outer"),
                              crash_hooks=False):
            assert REGISTRY.enabled
            with TelemetrySession(str(tmp_path / "inner"),
                                  crash_hooks=False):
                assert REGISTRY.enabled
            assert REGISTRY.enabled             # outer still live
        assert not REGISTRY.enabled

    def test_close_idempotent(self, tmp_path):
        s = TelemetrySession(str(tmp_path), crash_hooks=False)
        s.close()
        s.close()
        assert not REGISTRY.enabled
