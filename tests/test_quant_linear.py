"""Weight-only quantization (reference nn/quant/quantized_linear.py +
weight_only_linear_kernel.h): quantize/dequantize round-trip, the Pallas
streaming-dequant matmul, and the quantized Llama decode config."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.flags import FLAGS, set_flags
from paddle_tpu.nn.quant import (llm_int8_linear, weight_dequantize,
                                 weight_only_linear, weight_quantize)

rng = np.random.default_rng(0)


def test_weight_quantize_roundtrip_int8():
    w = rng.normal(size=(64, 32)).astype(np.float32)
    q, s = weight_quantize(pt.to_tensor(w))
    assert np.asarray(q).dtype == np.int8
    back = np.asarray(weight_dequantize(q, s))
    # per-channel absmax int8: max error <= scale/2 per element
    scale = np.abs(w).max(0) / 127.0
    assert np.max(np.abs(back - w) / scale[None, :]) <= 0.5 + 1e-3


def test_weight_quantize_roundtrip_int4():
    w = rng.normal(size=(63, 32)).astype(np.float32)   # odd K: packing pad
    q, s = weight_quantize(pt.to_tensor(w), algo="weight_only_int4")
    assert np.asarray(q).shape == (32, 32)             # ceil(63/2)
    back = np.asarray(weight_dequantize(q, s, algo="weight_only_int4",
                                        k=63))
    scale = np.abs(w).max(0) / 7.0
    assert back.shape == w.shape
    assert np.max(np.abs(back - w) / scale[None, :]) <= 0.5 + 1e-3


@pytest.mark.parametrize("wdt", ["int8", "int4"])
def test_weight_only_linear_matches_fp(wdt):
    x = rng.normal(size=(4, 10, 64)).astype(np.float32)
    w = (rng.normal(size=(64, 48)) * 0.1).astype(np.float32)
    b = rng.normal(size=(48,)).astype(np.float32) * 0.1
    algo = f"weight_only_{wdt}"
    q, s = weight_quantize(pt.to_tensor(w), algo=algo)
    y = np.asarray(weight_only_linear(pt.to_tensor(x), q, pt.to_tensor(b),
                                      s, weight_dtype=wdt))
    ref = x @ w + b
    # quantization noise accumulates ~ sqrt(K) * scale/2 * E|x|
    tol = 0.03 if wdt == "int8" else 0.6
    assert np.max(np.abs(y - ref)) < tol, np.max(np.abs(y - ref))
    # and the linear must be EXACT against its own dequantized weight
    back = np.asarray(weight_dequantize(
        q, s, algo=algo, k=64)) if wdt == "int4" else np.asarray(
        weight_dequantize(q, s))
    np.testing.assert_allclose(y, x @ back + b, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("wdt", ["int8", "int4"])
def test_weight_only_linear_pallas_matches_jnp(wdt):
    """The Pallas streaming-dequant kernels (incl. in-VMEM int4 nibble
    unpack) == the dense dequant matmul."""
    x = rng.normal(size=(300, 129)).astype(np.float32)   # unaligned shapes
    w = (rng.normal(size=(129, 70)) * 0.1).astype(np.float32)
    algo = f"weight_only_{wdt}"
    q, s = weight_quantize(pt.to_tensor(w), algo=algo)
    old = FLAGS.pallas_interpret
    try:
        set_flags({"pallas_interpret": True})
        got = np.asarray(weight_only_linear(pt.to_tensor(x), q, None, s,
                                            weight_dtype=wdt))
    finally:
        set_flags({"pallas_interpret": old})
    exp = np.asarray(weight_only_linear(pt.to_tensor(x), q, None, s,
                                        weight_dtype=wdt))
    np.testing.assert_allclose(got, exp, rtol=2e-4, atol=2e-4)


def test_llm_int8_linear_close_to_fp():
    x = rng.normal(size=(8, 64)).astype(np.float32)
    x[:, 5] *= 20.0   # outlier column
    w = (rng.normal(size=(64, 32)) * 0.1).astype(np.float32)
    q, s = weight_quantize(pt.to_tensor(w), algo="llm.int8")
    y = np.asarray(llm_int8_linear(pt.to_tensor(x), q, None, s))
    ref = x @ w
    assert np.max(np.abs(y - ref)) < 0.05


def test_llama_weight_only_decode():
    """Quantized Llama decode (BASELINE config 5): prefill logits close to
    fp, generation runs and matches fp tokens on a strong-signal prompt."""
    from paddle_tpu import parallel as dist
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step
    from paddle_tpu.models.generation import (build_llama_decoder,
                                              llama_generate,
                                              quantize_llama_params)
    from paddle_tpu.parallel.topology import HybridTopology, set_topology

    cfg = llama_tiny()
    topo = dist.init_topology()
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    qparams = quantize_llama_params(params)

    ids = rng.integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    pre_fp, _ = build_llama_decoder(cfg, 12, use_pallas=False)
    pre_q, _ = build_llama_decoder(cfg, 12, use_pallas=False,
                                   quant="weight_only_int8")
    _, logits_fp = pre_fp(params, jnp.asarray(ids))
    _, logits_q = pre_q(qparams, jnp.asarray(ids))
    # int8 weight error is ~1%; logits must track closely
    err = np.max(np.abs(np.asarray(logits_q) - np.asarray(logits_fp)))
    ref = np.max(np.abs(np.asarray(logits_fp))) + 1e-6
    assert err / ref < 0.1, (err, ref)

    out = llama_generate(qparams, cfg, ids, 4, temperature=0.0,
                         use_pallas=False, quant="weight_only_int8")
    assert out.shape == (2, 12)
    assert np.isfinite(np.asarray(out)).all()

class TestFp8Gemm:
    """fp8 gemm (reference fusion/fp8_gemm): e4m3 storage + fp32 accum."""

    def test_quantize_roundtrip(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.nn.quant import quantize_to_fp8
        x = np.random.default_rng(0).normal(size=(16, 16)).astype(np.float32)
        q, scale = quantize_to_fp8(pt.Tensor(x))
        import jax.numpy as jnp
        back = np.asarray(q._value).astype(np.float32) * float(
            np.asarray(scale._value))
        # e4m3 has ~2 decimal digits; absmax scaling bounds rel error
        assert np.abs(back - x).max() <= np.abs(x).max() * 0.08

    def test_fp8_gemm_close_to_fp32(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.nn.quant import fp8_gemm
        rng = np.random.default_rng(1)
        x = rng.normal(size=(8, 32)).astype(np.float32)
        w = rng.normal(size=(32, 16)).astype(np.float32)
        b = rng.normal(size=(16,)).astype(np.float32)
        out = np.asarray(fp8_gemm(pt.Tensor(x), pt.Tensor(w),
                                  bias=pt.Tensor(b))._value)
        ref = x @ w + b
        err = np.abs(out - ref).max() / np.abs(ref).max()
        assert err < 0.12, err

    def test_fp8_gemm_prequantized_and_act(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.nn.quant import fp8_gemm, quantize_to_fp8
        rng = np.random.default_rng(2)
        x = rng.normal(size=(4, 8)).astype(np.float32)
        w = rng.normal(size=(8, 8)).astype(np.float32)
        xq, xs = quantize_to_fp8(pt.Tensor(x))
        wq, ws = quantize_to_fp8(pt.Tensor(w))
        out = np.asarray(fp8_gemm(xq, wq, x_scale=xs, y_scale=ws,
                                  activation="relu")._value)
        ref = np.maximum(x @ w, 0)
        assert np.abs(out - ref).max() / max(np.abs(ref).max(), 1) < 0.15


# -- group-wise scales (r4: reference weight_quantize group_size=64/128) ---
@pytest.mark.parametrize("gs", [64, 128])
def test_weight_quantize_grouped_roundtrip(gs):
    """Group-wise scales track per-group magnitude: round-trip error stays
    within scale/2 of each group's OWN scale, even when magnitudes vary
    wildly across row groups (where per-channel scales would blow up)."""
    K, N = 256, 32
    w = rng.normal(size=(K, N)).astype(np.float32)
    w[:gs] *= 100.0                      # hot first group
    q, s = weight_quantize(pt.to_tensor(w), group_size=gs)
    G = K // gs
    assert np.asarray(s).shape == (G, N)
    back = np.asarray(weight_dequantize(q, s, group_size=gs))
    srow = np.repeat(np.asarray(s), gs, axis=0)
    assert np.max(np.abs(back - w) / srow) <= 0.5 + 1e-3
    # per-channel quantization of the same matrix is catastrophically
    # worse on the cold groups — the point of grouping
    q1, s1 = weight_quantize(pt.to_tensor(w))
    back1 = np.asarray(weight_dequantize(q1, s1))
    err_g = np.abs(back - w)[gs:].max()
    err_c = np.abs(back1 - w)[gs:].max()
    assert err_g < err_c / 10


@pytest.mark.parametrize("wdt,gs", [("int8", 64), ("int8", 128),
                                    ("int4", 64), ("int4", 128)])
def test_weight_only_linear_grouped_matches_dequant(wdt, gs):
    K, N = 256, 48
    x = rng.normal(size=(4, 10, K)).astype(np.float32)
    w = (rng.normal(size=(K, N)) * 0.1).astype(np.float32)
    algo = f"weight_only_{wdt}"
    q, s = weight_quantize(pt.to_tensor(w), algo=algo, group_size=gs)
    y = np.asarray(weight_only_linear(pt.to_tensor(x), q, None, s,
                                      weight_dtype=wdt, group_size=gs))
    back = np.asarray(weight_dequantize(q, s, algo=algo, k=K,
                                        group_size=gs))
    np.testing.assert_allclose(y, x @ back, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("wdt,gs", [("int8", 64), ("int4", 64),
                                    ("int8", 128), ("int4", 128)])
def test_weight_only_linear_grouped_pallas_matches_jnp(wdt, gs):
    """The grouped Pallas kernels (per-k-block scale rows; int4 hi-plane
    group offset) == the dense grouped dequant matmul."""
    K, N = 256, 40
    x = rng.normal(size=(30, K)).astype(np.float32)
    w = (rng.normal(size=(K, N)) * 0.1).astype(np.float32)
    w[:gs] *= 10.0
    algo = f"weight_only_{wdt}"
    q, s = weight_quantize(pt.to_tensor(w), algo=algo, group_size=gs)
    old = FLAGS.pallas_interpret
    try:
        set_flags({"pallas_interpret": True})
        got = np.asarray(weight_only_linear(pt.to_tensor(x), q, None, s,
                                            weight_dtype=wdt, group_size=gs))
    finally:
        set_flags({"pallas_interpret": old})
    exp = np.asarray(weight_only_linear(pt.to_tensor(x), q, None, s,
                                        weight_dtype=wdt, group_size=gs))
    np.testing.assert_allclose(got, exp, rtol=2e-4, atol=2e-4)


def test_grouped_misuse_raises():
    """r4 review: misuse fails loudly, not silently-wrong."""
    w = rng.normal(size=(128, 16)).astype(np.float32)
    with pytest.raises(ValueError, match="llm.int8"):
        weight_quantize(pt.to_tensor(w), algo="llm.int8", group_size=64)
    with pytest.raises(ValueError, match="group_size"):
        weight_dequantize(pt.to_tensor(w).astype("int8"),
                          np.ones(16, "float32"), group_size=256)
    # per-channel [N] scale with group_size set must raise in the kernel,
    # not zero out weight groups
    from paddle_tpu.ops.pallas.quant_linear import weight_only_matmul
    import jax.numpy as _jnp
    with pytest.raises(ValueError, match="grouped scale"):
        weight_only_matmul(_jnp.ones((4, 256), _jnp.float32),
                           _jnp.ones((256, 16), _jnp.int8),
                           _jnp.ones((16,), _jnp.float32), group_size=64)


# -- direct interpret-tier kernel parity (ISSUE 10, KL006's catch) --------
class TestQuantKernelInterpretParity:
    """The Pallas weight-only kernels vs a dense fp32 dequant matmul,
    fp32/bf16 tolerance tiers mirroring test_fused_head.py — the first
    direct-numerics coverage of `weight_only_matmul_int4` (previously
    referenced only by the hardware/lowering lanes, both skipped in
    this container: the KL006 interpret-parity gap)."""

    @pytest.fixture(autouse=True)
    def _interpret(self):
        old = FLAGS.pallas_interpret
        set_flags({"pallas_interpret": True})
        yield
        set_flags({"pallas_interpret": old})

    def _int8_case(self, K, N, gs):
        wq = rng.integers(-127, 128, (K, N)).astype(np.int8)
        G = 1 if gs in (-1, None) else K // gs
        s = (rng.uniform(0.5, 1.5, (N,)) / 127).astype(np.float32) \
            if G == 1 else \
            (rng.uniform(0.5, 1.5, (G, N)) / 127).astype(np.float32)
        dense = wq.astype(np.float32) * (
            s[None, :] if s.ndim == 1 else np.repeat(s, gs, axis=0))
        return wq, s, dense

    @pytest.mark.parametrize("gs", [-1, 64])
    def test_int8_fp32_parity(self, gs):
        from paddle_tpu.ops.pallas.quant_linear import weight_only_matmul
        K, N = 256, 48
        x = rng.normal(size=(10, K)).astype(np.float32)
        wq, s, dense = self._int8_case(K, N, gs)
        got = np.asarray(weight_only_matmul(
            jnp.asarray(x), jnp.asarray(wq), jnp.asarray(s),
            group_size=gs))
        np.testing.assert_allclose(got, x @ dense, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("gs", [-1, 64])
    def test_int8_bf16_parity(self, gs):
        from paddle_tpu.ops.pallas.quant_linear import weight_only_matmul
        K, N = 256, 40
        x = rng.normal(size=(8, K)).astype(np.float32)
        wq, s, dense = self._int8_case(K, N, gs)
        got = np.asarray(weight_only_matmul(
            jnp.asarray(x, jnp.bfloat16), jnp.asarray(wq),
            jnp.asarray(s), group_size=gs, out_dtype=jnp.float32),
            np.float32)
        ref = x @ dense
        np.testing.assert_allclose(got, ref, rtol=2e-2,
                                   atol=2e-2 * np.abs(ref).max())

    def _int4_case(self, K, N, gs, dtype):
        w = (rng.normal(size=(K, N)) * 0.1).astype(np.float32)
        q, s = weight_quantize(pt.to_tensor(w),
                               algo="weight_only_int4",
                               **({} if gs in (-1, None)
                                  else {"group_size": gs}))
        dense = np.asarray(weight_dequantize(
            q, s, algo="weight_only_int4", k=K,
            **({} if gs in (-1, None) else {"group_size": gs})))
        return np.asarray(q), np.asarray(s), dense

    @pytest.mark.parametrize("gs", [-1, 64])
    def test_int4_fp32_parity(self, gs):
        from paddle_tpu.ops.pallas.quant_linear import (
            weight_only_matmul_int4)
        K, N = 256, 48
        x = rng.normal(size=(10, K)).astype(np.float32)
        q, s, dense = self._int4_case(K, N, gs, np.float32)
        got = np.asarray(weight_only_matmul_int4(
            jnp.asarray(x), jnp.asarray(q), jnp.asarray(s),
            group_size=gs))
        np.testing.assert_allclose(got, x @ dense, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("gs", [-1, 64])
    def test_int4_bf16_parity(self, gs):
        from paddle_tpu.ops.pallas.quant_linear import (
            weight_only_matmul_int4)
        K, N = 256, 40
        x = rng.normal(size=(6, K)).astype(np.float32)
        q, s, dense = self._int4_case(K, N, gs, jnp.bfloat16)
        got = np.asarray(weight_only_matmul_int4(
            jnp.asarray(x, jnp.bfloat16), jnp.asarray(q),
            jnp.asarray(s), group_size=gs, out_dtype=jnp.float32),
            np.float32)
        ref = x @ dense
        np.testing.assert_allclose(got, ref, rtol=2e-2,
                                   atol=2e-2 * max(np.abs(ref).max(), 1e-3))
