"""Hybrid-parallel loss equivalence: every axis combination must reproduce
the single-device training trajectory (the reference pins this with
test/collective/fleet/hybrid_parallel_mp_model.py etc.; round-1's gap was
exactly mp×pp in one mesh — BASELINE config 4 is GPT mp2×pp2)."""

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as pt
from paddle_tpu import parallel as dist
from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
from paddle_tpu.parallel.topology import HybridTopology, set_topology


@pytest.fixture(autouse=True)
def reset_topology():
    yield
    set_topology(HybridTopology())


def _losses(dp=1, mp=1, pp=1, sep=1, sharding=1, steps=3,
            num_microbatches=None, batch=4, seq=32, schedule="1f1b",
            layers=2, sequence_parallel=False, sharding_stage=2,
            num_model_chunks=1, return_state=False, tp_overlap=False):
    topo = dist.init_topology(dp=dp, mp=mp, pp=pp, sep=sep,
                              sharding=sharding)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=layers,
                    num_heads=4, max_position_embeddings=64)
    if num_microbatches is None:
        num_microbatches = 2 if pp > 1 else 1
    step_fn, init_fn = build_gpt_train_step(
        cfg, topo, num_microbatches=num_microbatches, schedule=schedule,
        sharding_stage=sharding_stage, num_model_chunks=num_model_chunks,
        sequence_parallel=sequence_parallel, tp_overlap=tp_overlap)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    out = []
    for _ in range(steps):
        state, loss = step_fn(state, ids, labels)
        out.append(float(np.asarray(jax.device_get(loss))))
    if return_state:
        return out, state
    return out


BASE = None


def _base():
    global BASE
    if BASE is None:
        BASE = _losses()
    return BASE


def test_single_device_baseline_trains():
    base = _base()
    assert all(np.isfinite(base))
    assert base[-1] < base[0]


@pytest.mark.parametrize("axes", [
    dict(mp=2, pp=2, sep=2),            # BASELINE config 4 shape (+sep)
    dict(mp=2, pp=2, sharding=2),       # mp×pp×ZeRO
    dict(mp=2, pp=2, dp=2),
    dict(mp=4, pp=2),
    dict(mp=2, sharding=2, dp=2),
    dict(mp=2, sep=2, sharding=2),
    dict(pp=2, sharding=2, sep=2),
    dict(sharding=4,),                  # pure ZeRO
])
def test_hybrid_matches_single_device(axes):
    got = _losses(**axes)
    np.testing.assert_allclose(got, _base(), rtol=2e-4, atol=1e-5)


def _llama_losses(steps=3, **axes):
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step
    topo = dist.init_topology(**axes)
    cfg = llama_tiny()
    mb = 2 if axes.get("pp", 1) > 1 else 1
    step_fn, init_fn = build_llama_train_step(cfg, topo,
                                              num_microbatches=mb)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    out = []
    for _ in range(steps):
        state, loss = step_fn(state, ids, labels)
        out.append(float(np.asarray(jax.device_get(loss))))
    return out


LLAMA_BASE = None


def _llama_base():
    global LLAMA_BASE
    if LLAMA_BASE is None:
        LLAMA_BASE = _llama_losses()
    return LLAMA_BASE


@pytest.mark.parametrize("axes", [
    dict(mp=2, pp=2, sep=2),
    dict(mp=2, pp=2, sharding=2),
])
def test_llama_hybrid_matches_single_device(axes):
    base = _llama_base()
    got = _llama_losses(**axes)
    np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-5)
    assert base[-1] < base[0]


BASE8 = None


def _base8():
    """Single-device baseline for the deep-pipe cases (batch 8, 4 layers)."""
    global BASE8
    if BASE8 is None:
        BASE8 = _losses(batch=8, layers=4)
    return BASE8


@pytest.mark.parametrize("axes", [
    dict(pp=2, mp=2, sep=2),
    dict(pp=4, num_microbatches=8, batch=8, layers=4),  # deep pipe, M >> S
])
def test_gpipe_schedule_matches_single_device(axes):
    got = _losses(schedule="gpipe", **axes)
    base = _base8() if axes.get("batch") == 8 else _base()
    np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-5)


def test_1f1b_pp4_many_microbatches():
    got = _losses(pp=4, num_microbatches=8, batch=8, layers=4)
    np.testing.assert_allclose(got, _base8(), rtol=2e-4, atol=1e-5)


def test_1f1b_activation_memory_is_o_stages_not_o_microbatches():
    """The point of 1F1B (reference pipeline_parallel.py:547): peak
    activation state independent of microbatch count M.  The gpipe scan's
    saved residuals grow O(M); 1f1b's circular buffer is O(S).  Compare
    compiled temp memory at M=16 vs M=4 — 1f1b must grow far slower."""
    import jax

    def temp_bytes(schedule, M):
        topo = dist.init_topology(pp=4)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                        num_heads=4, max_position_embeddings=64)
        step_fn, init_fn = build_gpt_train_step(
            cfg, topo, num_microbatches=M, schedule=schedule)
        state = init_fn(0)
        ids = np.zeros((M * 2, 32), np.int64)
        lowered = step_fn.lower(state, ids, ids)
        mem = lowered.compile().memory_analysis()
        set_topology(HybridTopology())
        return mem.temp_size_in_bytes

    gp = temp_bytes("gpipe", 16) - temp_bytes("gpipe", 4)
    ob = temp_bytes("1f1b", 16) - temp_bytes("1f1b", 4)
    # growth going 4 -> 16 microbatches (batch grows with M; both schedules
    # see the same data): 1f1b's activation growth must be well under
    # gpipe's residual growth.
    assert ob < gp * 0.55, (ob, gp)


@pytest.mark.parametrize("axes", [
    dict(mp=2,),
    dict(mp=4,),
    dict(mp=2, pp=2),
    dict(mp=2, sep=2),
    dict(mp=2, dp=2, sharding=2),
])
def test_megatron_sp_matches_single_device(axes):
    """Megatron sequence parallelism (reference
    sequence_parallel_utils.py): activations seq-sharded over mp between
    blocks, all-gather/reduce-scatter around the matmuls, partial LN/bias
    grads psum'ed — must reproduce the dense trajectory exactly."""
    got = _losses(sequence_parallel=True, **axes)
    np.testing.assert_allclose(got, _base(), rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("axes", [
    dict(mp=2,),
    dict(mp=4,),
    dict(mp=2, pp=2),
])
def test_megatron_sp_tp_overlap_matches_single_device(axes):
    """SP with the collective-matmul ring (tp_overlap=True,
    parallel/overlap.py): the gather/scatter-decomposed matmuls must
    reproduce the same training trajectory as dense single-device."""
    got = _losses(sequence_parallel=True, tp_overlap=True, **axes)
    np.testing.assert_allclose(got, _base(), rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("tp_overlap", [False, True])
def test_llama_sp_matches_single_device(tp_overlap):
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step
    topo = dist.init_topology(mp=2, sep=2)
    cfg = llama_tiny()
    step_fn, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1,
                                              sequence_parallel=True,
                                              tp_overlap=tp_overlap)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    out = []
    for _ in range(3):
        state, loss = step_fn(state, ids, labels)
        out.append(float(np.asarray(jax.device_get(loss))))
    np.testing.assert_allclose(out, _llama_base(), rtol=2e-4, atol=1e-5)


def test_mp2_step_uses_pallas_flash():
    """VERDICT r1 weak-6: the flagship path must actually run the Pallas
    flash kernel on sharded meshes (round-1 gated it to mesh.size==1)."""
    import jax
    topo = dist.init_topology(mp=2)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128)
    step_fn, init_fn = build_gpt_train_step(cfg, topo, num_microbatches=1,
                                            use_flash=True)
    state = init_fn(0)
    ids = np.zeros((2, 128), np.int64)
    jx = str(jax.make_jaxpr(lambda s, i, l: step_fn(s, i, l))(
        state, ids, ids))
    # fwd kernel + recompute-bwd kernels (dq, dkv) must all be present
    assert jx.count("pallas_call") >= 3, jx.count("pallas_call")
    # and the step still runs numerically
    state, loss = step_fn(state, ids, ids)
    assert np.isfinite(float(np.asarray(jax.device_get(loss))))


def test_mp2_sharding4_moments_are_sharded():
    """ZeRO stage-1/2: optimizer moments are stored 1/shard per device
    (flat chunk layout over the sharding axis)."""
    topo = dist.init_topology(mp=2, sharding=4)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64)
    step_fn, init_fn = build_gpt_train_step(cfg, topo, num_microbatches=1)
    state = init_fn(0)
    m_wte = state["opt"]["m"]["wte"]
    # wte local shard = (128/2)*32 = 2048 elems; chunk = 2048/4 = 512
    assert m_wte.shape == (1, 2, 4 * 512)
    shard_bytes = [s.data.nbytes for s in m_wte.addressable_shards]
    assert max(shard_bytes) == 512 * 4  # fp32 chunk per device


# ---------------------------------------------------------------------------
# ZeRO stage-3 (params flat-sharded at rest, gathered at use;
# reference group_sharded_stage3.py:85)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("axes", [dict(sharding=4),
                                  dict(mp=2, sharding=2),
                                  dict(pp=2, sharding=2),
                                  dict(mp=2, pp=2, sharding=2)])
def test_stage3_matches_single_device(axes):
    ref = _losses()
    got = _losses(**axes, sharding_stage=3)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_stage3_params_sharded_at_rest():
    """Per-device param residency must drop ~1/shard vs stage 2."""
    _, st2 = _losses(sharding=4, steps=1, return_state=True)
    _, st3 = _losses(sharding=4, steps=1, sharding_stage=3,
                     return_state=True)

    def per_device_param_bytes(state):
        total = 0
        for leaf in jax.tree.leaves(state["params"]):
            shards = leaf.addressable_shards
            total += shards[0].data.nbytes
        return total

    b2 = per_device_param_bytes(st2)
    b3 = per_device_param_bytes(st3)
    # flat layout pads each leaf to a multiple of shard, so allow slack
    assert b3 < b2 * 0.35, (b2, b3)


def test_stage3_state_roundtrips_through_step():
    _, st = _losses(mp=2, sharding=2, pp=2, steps=2, sharding_stage=3,
                    return_state=True)
    # flat leaves stay flat (no silent re-densification)
    wte = st["params"]["wte"]
    assert wte.ndim == 3, wte.shape


# ---------------------------------------------------------------------------
# Interleaved / VPP schedule (reference pipeline_parallel.py:1138)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("axes,layers", [(dict(pp=2), 4),
                                         (dict(pp=2, mp=2), 4),
                                         (dict(pp=4), 8)])
def test_interleave_matches_single_device(axes, layers):
    ref = _losses(layers=layers)
    got = _losses(**axes, layers=layers, schedule="interleave",
                  num_microbatches=4, num_model_chunks=2)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_interleave_three_chunks():
    ref = _losses(layers=6)
    got = _losses(pp=2, layers=6, schedule="interleave",
                  num_microbatches=4, num_model_chunks=3)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("axes", [dict(pp=2, sharding=2),
                                  dict(pp=2, mp=2, sharding=2)])
def test_interleave_stage3_matches_single_device(axes):
    """VPP + ZeRO stage-3 (r4: the last unwired schedule x sharding
    combination): flat-at-rest params with the chunk axis, gather-at-use
    inside each virtual chunk's stack."""
    ref = _losses(layers=4, batch=8)
    got = _losses(**axes, layers=4, batch=8, schedule="interleave",
                  num_microbatches=4, num_model_chunks=2, sharding_stage=3)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# ZBH1 zero-bubble schedule (reference pipeline_scheduler_pass ZBH1)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("axes,layers", [
    (dict(pp=2), 2),
    (dict(pp=4, batch=8, num_microbatches=4), 4)])
def test_zbh1_matches_single_device(axes, layers):
    base = _base8() if axes.get("batch") == 8 else _base()
    got = _losses(schedule="zbh1", layers=layers, **axes)
    np.testing.assert_allclose(got, base, rtol=2e-4, atol=1e-5)


def test_offload_optimizer_matches_and_lives_on_host():
    """Optimizer-state offload (reference group_sharded offload): moments
    live in host numpy between steps; trajectory unchanged."""
    ref = _losses()
    topo = dist.init_topology(sharding=2)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64)
    step_fn, init_fn = build_gpt_train_step(cfg, topo, num_microbatches=1,
                                            offload_optimizer=True)
    state = init_fn(0)
    assert isinstance(jax.tree.leaves(state["opt"]["m"])[0], np.ndarray)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    out = []
    for _ in range(3):
        state, loss = step_fn(state, ids, labels)
        out.append(float(np.asarray(jax.device_get(loss))))
        assert isinstance(jax.tree.leaves(state["opt"]["m"])[0],
                          np.ndarray)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)


def test_llama_interleave_matches_single_device():
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step

    def run(**kw):
        topo = dist.init_topology(**{k: v for k, v in kw.items()
                                     if k in ("pp", "mp")})
        cfg = llama_tiny(num_layers=4)
        step_fn, init_fn = build_llama_train_step(
            cfg, topo, num_microbatches=kw.get("mb", 1),
            schedule=kw.get("schedule", "1f1b"),
            num_model_chunks=kw.get("chunks", 1))
        state = init_fn(0)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int64)
        out = []
        for _ in range(3):
            state, loss = step_fn(state, ids, np.roll(ids, -1, 1))
            out.append(float(np.asarray(jax.device_get(loss))))
        set_topology(HybridTopology())
        return out

    ref = run()
    got = run(pp=2, mb=4, schedule="interleave", chunks=2)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
