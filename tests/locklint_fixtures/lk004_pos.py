"""LK004 positive: ``if not ready: cond.wait()`` — the textbook
missed-wakeup bug (spurious wakeups / consumed notifications are
never re-checked)."""
import threading


class Box:
    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False

    def take(self):
        with self._cond:
            if not self.ready:
                self._cond.wait()
            return 1
