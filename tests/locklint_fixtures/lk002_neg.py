"""LK002 negative: blocking work happens outside the lock (collect
under the lock, act after releasing), bounded waits are fine, and the
condition-variable wait-under-its-own-condition idiom is exempt."""
import queue
import threading
import time


class Sender:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._q = queue.Queue()
        self.sock = sock
        self.ready = False

    def send(self, data):
        with self._lock:
            payload = bytes(data)       # stage under the lock...
        self.sock.sendall(payload)      # ...send after releasing

    def nap(self):
        time.sleep(0.01)                # not under any lock

    def take(self):
        with self._lock:
            return self._q.get(timeout=0.5)    # bounded wait

    def wait_ready(self):
        with self._cond:
            while not self.ready:
                self._cond.wait(0.1)    # the CV idiom: exempt
