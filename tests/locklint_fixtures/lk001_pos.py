"""LK001 positive: ``_status`` is written by both the public (main)
surface and the worker thread with no common lock."""
import threading


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._status = "idle"
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        self._status = "running"        # thread-role write, unlocked

    def poke(self):
        self._status = "poked"          # main-role write, unlocked

    def close(self):
        self._thread.join(timeout=1.0)
