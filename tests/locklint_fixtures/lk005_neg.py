"""LK005 negative: the finalizer touches only plain object state — no
locks, no thread joins, no queue handoff."""


class Plain:
    def __init__(self):
        self._fh = None

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __del__(self):
        self.close()
