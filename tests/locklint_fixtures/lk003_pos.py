"""LK003 positive: two locks acquired in opposite orders on two code
paths — the classic ABBA deadlock."""
import threading


class Transfer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def deposit(self):
        with self._a:
            with self._b:
                pass

    def withdraw(self):
        with self._b:
            with self._a:
                pass
