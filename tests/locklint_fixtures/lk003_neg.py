"""LK003 negative: both paths acquire in the same global order (one
directly nested, one through a call — the one-level closure sees
both), so the order graph stays acyclic."""
import threading


class Transfer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def deposit(self):
        with self._a:
            with self._b:
                pass

    def withdraw(self):
        with self._a:
            self._log()

    def _log(self):
        with self._b:
            pass
