"""LK002 positive: three shapes of blocking call under a held lock —
socket send, time.sleep, and an unbounded queue get."""
import queue
import threading
import time


class Sender:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self.sock = sock

    def send(self, data):
        with self._lock:
            self.sock.sendall(data)     # network write under the lock

    def nap(self):
        with self._lock:
            time.sleep(1.0)             # sleep under the lock

    def take(self):
        with self._lock:
            return self._q.get()        # unbounded get under the lock
