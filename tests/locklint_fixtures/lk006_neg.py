"""LK006 negative: every started thread has a join on its binding
somewhere on the owner's shutdown path (local aliases count)."""
import threading


class Owner:
    def start(self):
        self._thread = threading.Thread(target=self._run, name="pump",
                                        daemon=True)
        self._thread.start()

    def _run(self):
        pass

    def close(self):
        self._thread.join(timeout=1.0)


def run_once(job):
    t = threading.Thread(target=job)
    t.start()
    t.join(timeout=5.0)
