"""LK004 negative: the wait sits in a while loop that re-checks the
predicate after every wakeup."""
import threading


class Box:
    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False

    def take(self):
        with self._cond:
            while not self.ready:
                self._cond.wait(0.1)
            return 1
