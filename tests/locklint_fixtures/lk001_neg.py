"""LK001 negative: every cross-role write happens under the same
lock, so the roles share a guard."""
import threading


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._status = "idle"           # __init__ writes are exempt
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        with self._lock:
            self._status = "running"

    def poke(self):
        with self._lock:
            self._status = "poked"

    def close(self):
        self._thread.join(timeout=1.0)
