"""LK006 positive: a bound thread nobody ever joins, and an unbound
``Thread(...).start()`` that can never be joined at all."""
import threading


class Owner:
    def start(self):
        self._thread = threading.Thread(target=self._run, name="pump",
                                        daemon=True)
        self._thread.start()

    def _run(self):
        pass


def fire(job):
    threading.Thread(target=job, daemon=True).start()
