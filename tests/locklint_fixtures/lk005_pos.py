"""LK005 positive: a ``__del__`` whose transitive close() both
acquires a lock and joins a thread, plus an atexit handler acquiring a
module lock."""
import atexit
import threading

_tasks = []
_reg_lock = threading.Lock()


def _drain():
    with _reg_lock:
        _tasks.clear()


atexit.register(_drain)


class Holder:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        pass

    def close(self):
        with self._lock:
            pass
        self._thread.join(timeout=1.0)

    def __del__(self):
        self.close()
