"""Launcher / elastic / watchdog tests (reference test/collective
launcher harness tests; SURVEY §4 'multi-node without a cluster' —
multi-process on one host)."""

import os
import subprocess
import sys
import time

import pytest

from paddle_tpu.distributed.elastic import (ElasticManager, FileStore,
                                            StepWatchdog)
from paddle_tpu.distributed.launch.main import _nnodes_range, launch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_nnodes_range():
    assert _nnodes_range("4") == (4, 4)
    assert _nnodes_range("2:6") == (2, 6)


def test_local_launch_spawns_ranks(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(
        "import os\n"
        "print('rank', os.environ['PADDLE_TRAINER_ID'],\n"
        "      'of', os.environ['PADDLE_TRAINERS_NUM'])\n")
    log_dir = tmp_path / "logs"
    code = launch(["--nproc_per_node", "2", "--log_dir", str(log_dir),
                   str(script)])
    assert code == 0
    logs = sorted(os.listdir(log_dir))
    assert logs == ["workerlog.0", "workerlog.1"]
    assert "rank 0 of 2" in (log_dir / "workerlog.0").read_text()
    assert "rank 1 of 2" in (log_dir / "workerlog.1").read_text()


def test_local_launch_failure_propagates(tmp_path):
    script = tmp_path / "fail.py"
    script.write_text(
        "import os, sys\n"
        "sys.exit(3 if os.environ['PADDLE_TRAINER_ID'] == '1' else 0)\n")
    code = launch(["--nproc_per_node", "2", str(script)])
    assert code == 3


def test_max_restart(tmp_path):
    # first attempt fails, then the marker exists and the job succeeds
    marker = tmp_path / "marker"
    script = tmp_path / "flaky.py"
    script.write_text(
        f"import os, sys\n"
        f"m = {str(marker)!r}\n"
        f"if os.path.exists(m): sys.exit(0)\n"
        f"open(m, 'w').close(); sys.exit(1)\n")
    code = launch(["--nproc_per_node", "1", "--max_restart", "2",
                   str(script)])
    assert code == 0


class TestElastic:
    def test_membership_and_ttl(self, tmp_path):
        store = FileStore(str(tmp_path), ttl=0.5)
        store.register("host_a")
        store.register("host_b")
        assert store.hosts() == ["host_a", "host_b"]
        time.sleep(0.6)
        store.register("host_a")
        assert store.hosts() == ["host_a"]  # b's lease expired
        store.deregister("host_a")
        assert store.hosts() == []

    def test_scale_decision(self, tmp_path):
        store = FileStore(str(tmp_path))
        m = ElasticManager(store, "h0", nnodes="2:4")
        assert m.elastic_enabled
        assert m.scale_decision(["h0"]) == "wait"
        assert m.scale_decision(["h0", "h1"]) == "ok"
        m._known = ["h0", "h1"]
        assert m.scale_decision(["h0", "h1", "h2"]) == "restart"
        assert m.scale_decision(["h0", "h1"]) == "ok"


class TestWatchdog:
    def test_fires_on_hang(self):
        fired = []
        wd = StepWatchdog(timeout=0.3, on_timeout=lambda: fired.append(1),
                          poll=0.05).start()
        with wd.step():
            time.sleep(0.7)
        wd.stop()
        assert fired

    def test_quiet_on_fast_steps(self):
        fired = []
        wd = StepWatchdog(timeout=1.0, on_timeout=lambda: fired.append(1),
                          poll=0.05).start()
        for _ in range(3):
            with wd.step():
                time.sleep(0.02)
        wd.stop()
        assert not fired
