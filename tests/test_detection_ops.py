"""Detection-op family tests (reference test/legacy_test/test_box_coder_op.py,
test_roi_align_op.py, test_roi_pool_op.py, test_yolo_box_op.py,
test_matrix_nms_op.py, test_bipartite_match_op.py,
test_deform_conv2d.py — identity/roundtrip/structural checks rather than
the reference's CUDA-vs-CPU cross-check)."""

import numpy as np
import pytest

import paddle_tpu as pt


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.default_rng(0)
        priors = rng.uniform(0, 10, (5, 4)).astype(np.float32)
        priors[:, 2:] = priors[:, :2] + rng.uniform(1, 5, (5, 2))
        targets = rng.uniform(0, 10, (3, 4)).astype(np.float32)
        targets[:, 2:] = targets[:, :2] + rng.uniform(1, 5, (3, 2))
        var = [0.1, 0.1, 0.2, 0.2]
        enc = _np(pt.box_coder(pt.Tensor(priors), var, pt.Tensor(targets),
                               code_type="encode_center_size"))
        assert enc.shape == (3, 5, 4)
        dec = _np(pt.box_coder(pt.Tensor(priors), var, pt.Tensor(enc),
                               code_type="decode_center_size", axis=1))
        # decoding the encoding of target t against prior m recovers t
        np.testing.assert_allclose(
            dec, np.broadcast_to(targets[:, None, :], dec.shape), rtol=1e-4,
            atol=1e-4)

    def test_box_clip(self):
        boxes = np.array([[[-5.0, -5.0, 20.0, 30.0]]], np.float32)
        im_info = np.array([[10.0, 10.0, 1.0]], np.float32)
        out = _np(pt.box_clip(pt.Tensor(boxes), pt.Tensor(im_info)))
        np.testing.assert_allclose(out[0, 0], [0.0, 0.0, 9.0, 9.0])


class TestRoi:
    def test_roi_align_whole_image_equals_mean(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(1, 3, 8, 8)).astype(np.float32)
        # single ROI covering the full map, 1x1 output, aligned=False:
        # average of the four bilinear samples ~ center mean; use a constant
        # map for an exact check instead
        xc = np.full((1, 2, 6, 6), 3.5, np.float32)
        out = _np(pt.roi_align(pt.Tensor(xc), pt.Tensor(
            np.array([[0.0, 0.0, 6.0, 6.0]], np.float32)), [1],
            pooled_height=2, pooled_width=2, spatial_scale=1.0,
            aligned=False))
        assert out.shape == (1, 2, 2, 2)
        np.testing.assert_allclose(out, 3.5, rtol=1e-6)
        # gradient flows to x
        import jax
        g = jax.grad(lambda a: pt.ops.get_op("roi_align").fn.raw(
            a, np.array([[0.0, 0.0, 6.0, 6.0]], np.float32), [1],
            pooled_height=2, pooled_width=2).sum())(xc)
        assert np.abs(g).sum() > 0

    def test_roi_pool_exact_max(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        boxes = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
        out = _np(pt.roi_pool(pt.Tensor(x), pt.Tensor(boxes), [1],
                              pooled_height=2, pooled_width=2))
        np.testing.assert_allclose(out[0, 0], [[5.0, 7.0], [13.0, 15.0]])

    def test_psroi_pool_shapes(self):
        x = np.random.default_rng(2).normal(
            size=(1, 8, 6, 6)).astype(np.float32)
        boxes = np.array([[0.0, 0.0, 5.0, 5.0]], np.float32)
        out = _np(pt.psroi_pool(pt.Tensor(x), pt.Tensor(boxes), [1],
                                output_size=2))
        assert out.shape == (1, 2, 2, 2)

    def test_roi_batch_mapping(self):
        # two images; second image's map is constant 7 — its ROI must read 7
        x = np.zeros((2, 1, 4, 4), np.float32)
        x[1] = 7.0
        boxes = np.array([[0.0, 0.0, 3.0, 3.0],
                          [0.0, 0.0, 3.0, 3.0]], np.float32)
        out = _np(pt.roi_pool(pt.Tensor(x), pt.Tensor(boxes), [1, 1],
                              pooled_height=1, pooled_width=1))
        np.testing.assert_allclose(out[:, 0, 0, 0], [0.0, 7.0])


class TestPriorYolo:
    def test_prior_box_structure(self):
        feat = np.zeros((1, 8, 4, 4), np.float32)
        img = np.zeros((1, 3, 32, 32), np.float32)
        boxes, var = pt.prior_box(pt.Tensor(feat), pt.Tensor(img),
                                  min_sizes=[8.0], max_sizes=[16.0],
                                  aspect_ratios=[2.0], flip=True, clip=True)
        b, v = _np(boxes), _np(var)
        # priors: ratio1 + ratio2 + ratio0.5 + minmax = 4
        assert b.shape == (4, 4, 4, 4) and v.shape == b.shape
        assert (b >= 0).all() and (b <= 1).all()
        # first cell's ratio-1 prior is centered at offset*step/img = 4/32
        c = (b[0, 0, 0, :2] + b[0, 0, 0, 2:]) / 2
        np.testing.assert_allclose(c, [4.0 / 32, 4.0 / 32], atol=1e-6)

    def test_yolo_box_zero_logits(self):
        A, C, H, W = 1, 2, 2, 2
        x = np.zeros((1, A * (5 + C), H, W), np.float32)
        img = np.array([[64, 64]], np.int32)
        boxes, scores = pt.yolo_box(pt.Tensor(x), pt.Tensor(img),
                                    anchors=[16, 16], class_num=C,
                                    conf_thresh=0.01, downsample_ratio=32)
        b, s = _np(boxes), _np(scores)
        assert b.shape == (1, H * W * A, 4) and s.shape == (1, H * W * A, C)
        # sigmoid(0)=0.5: first cell center = 0.5/2 * 64 = 16; w = 16/64*64
        np.testing.assert_allclose(b[0, 0], [16 - 8, 16 - 8, 16 + 8, 16 + 8],
                                   rtol=1e-5)
        np.testing.assert_allclose(s, 0.25, rtol=1e-5)


class TestNmsMatch:
    def test_matrix_nms_decays_duplicates(self):
        bb = np.array([[[0, 0, 10, 10], [0, 0, 10, 10],
                        [20, 20, 30, 30]]], np.float32)
        sc = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # one class
        out, idx, num = pt.matrix_nms(bb, sc, score_threshold=0.1,
                                      post_threshold=0.0,
                                      background_label=-1)
        out, idx, num = _np(out), _np(idx), _np(num)
        # the exact duplicate decays to 0 and is dropped (ds <= post_thresh,
        # reference matrix_nms_kernel.cc:149); the distinct box survives
        assert num[0] == 2
        scores = {int(i): s for i, s in zip(idx, out[:, 1])}
        assert scores[0] == pytest.approx(0.9)
        assert 1 not in scores
        assert scores[2] == pytest.approx(0.7, abs=1e-6)

    def test_bipartite_match_greedy(self):
        d = np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)
        idx, dist = pt.bipartite_match(d)
        np.testing.assert_array_equal(_np(idx)[0], [0, 1])
        np.testing.assert_allclose(_np(dist)[0], [0.9, 0.8])


class TestDeformableConv:
    def test_zero_offset_equals_conv(self):
        import jax
        rng = np.random.default_rng(3)
        x = rng.normal(size=(2, 4, 8, 8)).astype(np.float32)
        w = rng.normal(size=(6, 4, 3, 3)).astype(np.float32)
        off = np.zeros((2, 2 * 9, 8, 8), np.float32)
        mask = np.ones((2, 9, 8, 8), np.float32)
        out = _np(pt.deformable_conv(pt.Tensor(x), pt.Tensor(off),
                                     pt.Tensor(w), pt.Tensor(mask),
                                     stride=1, padding=1))
        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-4,
                                   atol=2e-4)

    def test_integer_shift_offset(self):
        # offset of exactly (0, +1) shifts every tap one column right
        x = np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5)
        w = np.ones((1, 1, 1, 1), np.float32)
        off = np.zeros((1, 2, 5, 5), np.float32)
        off[:, 1] = 1.0  # dx = +1
        out = _np(pt.deformable_conv(pt.Tensor(x), pt.Tensor(off),
                                     pt.Tensor(w), None, stride=1,
                                     padding=0))
        expected = np.concatenate(
            [x[0, 0, :, 1:], np.zeros((5, 1), np.float32)], axis=1)
        np.testing.assert_allclose(out[0, 0], expected)
