"""Tensor façade + eager autograd tests (covers SURVEY §3.1/§3.2 semantics)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.tensor import Tensor


def test_to_tensor_basic():
    t = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert str(t.dtype) == "float32"
    assert t.stop_gradient
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_coercion_and_cast():
    t = pt.to_tensor(np.arange(4, dtype=np.int32), dtype="float32")
    assert str(t.dtype) == "float32"
    u = t.astype("bfloat16")
    assert str(u.dtype) == "bfloat16"
    assert t.item(0) == 0.0


def test_operators():
    a = pt.to_tensor([1.0, 2.0])
    b = pt.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a - 1).numpy(), [0, 1])
    np.testing.assert_allclose((2 - a).numpy(), [1, 0])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    assert bool((a < b).all())
    assert (a @ b).item() == pytest.approx(11.0)


def test_indexing():
    x = pt.to_tensor(np.arange(12.0).reshape(3, 4))
    np.testing.assert_allclose(x[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(x[1:, ::2].numpy(), [[4, 6], [8, 10]])
    idx = pt.to_tensor(np.array([0, 2]))
    np.testing.assert_allclose(x[idx].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])


def test_setitem():
    x = pt.to_tensor(np.zeros((3, 3), np.float32))
    x[1] = 5.0
    np.testing.assert_allclose(x.numpy()[1], [5, 5, 5])
    x[0, 0] = -1.0
    assert x.numpy()[0, 0] == -1


def test_backward_simple():
    x = pt.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_backward_chain_and_accumulate():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    z = y * 3 + y
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])
    # second backward accumulates
    w = (x * 5.0)
    w.backward()
    np.testing.assert_allclose(x.grad.numpy(), [13.0])


def test_multi_output_grad():
    x = pt.to_tensor(np.arange(6.0, dtype=np.float32), stop_gradient=False)
    parts = pt.split(x, 3)
    loss = parts[0].sum() + (parts[2] * 2).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 1, 0, 0, 2, 2])


def test_no_grad():
    x = pt.to_tensor([1.0], stop_gradient=False)
    with pt.no_grad():
        y = x * 2
    assert y._node is None
    z = x * 2
    assert z._node is not None


def test_grad_api():
    x = pt.to_tensor([3.0], stop_gradient=False)
    y = x * x
    (g,) = pt.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [6.0])
    assert x.grad is None  # .grad slot untouched


def test_register_hook():
    x = pt.to_tensor([1.0], stop_gradient=False)
    x.register_hook(lambda g: g * 10)
    (x * 2.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [20.0])


def test_detach():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    assert y.stop_gradient
    z = x * 2
    loss = (z + y).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_retain_grads():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y.stop_gradient = False
    y.retain_grads()
    (y * 3).sum().backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0])


def test_inplace_ops():
    x = pt.to_tensor([1.0, 2.0])
    x.add_(pt.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.numpy(), [2, 3])
    x.scale_(2.0)
    np.testing.assert_allclose(x.numpy(), [4, 6])


def test_pytree_registration():
    import jax
    x = pt.to_tensor([1.0, 2.0])
    leaves, treedef = jax.tree.flatten(x)
    assert len(leaves) == 1
    y = jax.tree.unflatten(treedef, leaves)
    assert isinstance(y, Tensor)


def test_random_seed_reproducible():
    pt.seed(7)
    a = pt.rand([4])
    pt.seed(7)
    b = pt.rand([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())


def test_nan_check_flag():
    pt.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = pt.to_tensor([1.0, 0.0])
        with pytest.raises(FloatingPointError):
            pt.log(x * 0.0 - 1.0)  # log(-1) = nan
    finally:
        pt.set_flags({"FLAGS_check_nan_inf": False})
