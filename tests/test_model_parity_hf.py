"""Cross-framework model parity: our GPT/Llama vs HuggingFace (torch CPU).

The strongest correctness evidence for a model family is bit-level
agreement with an independent trusted implementation under identical
weights (the reference does this with OpTest numpy refs per op,
test/legacy_test/op_test.py:2910; this is the model-level analog).
Weights are mapped HF -> paddle_tpu and logits compared in f32.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

ATOL = 1e-3   # f32 end-to-end over 2 layers; observed max err ~1e-4


def _to_np(t):
    return t.detach().cpu().numpy()


class TestGPT2Parity:
    def test_logits_match_hf_gpt2(self):
        import torch
        from transformers import GPT2Config, GPT2LMHeadModel
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        V, h, L, H, S = 128, 64, 2, 4, 32
        d = h // H
        torch.manual_seed(0)
        hf = GPT2LMHeadModel(GPT2Config(
            vocab_size=V, n_positions=S, n_embd=h, n_layer=L, n_head=H,
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
            activation_function="gelu_new")).eval()

        ours = GPTForCausalLM(GPTConfig(
            vocab_size=V, hidden_size=h, num_layers=L, num_heads=H,
            max_position_embeddings=S, dropout=0.0, dtype="float32"))

        hsd = hf.state_dict()
        # our qkv layout is per-head [q_i|k_i|v_i]; HF c_attn is [q|k|v]
        perm = np.concatenate(
            [np.concatenate([np.arange(i * d, (i + 1) * d) + s * h
                             for s in range(3)]) for i in range(H)])
        sd = {"gpt.wte.weight": _to_np(hsd["transformer.wte.weight"]),
              "gpt.wpe.weight": _to_np(hsd["transformer.wpe.weight"]),
              "gpt.ln_f.weight": _to_np(hsd["transformer.ln_f.weight"]),
              "gpt.ln_f.bias": _to_np(hsd["transformer.ln_f.bias"])}
        for i in range(L):
            p = f"transformer.h.{i}."
            q = f"gpt.blocks.{i}."
            sd[q + "ln1.weight"] = _to_np(hsd[p + "ln_1.weight"])
            sd[q + "ln1.bias"] = _to_np(hsd[p + "ln_1.bias"])
            sd[q + "ln2.weight"] = _to_np(hsd[p + "ln_2.weight"])
            sd[q + "ln2.bias"] = _to_np(hsd[p + "ln_2.bias"])
            # HF Conv1D stores [in, out] like our Linear: no transpose
            sd[q + "qkv.weight"] = _to_np(hsd[p + "attn.c_attn.weight"])[:, perm]
            sd[q + "qkv.bias"] = _to_np(hsd[p + "attn.c_attn.bias"])[perm]
            sd[q + "proj.weight"] = _to_np(hsd[p + "attn.c_proj.weight"])
            sd[q + "proj.bias"] = _to_np(hsd[p + "attn.c_proj.bias"])
            sd[q + "fc1.weight"] = _to_np(hsd[p + "mlp.c_fc.weight"])
            sd[q + "fc1.bias"] = _to_np(hsd[p + "mlp.c_fc.bias"])
            sd[q + "fc2.weight"] = _to_np(hsd[p + "mlp.c_proj.weight"])
            sd[q + "fc2.bias"] = _to_np(hsd[p + "mlp.c_proj.bias"])
        missing = set(ours.state_dict()) - set(sd)
        assert not missing, f"unmapped params: {missing}"
        ours.set_state_dict(sd)
        ours.eval()

        import paddle_tpu as paddle
        ids = np.random.default_rng(0).integers(0, V, (2, S))
        ref = _to_np(hf(torch.tensor(ids)).logits)
        got = np.asarray(ours(paddle.to_tensor(ids.astype("int64"))).numpy())
        err = np.max(np.abs(got - ref))
        assert err < ATOL, f"GPT-2 logits diverge: max err {err}"

    def test_loss_matches_hf(self):
        # spot-check the LM loss path too (shifted-label convention is
        # ours: labels pre-shifted by the caller)
        import torch
        from transformers import GPT2Config, GPT2LMHeadModel
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        import paddle_tpu as paddle

        V, h, L, H, S = 64, 32, 1, 2, 16
        torch.manual_seed(1)
        hf = GPT2LMHeadModel(GPT2Config(
            vocab_size=V, n_positions=S, n_embd=h, n_layer=L, n_head=H,
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)).eval()
        ours = GPTForCausalLM(GPTConfig(
            vocab_size=V, hidden_size=h, num_layers=L, num_heads=H,
            max_position_embeddings=S, dropout=0.0, dtype="float32"))
        d = h // H
        perm = np.concatenate(
            [np.concatenate([np.arange(i * d, (i + 1) * d) + s * h
                             for s in range(3)]) for i in range(H)])
        hsd = hf.state_dict()
        sd = {"gpt.wte.weight": _to_np(hsd["transformer.wte.weight"]),
              "gpt.wpe.weight": _to_np(hsd["transformer.wpe.weight"]),
              "gpt.ln_f.weight": _to_np(hsd["transformer.ln_f.weight"]),
              "gpt.ln_f.bias": _to_np(hsd["transformer.ln_f.bias"]),
              "gpt.blocks.0.ln1.weight": _to_np(hsd["transformer.h.0.ln_1.weight"]),
              "gpt.blocks.0.ln1.bias": _to_np(hsd["transformer.h.0.ln_1.bias"]),
              "gpt.blocks.0.ln2.weight": _to_np(hsd["transformer.h.0.ln_2.weight"]),
              "gpt.blocks.0.ln2.bias": _to_np(hsd["transformer.h.0.ln_2.bias"]),
              "gpt.blocks.0.qkv.weight": _to_np(hsd["transformer.h.0.attn.c_attn.weight"])[:, perm],
              "gpt.blocks.0.qkv.bias": _to_np(hsd["transformer.h.0.attn.c_attn.bias"])[perm],
              "gpt.blocks.0.proj.weight": _to_np(hsd["transformer.h.0.attn.c_proj.weight"]),
              "gpt.blocks.0.proj.bias": _to_np(hsd["transformer.h.0.attn.c_proj.bias"]),
              "gpt.blocks.0.fc1.weight": _to_np(hsd["transformer.h.0.mlp.c_fc.weight"]),
              "gpt.blocks.0.fc1.bias": _to_np(hsd["transformer.h.0.mlp.c_fc.bias"]),
              "gpt.blocks.0.fc2.weight": _to_np(hsd["transformer.h.0.mlp.c_proj.weight"]),
              "gpt.blocks.0.fc2.bias": _to_np(hsd["transformer.h.0.mlp.c_proj.bias"])}
        ours.set_state_dict(sd)
        ours.eval()
        ids = np.random.default_rng(1).integers(0, V, (2, S))
        import torch as t
        hf_loss = float(hf(t.tensor(ids), labels=t.tensor(ids)).loss)
        labels = np.roll(ids, -1, 1)
        loss = ours(paddle.to_tensor(ids.astype("int64")),
                    labels=paddle.to_tensor(labels.astype("int64")))
        # HF drops the last position (shift-inside); ours scores all S
        # positions against pre-shifted labels — compare on the common
        # S-1 prefix by rescaling
        got = float(np.asarray(loss.numpy()))
        full = got * S                      # sum over S positions
        # recompute our sum without the final (wrapped) position
        logits = np.asarray(ours(paddle.to_tensor(ids.astype("int64"))).numpy())
        lp = logits - logits.max(-1, keepdims=True)
        lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
        nll = -np.take_along_axis(lp, labels[..., None], -1)[..., 0]
        ours_prefix = nll[:, :-1].mean()
        assert abs(ours_prefix - hf_loss) < 2e-3, (ours_prefix, hf_loss, got, full)


class TestBertParity:
    def test_hidden_states_match_hf_bert(self):
        import torch
        from transformers import BertConfig as HFBertConfig
        from transformers import BertModel as HFBert
        from paddle_tpu.models.bert import BertConfig, BertModel
        import paddle_tpu as paddle

        V, h, f, L, H, S = 128, 64, 128, 2, 4, 32
        torch.manual_seed(0)
        hf = HFBert(HFBertConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f,
            num_hidden_layers=L, num_attention_heads=H,
            max_position_embeddings=S, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0, hidden_act="gelu",
            attn_implementation="eager")).eval()

        ours = BertModel(BertConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f, num_layers=L,
            num_heads=H, max_position_embeddings=S, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0, hidden_act="gelu"))

        hsd = hf.state_dict()
        sd = {
            "embeddings.word_embeddings.weight":
                _to_np(hsd["embeddings.word_embeddings.weight"]),
            "embeddings.position_embeddings.weight":
                _to_np(hsd["embeddings.position_embeddings.weight"]),
            "embeddings.token_type_embeddings.weight":
                _to_np(hsd["embeddings.token_type_embeddings.weight"]),
            "embeddings.layer_norm.weight":
                _to_np(hsd["embeddings.LayerNorm.weight"]),
            "embeddings.layer_norm.bias":
                _to_np(hsd["embeddings.LayerNorm.bias"]),
            "pooler.dense.weight": _to_np(hsd["pooler.dense.weight"]).T,
            "pooler.dense.bias": _to_np(hsd["pooler.dense.bias"]),
        }
        lin = {  # HF name -> ours (torch Linear [out,in] -> ours [in,out])
            "attention.self.query": "self_attn.q_proj",
            "attention.self.key": "self_attn.k_proj",
            "attention.self.value": "self_attn.v_proj",
            "attention.output.dense": "self_attn.out_proj",
            "intermediate.dense": "linear1",
            "output.dense": "linear2",
        }
        lns = {"attention.output.LayerNorm": "norm1", "output.LayerNorm": "norm2"}
        for i in range(L):
            p = f"encoder.layer.{i}."
            q = f"encoder.layers.{i}."
            for src, dst in lin.items():
                sd[q + dst + ".weight"] = _to_np(hsd[p + src + ".weight"]).T
                sd[q + dst + ".bias"] = _to_np(hsd[p + src + ".bias"])
            for src, dst in lns.items():
                sd[q + dst + ".weight"] = _to_np(hsd[p + src + ".weight"])
                sd[q + dst + ".bias"] = _to_np(hsd[p + src + ".bias"])
        missing = set(ours.state_dict()) - set(sd)
        assert not missing, f"unmapped params: {missing}"
        ours.set_state_dict(sd)
        ours.eval()

        ids = np.random.default_rng(3).integers(0, V, (2, S))
        ref = _to_np(hf(torch.tensor(ids)).last_hidden_state)
        seq, pooled = ours(paddle.to_tensor(ids.astype("int64")))
        got = np.asarray(seq.numpy())
        err = np.max(np.abs(got - ref))
        assert err < ATOL, f"BERT hidden states diverge: max err {err}"
        ref_pooled = _to_np(hf(torch.tensor(ids)).pooler_output)
        errp = np.max(np.abs(np.asarray(pooled.numpy()) - ref_pooled))
        assert errp < ATOL, f"BERT pooler diverges: max err {errp}"


class TestRopeScalingParity:
    @pytest.mark.parametrize("scaling", [
        {"rope_type": "linear", "factor": 2.0},
        {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
         "high_freq_factor": 4.0,
         "original_max_position_embeddings": 16},
    ])
    def test_logits_match_hf_llama_with_rope_scaling(self, scaling):
        """Long-context RoPE scaling (linear position interpolation and
        llama3 per-frequency wavelength interpolation) must match the HF
        implementation bitwise-close under identical weights."""
        import torch
        from transformers import LlamaConfig as HFLlamaConfig
        from transformers import LlamaForCausalLM as HFLlama
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        import paddle_tpu as paddle

        V, h, f, L, H, KV, S = 128, 64, 128, 2, 4, 2, 32
        torch.manual_seed(0)
        hf = HFLlama(HFLlamaConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f,
            num_hidden_layers=L, num_attention_heads=H,
            num_key_value_heads=KV, max_position_embeddings=S,
            rope_theta=10000.0, rms_norm_eps=1e-5,
            rope_scaling=dict(scaling), tie_word_embeddings=False,
            attn_implementation="eager")).eval()

        ours = LlamaForCausalLM(LlamaConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f, num_layers=L,
            num_heads=H, num_kv_heads=KV, max_position_embeddings=S,
            rope_theta=10000.0, rms_norm_eps=1e-5, dtype="float32",
            rope_scaling=dict(scaling)))

        hsd = hf.state_dict()
        sd = {"llama.embed_tokens.weight":
              _to_np(hsd["model.embed_tokens.weight"]),
              "llama.norm.weight": _to_np(hsd["model.norm.weight"]),
              "lm_head.weight": _to_np(hsd["lm_head.weight"]).T}
        for i in range(L):
            p = f"model.layers.{i}."
            q = f"llama.layers.{i}."
            sd[q + "input_layernorm.weight"] = \
                _to_np(hsd[p + "input_layernorm.weight"])
            sd[q + "post_attention_layernorm.weight"] = \
                _to_np(hsd[p + "post_attention_layernorm.weight"])
            for w in ("self_attn.q_proj", "self_attn.k_proj",
                      "self_attn.v_proj", "self_attn.o_proj",
                      "mlp.gate_proj", "mlp.up_proj", "mlp.down_proj"):
                sd[q + w + ".weight"] = _to_np(hsd[p + w + ".weight"]).T
        ours.set_state_dict(sd)
        ours.eval()

        ids = np.random.default_rng(4).integers(0, V, (2, S))
        ref = _to_np(hf(torch.tensor(ids)).logits)
        got = np.asarray(ours(paddle.to_tensor(ids.astype("int64"))).numpy())
        err = np.max(np.abs(got - ref))
        assert err < ATOL, \
            f"rope-scaled logits diverge ({scaling['rope_type']}): {err}"


class TestMixtralParity:
    def test_logits_match_hf_mixtral_moe(self):
        """Sparse-MoE cross-framework pin: our Llama-MoE (GShard-style
        renormalized top-k over full-softmax probs) equals Mixtral's
        softmax-over-top-k-logits EXACTLY when no token drops —
        exp(l_i)/sum_topk exp(l_j) is the same ratio either way — so with
        capacity_factor = E/k (capacity == T) the two implementations
        must agree to fp tolerance under identical weights."""
        import torch
        from transformers import MixtralConfig as HFMixtralConfig
        from transformers import MixtralForCausalLM as HFMixtral
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        import paddle_tpu as paddle

        V, h, f, L, H, KV, S, E, K = 128, 64, 128, 2, 4, 2, 32, 4, 2
        torch.manual_seed(0)
        hf = HFMixtral(HFMixtralConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f,
            num_hidden_layers=L, num_attention_heads=H,
            num_key_value_heads=KV, max_position_embeddings=S,
            num_local_experts=E, num_experts_per_tok=K,
            rope_theta=10000.0, rms_norm_eps=1e-5,
            tie_word_embeddings=False,
            attn_implementation="eager")).eval()

        ours = LlamaForCausalLM(LlamaConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f, num_layers=L,
            num_heads=H, num_kv_heads=KV, max_position_embeddings=S,
            rope_theta=10000.0, rms_norm_eps=1e-5, dtype="float32",
            moe_num_experts=E, moe_top_k=K,
            moe_capacity_factor=float(E) / K))   # capacity == T: no drops

        hsd = hf.state_dict()
        sd = {"llama.embed_tokens.weight":
              _to_np(hsd["model.embed_tokens.weight"]),
              "llama.norm.weight": _to_np(hsd["model.norm.weight"]),
              "lm_head.weight": _to_np(hsd["lm_head.weight"]).T}
        for i in range(L):
            p = f"model.layers.{i}."
            q = f"llama.layers.{i}."
            sd[q + "input_layernorm.weight"] = \
                _to_np(hsd[p + "input_layernorm.weight"])
            sd[q + "post_attention_layernorm.weight"] = \
                _to_np(hsd[p + "post_attention_layernorm.weight"])
            for w in ("self_attn.q_proj", "self_attn.k_proj",
                      "self_attn.v_proj", "self_attn.o_proj"):
                sd[q + w + ".weight"] = _to_np(hsd[p + w + ".weight"]).T
            moe = p + "block_sparse_moe."
            sd[q + "mlp.router_w"] = _to_np(hsd[moe + "gate.weight"]).T
            # HF experts: w1 = gate [f, h], w3 = up [f, h], w2 = down [h, f]
            sd[q + "mlp.e_gate"] = np.stack(
                [_to_np(hsd[f"{moe}experts.{e}.w1.weight"]).T
                 for e in range(E)])
            sd[q + "mlp.e_up"] = np.stack(
                [_to_np(hsd[f"{moe}experts.{e}.w3.weight"]).T
                 for e in range(E)])
            sd[q + "mlp.e_down"] = np.stack(
                [_to_np(hsd[f"{moe}experts.{e}.w2.weight"]).T
                 for e in range(E)])
        missing = set(ours.state_dict()) - set(sd)
        assert not missing, f"unmapped params: {missing}"
        ours.set_state_dict(sd)
        ours.eval()

        ids = np.random.default_rng(3).integers(0, V, (2, S))
        ref = _to_np(hf(torch.tensor(ids)).logits)
        got = np.asarray(ours(paddle.to_tensor(ids.astype("int64"))).numpy())
        err = np.max(np.abs(got - ref))
        assert err < ATOL, f"Mixtral logits diverge: max err {err}"


class TestLlamaParity:
    def test_logits_match_hf_llama_gqa(self):
        import torch
        from transformers import LlamaConfig as HFLlamaConfig
        from transformers import LlamaForCausalLM as HFLlama
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        import paddle_tpu as paddle

        V, h, f, L, H, KV, S = 128, 64, 128, 2, 4, 2, 32
        torch.manual_seed(0)
        hf = HFLlama(HFLlamaConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f,
            num_hidden_layers=L, num_attention_heads=H,
            num_key_value_heads=KV, max_position_embeddings=S,
            rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
            attn_implementation="eager")).eval()

        ours = LlamaForCausalLM(LlamaConfig(
            vocab_size=V, hidden_size=h, intermediate_size=f, num_layers=L,
            num_heads=H, num_kv_heads=KV, max_position_embeddings=S,
            rope_theta=10000.0, rms_norm_eps=1e-5, dtype="float32"))

        hsd = hf.state_dict()
        sd = {"llama.embed_tokens.weight": _to_np(hsd["model.embed_tokens.weight"]),
              "llama.norm.weight": _to_np(hsd["model.norm.weight"]),
              "lm_head.weight": _to_np(hsd["lm_head.weight"]).T}
        for i in range(L):
            p = f"model.layers.{i}."
            q = f"llama.layers.{i}."
            sd[q + "input_layernorm.weight"] = _to_np(hsd[p + "input_layernorm.weight"])
            sd[q + "post_attention_layernorm.weight"] = \
                _to_np(hsd[p + "post_attention_layernorm.weight"])
            for w in ("self_attn.q_proj", "self_attn.k_proj",
                      "self_attn.v_proj", "self_attn.o_proj",
                      "mlp.gate_proj", "mlp.up_proj", "mlp.down_proj"):
                # torch Linear stores [out, in]; ours [in, out]
                sd[q + w + ".weight"] = _to_np(hsd[p + w + ".weight"]).T
        missing = set(ours.state_dict()) - set(sd)
        assert not missing, f"unmapped params: {missing}"
        ours.set_state_dict(sd)
        ours.eval()

        ids = np.random.default_rng(2).integers(0, V, (2, S))
        ref = _to_np(hf(torch.tensor(ids)).logits)
        got = np.asarray(ours(paddle.to_tensor(ids.astype("int64"))).numpy())
        err = np.max(np.abs(got - ref))
        assert err < ATOL, f"Llama logits diverge: max err {err}"
