"""kernellint unit tests: the cost model, the pallas_call extractor,
per-rule fixtures, suppressions, and the CLI lane.

Fixture files under tests/kernellint_fixtures/ are ANALYZED, never
imported (the KL006 pair lives under an ops/pallas/ subpath because
that rule is scoped to kernel modules).  CPU-only, no jax execution.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.analysis import core
from paddle_tpu.analysis.kernel import cost
from paddle_tpu.analysis.kernel.extract import extract_sites

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "kernellint_fixtures")
REPO = os.path.dirname(HERE)

KL_IDS = ("KL001", "KL002", "KL003", "KL004", "KL005", "KL006")

_FIXTURE_PATHS = {
    "KL006": os.path.join("ops", "pallas"),
}


def fixture_path(rid, kind):
    sub = _FIXTURE_PATHS.get(rid, "")
    return os.path.join(FIXTURES, sub, f"{rid.lower()}_{kind}.py")


def run_fixture(rid, kind):
    return core.run([fixture_path(rid, kind)], select={rid})


# -- registry -----------------------------------------------------------

def test_kl_rules_registered_with_metadata():
    ids = [r.id for r in core.all_rules()]
    for rid in KL_IDS:
        assert rid in ids
    for rule in core.all_rules():
        if rule.id.startswith("KL"):
            assert rule.severity in core.SEVERITIES
            assert rule.doc and rule.hint and rule.name


# -- cost model ---------------------------------------------------------

def test_itemsize_accepts_strings_and_reprs():
    assert cost.itemsize("float32") == 4
    assert cost.itemsize("bfloat16") == 2
    assert cost.itemsize("int8") == 1
    with pytest.raises(ValueError):
        cost.itemsize("not_a_dtype")


def test_budget_reproduces_hand_constant():
    # 0.75 * 16 MB == the pre-ISSUE-10 VMEM_BUDGET_BYTES
    assert cost.budget_bytes() == 12 * 2 ** 20
    assert cost.fits(12 * 2 ** 20)
    assert not cost.fits(12 * 2 ** 20 + 1)


def test_decode_block_vmem_breakdown_adds_up():
    est = cost.decode_block_vmem(
        hidden=64, num_heads=4, kv_heads=2, head_dim=16, block_size=8,
        pages=2, weight_bytes=1000, pool_itemsize=2, x_itemsize=4)
    assert est["total"] == (est["weights"] + est["staging"]
                            + est["scratch"] + est["io"])
    # double-buffered: DMA_STAGING_SLOTS revolving copies of k+v pages
    assert est["staging"] == cost.DMA_STAGING_SLOTS * 2 * 2 * 8 * 2 * 16 * 2
    # doubling pages doubles ONLY staging
    est2 = cost.decode_block_vmem(
        hidden=64, num_heads=4, kv_heads=2, head_dim=16, block_size=8,
        pages=4, weight_bytes=1000, pool_itemsize=2, x_itemsize=4)
    assert est2["total"] - est["total"] == est["staging"]


def test_linear_ce_vmem_scales_with_blocks():
    small = cost.linear_ce_vmem(block_rows=128, chunk=512, hidden=256)
    big = cost.linear_ce_vmem(block_rows=512, chunk=2048, hidden=256)
    assert big["total"] > small["total"]
    assert cost.linear_ce_fits(128, 512, 256)
    assert not cost.linear_ce_fits(512, 2048, 8192)


# -- extractor ----------------------------------------------------------

def test_extractor_models_real_kernels():
    mod = core.load_module(os.path.join(
        REPO, "paddle_tpu", "ops", "pallas", "linear_ce.py"))
    sites = extract_sites(mod)
    assert len(sites) == 3                      # fwd, dx, dw
    fwd = sites[0]
    assert fwd.grid_rank == 2
    assert fwd.grid_has_cdiv                    # nv = pl.cdiv(V, C)
    assert fwd.kernel_name == "_fwd_kernel"
    assert len(fwd.in_specs) == 3 and fwd.in_specs_complete
    assert [s.index_map_arity for s in fwd.in_specs] == [2, 2, 2]
    assert len(fwd.scratch) == 4                # [VMEM(...)] * 4 folds
    assert all(s.kind == "vmem" and s.dtype == "float32"
               for s in fwd.scratch)


def test_extractor_handles_decode_block_megakernel():
    mod = core.load_module(os.path.join(
        REPO, "paddle_tpu", "ops", "pallas", "decode_block.py"))
    sites = extract_sites(mod)
    assert len(sites) == 1
    site = sites[0]
    assert site.grid_rank == 2
    assert site.grid_has_cdiv                   # nt = -(-mb // pages)
    assert site.kernel_name == "_kernel"
    assert not site.in_specs_complete           # *[wspec(...)] splat
    smem = [s for s in site.in_specs if s.memory_space == "smem"]
    anys = [s for s in site.in_specs if s.memory_space == "any"]
    assert len(smem) == 2 and len(anys) == 2    # tables + pools
    assert any(s.kind == "sem" for s in site.scratch)


def test_const_env_folds_module_and_local_names():
    import ast
    from paddle_tpu.analysis.kernel.extract import ConstEnv
    src = textwrap.dedent("""
        BM, BK = 256, 512
        TWO = 2
        def f(M):
            bm = min(BM, max(8, M))
            bk = BK // TWO
            pair = (bm, bk)
    """)
    mod = core.Module("x.py", "x.py", src, ast.parse(src))
    env = ConstEnv(mod, mod.functions["f"])
    assert env.lookup("bk") == 256
    assert env.lookup("bm") is None             # M is runtime -> unproven
    assert env.lookup("BM") == 256


# -- per-rule fixtures --------------------------------------------------

@pytest.mark.parametrize("rid", KL_IDS)
def test_rule_fires_on_positive_fixture(rid):
    findings = run_fixture(rid, "pos")
    assert findings, f"{rid} found nothing in its positive fixture"
    assert {f.rule for f in findings} == {rid}


@pytest.mark.parametrize("rid", KL_IDS)
def test_rule_quiet_on_negative_fixture(rid):
    findings = run_fixture(rid, "neg")
    assert not findings, [f.format() for f in findings]


def test_kl001_message_names_the_bound():
    findings = run_fixture("KL001", "pos")
    assert any("MB" in f.message and "budget" in f.message
               for f in findings)
    assert all(f.severity == "error" for f in findings)


def test_kl002_catches_all_three_shapes():
    findings = run_fixture("KL002", "pos")
    msgs = " ".join(f.message for f in findings)
    assert "arg(s) but the grid has rank" in msgs
    assert "coordinate(s) for a rank-" in msgs
    assert "program_id(2)" in msgs
    assert len(findings) == 3


def test_kl005_key_drift(tmp_path):
    drift = tmp_path / "drifting.py"
    drift.write_text(textwrap.dedent("""
        from paddle_tpu.ops.pallas.autotune import lookup, pick
        def tune(key, cands, run, args):
            return pick("flash_fwd2", key, cands, run, args, cands[0])
        def traced(key):
            return lookup("flash_fwd", key, None)
    """))
    findings = core.run([str(drift)], select={"KL005"})
    assert len(findings) == 1
    assert "key drift" in findings[0].message


def test_kernellint_suppression_alias(tmp_path):
    bad = tmp_path / "suppressed.py"
    bad.write_text(textwrap.dedent("""
        from jax.experimental import pallas as pl
        import jax.numpy as jnp
        import jax

        def _kernel(x_ref, o_ref):
            o_ref[:] = x_ref[:]

        def f(x):
            # tile overhang folds into a copy, reviewed: harmless here
            return pl.pallas_call(  # kernellint: disable=KL003
                _kernel,
                grid=(pl.cdiv(x.shape[0], 8),),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            )(x)
    """))
    assert core.run([str(bad)], select={"KL003"}) == []


# -- the CLI lane -------------------------------------------------------

def test_cli_select_kl_prefix_expands():
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--select", "KL",
         "--no-baseline", "--json",
         os.path.join(FIXTURES, "ops", "pallas", "kl006_pos.py")],
        capture_output=True, text=True, cwd=REPO)
    import json
    payload = json.loads(proc.stdout)
    assert proc.returncode == 1
    assert set(payload["counts"]) == {"KL006"}


def test_cli_kl_lane_clean_on_ops_pallas():
    """The ISSUE 10 acceptance command: `python -m paddle_tpu.analysis
    --select KL ops/pallas/` runs clean against the committed (empty)
    KERNELLINT.md ledger."""
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--select", "KL",
         os.path.join("paddle_tpu", "ops", "pallas")],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 above baseline" in proc.stdout
