"""Op-form nn kernels (reference phi ops: pool2d/conv2d/*_interp/
spectral_norm/hsigmoid_loss/fractional pools/pad3d/...; test model
test/legacy_test/test_pool2d_op.py etc.)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


rng = np.random.default_rng(0)


class TestPoolConvForms:
    def test_pool2d_forms(self):
        x = rng.normal(size=(1, 2, 6, 6)).astype(np.float32)
        mx = _np(pt.pool2d(pt.Tensor(x), kernel_size=2, stride=2,
                           pooling_type="max"))
        av = _np(pt.pool2d(pt.Tensor(x), kernel_size=2, stride=2,
                           pooling_type="avg"))
        assert mx.shape == av.shape == (1, 2, 3, 3)
        assert (mx >= av - 1e-6).all()
        g = _np(pt.pool2d(pt.Tensor(x), pooling_type="avg",
                          global_pooling=True))
        np.testing.assert_allclose(g[..., 0, 0], x.mean(axis=(2, 3)),
                                   rtol=1e-6)
        a = _np(pt.pool2d(pt.Tensor(x), kernel_size=3, adaptive=True,
                          pooling_type="avg"))
        assert a.shape == (1, 2, 3, 3)

    def test_conv_forms(self):
        x = rng.normal(size=(1, 3, 8, 8)).astype(np.float32)
        w = rng.normal(size=(6, 3, 3, 3)).astype(np.float32)
        out = _np(pt.conv2d(pt.Tensor(x), pt.Tensor(w), padding=1))
        assert out.shape == (1, 6, 8, 8)
        wd = rng.normal(size=(3, 1, 3, 3)).astype(np.float32)
        dw = _np(pt.depthwise_conv2d(pt.Tensor(x), pt.Tensor(wd), padding=1))
        assert dw.shape == (1, 3, 8, 8)
        wt = rng.normal(size=(3, 4, 2, 2)).astype(np.float32)
        tr = _np(pt.conv2d_transpose(pt.Tensor(x), pt.Tensor(wt), stride=2))
        assert tr.shape == (1, 4, 16, 16)

    def test_max_pool3d_with_index(self):
        x = rng.normal(size=(1, 1, 4, 4, 4)).astype(np.float32)
        out, idx = pt.max_pool3d_with_index(pt.Tensor(x), kernel_size=2,
                                            stride=2)
        assert _np(out).shape == (1, 1, 2, 2, 2)
        assert _np(idx).shape == (1, 1, 2, 2, 2)

    def test_fractional_max_pool2d(self):
        x = np.arange(49, dtype=np.float32).reshape(1, 1, 7, 7)
        out = _np(pt.fractional_max_pool2d(pt.Tensor(x), output_size=3))
        assert out.shape == (1, 1, 3, 3)
        # windows are disjoint and cover the input: last bin holds the max
        assert out[0, 0, 2, 2] == 48.0
        # constant input pools to the constant
        c = _np(pt.fractional_max_pool2d(
            pt.Tensor(np.full((1, 1, 7, 7), 2.5, np.float32)), 3))
        np.testing.assert_allclose(c, 2.5)

    def test_unpool3d_roundtrip(self):
        x = rng.normal(size=(1, 1, 4, 4, 4)).astype(np.float32)
        out, idx = pt.max_pool3d_with_index(pt.Tensor(x), 2, 2)
        up = _np(pt.unpool3d(out, idx, 2, 2))
        assert up.shape == (1, 1, 4, 4, 4)
        # scattered values are exactly the pooled maxima
        np.testing.assert_allclose(np.sort(up[up != 0]),
                                   np.sort(_np(out).ravel()))


class TestInterpNorm:
    def test_interp_ops(self):
        x = rng.normal(size=(1, 2, 4, 4)).astype(np.float32)
        for op, sz in [(pt.bilinear_interp, (8, 8)),
                       (pt.nearest_interp, (8, 8)),
                       (pt.bicubic_interp, (8, 8))]:
            out = _np(op(pt.Tensor(x), size=sz))
            assert out.shape == (1, 2, 8, 8)
        x1 = rng.normal(size=(1, 2, 4)).astype(np.float32)
        assert _np(pt.linear_interp(pt.Tensor(x1), size=(8,),
                                    data_format="NCL")).shape == (1, 2, 8)
        x3 = rng.normal(size=(1, 2, 4, 4, 4)).astype(np.float32)
        assert _np(pt.trilinear_interp(
            pt.Tensor(x3), size=(8, 8, 8))).shape == (1, 2, 8, 8, 8)

    def test_norm_op_forms(self):
        x = rng.normal(size=(2, 4, 3, 3)).astype(np.float32)
        ln = _np(pt.layer_norm(pt.Tensor(x), begin_norm_axis=1))
        np.testing.assert_allclose(ln.reshape(2, -1).mean(-1), 0.0,
                                   atol=1e-5)
        gn = _np(pt.group_norm(pt.Tensor(x), groups=2))
        assert gn.shape == x.shape
        inn = _np(pt.instance_norm(pt.Tensor(x)))
        np.testing.assert_allclose(inn.mean(axis=(2, 3)), 0.0, atol=1e-5)

    def test_spectral_norm(self):
        w = rng.normal(size=(4, 6)).astype(np.float32)
        u = rng.normal(size=(4,)).astype(np.float32)
        v = rng.normal(size=(6,)).astype(np.float32)
        out = _np(pt.spectral_norm(pt.Tensor(w), pt.Tensor(u), pt.Tensor(v),
                                   power_iters=20))
        # after normalization the top singular value is ~1
        s = np.linalg.svd(out, compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)

    def test_sync_batch_norm_single(self):
        x = rng.normal(size=(4, 3, 2, 2)).astype(np.float32)
        m = np.zeros(3, np.float32)
        va = np.ones(3, np.float32)
        y, nm, nv = pt.sync_batch_norm_(pt.Tensor(x), pt.Tensor(m),
                                        pt.Tensor(va), None, None)
        np.testing.assert_allclose(_np(y).mean(axis=(0, 2, 3)), 0.0,
                                   atol=1e-5)
        # running stats move toward batch stats
        assert not np.allclose(_np(nm), m)


class TestMiscNN:
    def test_pad3d_modes(self):
        x = np.arange(8, dtype=np.float32).reshape(1, 1, 2, 2, 2)
        out = _np(pt.pad3d(pt.Tensor(x), [1, 1, 0, 0, 0, 0], value=9.0))
        assert out.shape == (1, 1, 2, 2, 4)
        assert out[0, 0, 0, 0, 0] == 9.0
        r = _np(pt.pad3d(pt.Tensor(x), [1, 1, 1, 1, 1, 1], mode="reflect"))
        assert r.shape == (1, 1, 4, 4, 4)

    def test_hsigmoid_loss_learns_sign(self):
        # loss is differentiable and positive; grad check vs finite diff
        x = rng.normal(size=(5, 3)).astype(np.float32)
        lab = np.array([0, 1, 2, 3, 1], np.int64)
        w = rng.normal(size=(3, 3)).astype(np.float32) * 0.1
        b = np.zeros(3, np.float32)
        raw = pt.ops.get_op("hsigmoid_loss").fn.raw
        loss = raw(x, lab, w, b, num_classes=4)
        assert loss.shape == (5, 1) and (np.asarray(loss) > 0).all()
        g = jax.grad(lambda ww: raw(x, lab, ww, b, num_classes=4).sum())(w)
        eps = 1e-3
        w2 = w.copy()
        w2[0, 0] += eps
        fd = (np.asarray(raw(x, lab, w2, b, num_classes=4)).sum()
              - np.asarray(raw(x, lab, w, b, num_classes=4)).sum()) / eps
        np.testing.assert_allclose(np.asarray(g)[0, 0], fd, rtol=1e-2,
                                   atol=1e-3)

    def test_clip_by_norm(self):
        x = np.ones(16, np.float32) * 2.0          # norm = 8
        out = _np(pt.clip_by_norm(pt.Tensor(x), 4.0))
        np.testing.assert_allclose(np.linalg.norm(out), 4.0, rtol=1e-5)
        small = np.ones(4, np.float32) * 0.1
        np.testing.assert_allclose(_np(pt.clip_by_norm(pt.Tensor(small),
                                                       4.0)), small)

    def test_fused_softmax_masks(self):
        x = rng.normal(size=(1, 1, 4, 4)).astype(np.float32)
        m = np.where(np.arange(4)[None, None, None] > 1, -1e9,
                     0.0).astype(np.float32)
        out = _np(pt.fused_softmax_mask(pt.Tensor(x), pt.Tensor(m)))
        np.testing.assert_allclose(out[..., 2:].sum(), 0.0, atol=1e-6)
        tri = _np(pt.fused_softmax_mask_upper_triangle(pt.Tensor(x)))
        assert tri[0, 0, 0, 1] == pytest.approx(0.0, abs=1e-6)
        np.testing.assert_allclose(tri.sum(-1), 1.0, rtol=1e-5)

    def test_cross_entropy_with_softmax_op(self):
        logits = rng.normal(size=(4, 7)).astype(np.float32)
        lab = np.array([[1], [2], [3], [0]], np.int64)
        out = _np(pt.cross_entropy_with_softmax(pt.Tensor(logits),
                                                pt.Tensor(lab)))
        ref = -np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
        np.testing.assert_allclose(
            out.ravel(), ref[np.arange(4), lab.ravel()], rtol=1e-5)


class TestAttentionOpForms:
    def test_flash_attn_op(self):
        q = rng.normal(size=(2, 8, 2, 16)).astype(np.float32)
        out = _np(pt.flash_attn(pt.Tensor(q), pt.Tensor(q), pt.Tensor(q),
                                causal=True))
        assert out.shape == q.shape

    def test_flash_attn_qkvpacked(self):
        qkv = rng.normal(size=(2, 8, 3, 2, 16)).astype(np.float32)
        out = _np(pt.flash_attn_qkvpacked(pt.Tensor(qkv)))
        assert out.shape == (2, 8, 2, 16)

    def test_flash_attn_unpadded_op(self):
        q = rng.normal(size=(10, 2, 8)).astype(np.float32)
        cu = np.array([0, 4, 10], np.int32)
        out = _np(pt.flash_attn_unpadded(pt.Tensor(q), pt.Tensor(q),
                                         pt.Tensor(q), pt.Tensor(cu),
                                         pt.Tensor(cu), 6, 6))
        assert out.shape == q.shape

    def test_memory_efficient_attention(self):
        q = rng.normal(size=(2, 8, 2, 16)).astype(np.float32)
        out = _np(pt.memory_efficient_attention(pt.Tensor(q), pt.Tensor(q),
                                                pt.Tensor(q), causal=True))
        assert out.shape == q.shape
