"""Auto-tuner + incubate (higher-order autograd, fused layers) tests
(reference test/auto_tuner, test/legacy_test/test_fused_attention_op.py,
incubate autograd suites)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed.auto_tuner import (AutoTuner, TuneConfig,
                                               default_candidates, prune)
from paddle_tpu.incubate import autograd as ia


@pytest.fixture(autouse=True, scope="module")
def _no_persistent_compile_cache():
    """ISSUE 9 satellite: the PR 8 donated-deserialize opt-out, applied
    to the fused_attention_grad suspect.  Finding: the failure
    reproduces in ISOLATION with the cache opted out too (CHANGES.md
    PR 6 already observed it failing identically in isolation) — a
    genuine numeric gap in that grad path, NOT the compile-cache bug;
    the opt-out stays to keep the cache out of the equation."""
    from conftest import disable_persistent_compile_cache

    restore = disable_persistent_compile_cache()
    yield
    restore()


class TestAutoTuner:
    def test_candidates_factor_device_count(self):
        cands = default_candidates(8, global_batch_size=32, num_layers=8,
                                   num_heads=8)
        assert cands
        for c in cands:
            assert c.degrees_product() == 8
            assert 32 % (c.dp_degree * c.sharding_degree) == 0
            assert 8 % c.mp_degree == 0 and 8 % c.pp_degree == 0

    def test_prune_rules(self):
        bad = [TuneConfig(dp_degree=3),                      # not factor 8
               TuneConfig(dp_degree=8, micro_batch_size=3),  # mbs not div
               TuneConfig(dp_degree=4, mp_degree=2,
                          sharding_stage=2)]                 # stage w/o shard
        assert prune(bad, 8, 32) == []

    def test_tune_picks_best(self, tmp_path):
        tuner = AutoTuner(num_devices=8, global_batch_size=32,
                          model_params=1e8, hidden=512, layers=8,
                          num_heads=8, max_trials=6,
                          history_path=str(tmp_path / "hist.csv"))

        def run(cfg):
            # favor pure dp with bigger micro batches
            if cfg["mp_degree"] > 1 or cfg["pp_degree"] > 1:
                return 10.0
            return 100.0 * cfg["micro_batch_size"]

        best, metric = tuner.tune(run)
        assert best is not None and metric > 10
        assert (tmp_path / "hist.csv").exists()
        assert len(tuner.history) == 6

    def test_failed_trials_skipped(self):
        tuner = AutoTuner(num_devices=4, global_batch_size=16,
                          model_params=1e7, layers=4, max_trials=3)
        calls = []

        def run(cfg):
            calls.append(cfg)
            if len(calls) == 1:
                raise MemoryError("oom")
            return 1.0

        best, metric = tuner.tune(run)
        assert best is not None
        assert tuner.history[0]["metric"] is None


class TestHigherOrderAutograd:
    def test_jacobian_hessian(self):
        xs = pt.to_tensor(np.array([1.0, 2.0], np.float32),
                          stop_gradient=False)
        f = lambda t: (t ** 3).sum()
        np.testing.assert_allclose(np.asarray(ia.jacobian(f, xs).numpy()),
                                   [3.0, 12.0], rtol=1e-5)
        np.testing.assert_allclose(np.asarray(ia.hessian(f, xs).numpy()),
                                   [[6.0, 0.0], [0.0, 12.0]], rtol=1e-5)

    def test_jvp_vjp_roundtrip(self):
        xs = pt.to_tensor(np.array([0.5, 1.5], np.float32),
                          stop_gradient=False)
        f = lambda t: t * t
        v = pt.to_tensor(np.array([1.0, 1.0], np.float32))
        _, tangent = ia.jvp(f, xs, v)
        np.testing.assert_allclose(np.asarray(tangent.numpy()),
                                   [1.0, 3.0], rtol=1e-5)
        _, cotangent = ia.vjp(f, xs, v)
        np.testing.assert_allclose(np.asarray(cotangent.numpy()),
                                   [1.0, 3.0], rtol=1e-5)

    def test_forward_grad(self):
        xs = pt.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        t = ia.forward_grad(lambda v: v ** 2, xs)
        np.testing.assert_allclose(np.asarray(t.numpy()), [4.0], rtol=1e-5)


class TestFusedLayers:
    def test_encoder_layer_matches_unfused_shape(self):
        from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer
        pt.seed(0)
        net = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
        net.eval()
        x = pt.to_tensor(np.random.default_rng(0).standard_normal(
            (2, 6, 32)).astype(np.float32))
        y = net(x)
        assert tuple(y.shape) == (2, 6, 32)
        assert np.isfinite(y.numpy()).all()

    def test_fused_attention_grad(self):
        """Gradients flow through the whole fused block — qkv, the
        output projection, AND the epilogue LN params.

        The loss must NOT be a bare ``out.sum()``: the block ends in a
        post-LN (normalize_before=False) whose scale initializes to 1,
        and a uniform cotangent is exactly in that LayerNorm Jacobian's
        null space — ``dx = inv*(w·g - mean(w·g) - xhat·mean(w·g·xhat))``
        vanishes identically when ``w·g`` is constant (mean(xhat)=0).
        Every mathematically-exact backward therefore produces
        qkv/linear grads of literally 0.0 there; only fp rounding noise
        in a non-analytic implementation makes them "nonzero".  A
        seeded non-uniform weighting keeps the cotangent out of the
        null space, so the assertion tests grad FLOW, not noise."""
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention
        pt.seed(1)
        net = FusedMultiHeadAttention(16, 2, dropout_rate=0.0,
                                      attn_dropout_rate=0.0)
        rng = np.random.default_rng(1)
        x = pt.to_tensor(rng.standard_normal(
            (2, 4, 16)).astype(np.float32))
        w = pt.to_tensor(rng.standard_normal(
            (2, 4, 16)).astype(np.float32))
        out = net(x)
        (out * w).sum().backward()
        assert net.qkv_weight.grad is not None
        assert np.abs(net.qkv_weight.grad.numpy()).sum() > 0
        assert np.abs(net.linear_weight.grad.numpy()).sum() > 0
        assert np.abs(net.ln_scale.grad.numpy()).sum() > 0

    def test_fused_attention_uniform_cotangent_null_space(self):
        """The property that made the old assertion unsatisfiable: with
        a uniform cotangent and unit LN scale, the analytic LayerNorm
        backward annihilates the upstream gradient (exactly in real
        arithmetic; to fp32 rounding noise in practice — orders of
        magnitude below any real gradient).  A non-negligible qkv grad
        here would mean the backward picked up spurious terms; the
        weighted-loss test above is where genuine grad FLOW is
        asserted."""
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention
        pt.seed(1)
        net = FusedMultiHeadAttention(16, 2, dropout_rate=0.0,
                                      attn_dropout_rate=0.0)
        x = pt.to_tensor(np.random.default_rng(1).standard_normal(
            (2, 4, 16)).astype(np.float32))
        out = net(x)
        out.sum().backward()
        assert net.qkv_weight.grad is not None
        # noise floor: the weighted-loss variant measures ~1e0-1e2 here
        assert np.abs(net.qkv_weight.grad.numpy()).max() < 1e-5
        # the LN's own params DO see the uniform cotangent
        assert np.abs(net.ln_bias.grad.numpy()).sum() > 0


class TestIncubateFusedLayers:
    """The 7 fused layer classes added for full incubate.nn parity."""

    def test_fused_linear_and_dropout_add(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn import (FusedDropoutAdd, FusedLinear)
        rng = np.random.default_rng(0)
        lin = FusedLinear(8, 4)
        x = rng.normal(size=(3, 8)).astype(np.float32)
        out = np.asarray(lin(pt.Tensor(x))._value)
        ref = x @ np.asarray(lin.weight._value) + np.asarray(
            lin.bias._value)
        np.testing.assert_allclose(out, ref, rtol=1e-5)
        da = FusedDropoutAdd(0.5)
        da.eval()
        y = rng.normal(size=(3, 8)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(da(pt.Tensor(x), pt.Tensor(y))._value), x + y,
            rtol=1e-6)

    def test_fused_bias_dropout_residual_ln(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn import FusedBiasDropoutResidualLayerNorm
        rng = np.random.default_rng(1)
        m = FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
        x = rng.normal(size=(2, 5, 8)).astype(np.float32)
        r = rng.normal(size=(2, 5, 8)).astype(np.float32)
        out = np.asarray(m(pt.Tensor(x), pt.Tensor(r))._value)
        h = x + np.asarray(m.linear_bias._value) + r
        mu = h.mean(-1, keepdims=True)
        ref = (h - mu) / np.sqrt(h.var(-1, keepdims=True) + 1e-5)
        ref = ref * np.asarray(m.ln_scale._value) + np.asarray(
            m.ln_bias._value)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    def test_fused_ec_moe(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn import FusedEcMoe
        rng = np.random.default_rng(2)
        m = FusedEcMoe(8, 16, num_experts=2)
        x = rng.normal(size=(4, 8)).astype(np.float32)
        gate = rng.normal(size=(4, 2)).astype(np.float32)
        out = np.asarray(m(pt.Tensor(x), pt.Tensor(gate))._value)
        assert out.shape == (4, 8) and np.isfinite(out).all()

    def test_fused_multi_transformer_layer(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn import FusedMultiTransformer
        rng = np.random.default_rng(3)
        m = FusedMultiTransformer(16, 2, 32, num_layers=2)
        # LN scales must initialize to ones (reference convention)
        np.testing.assert_allclose(np.asarray(m.ln_scales[0]._value), 1.0)
        np.testing.assert_allclose(np.asarray(m.ffn_ln_scales[1]._value),
                                   1.0)
        x = rng.normal(size=(1, 5, 16)).astype(np.float32)
        out = np.asarray(m(pt.Tensor(x))._value)
        assert out.shape == (1, 5, 16) and np.isfinite(out).all()

    def test_fused_transformer_stack(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.incubate.nn import FusedTransformer
        m = FusedTransformer(d_model=16, nhead=2, num_encoder_layers=2,
                             dim_feedforward=32, dropout=0.0)
        m.eval()
        x = np.random.default_rng(4).normal(size=(2, 6, 16)).astype(
            np.float32)
        out = np.asarray(m(pt.Tensor(x))._value)
        assert out.shape == (2, 6, 16) and np.isfinite(out).all()
