"""tracelint ratchet: the real package versus the committed baseline.

Tier-1 and CPU-only: pure AST analysis, no jax execution.  The ratchet
fails when any (rule, file) finding count exceeds TRACELINT.md — the
same comparison `python tools/tracelint_baseline.py --check` runs
standalone (pre-commit style).
"""

import functools
import os
import subprocess
import sys

from paddle_tpu.analysis import baseline as baseline_mod
from paddle_tpu.analysis import core
from paddle_tpu.analysis.cli import default_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CORE_TREES = ("paddle_tpu/checkpoint/", "paddle_tpu/io/",
              "paddle_tpu/optimizer/", "paddle_tpu/parallel/")


@functools.lru_cache(maxsize=1)
def _scan_once():
    # the committed tree is immutable for the lifetime of the test run;
    # one full scan serves every ratchet assertion below
    return tuple(core.run(default_paths()))


def _current_findings():
    return list(_scan_once())


def test_package_at_or_below_baseline():
    findings = _current_findings()
    base = baseline_mod.load()
    regressions = baseline_mod.compare(baseline_mod.counts(findings),
                                       base)
    assert regressions == [], (
        "tracelint findings grew beyond TRACELINT.md:\n  "
        + "\n  ".join(regressions)
        + "\nfix or suppress (with justification), or regenerate the "
          "baseline via `python tools/tracelint_baseline.py` with "
          "reviewer sign-off")


def test_observability_has_zero_tl001_tl006():
    """ISSUE 5 contract: the telemetry package records HOST-side only —
    no host-sync in traced code (TL001; a metrics call inside jit is
    that hazard by construction) and no silent broad excepts (TL006) —
    live scan AND committed ledger."""
    tree = "paddle_tpu/observability/"
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.startswith(tree)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.startswith(tree):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_aot_has_zero_tl001_tl006():
    """ISSUE 6 contract: the AOT subsystem is host-side plumbing around
    traced programs — no host-sync in traced code (TL001) and no silent
    broad excepts (TL006; a swallowed artifact error would turn a warm
    start into a silent cold start) — live scan AND committed ledger."""
    tree = "paddle_tpu/aot/"
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.startswith(tree)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.startswith(tree):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_serving_has_zero_tl001_tl006():
    """ISSUE 7 contract: the streaming front-end is host-side scheduler
    code — no host-sync in traced code (TL001) and no silent broad
    excepts (TL006; a swallowed delivery/cancel error would strand a
    consumer or leak KV pages) — live scan AND committed ledger."""
    tree = "paddle_tpu/serving/"
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.startswith(tree)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.startswith(tree):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_serving_http_has_zero_tl001_tl006():
    """ISSUE 13 contract: the HTTP/SSE front door is pure host-side
    connection plumbing over the frontend — no host-sync in traced
    code (TL001) and no silent broad excepts (TL006; a swallowed
    disconnect/stall/shutdown error would leak the very KV pages the
    wire layer exists to free) — live scan AND committed ledger."""
    files = ("paddle_tpu/serving/http.py",)
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_spec_decode_has_zero_tl001_tl006():
    """ISSUE 8 contract: speculative decoding is host-side scheduling
    around two traced programs — no host-sync in traced code (TL001;
    the draft/verify closures must stay pure) and no silent broad
    excepts (TL006; a swallowed commit/rollback error would corrupt the
    accepted-prefix accounting) — live scan AND committed ledger."""
    tree = "paddle_tpu/spec_decode/"
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.startswith(tree)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.startswith(tree):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_serving_fleet_has_zero_tl001_tl006():
    """ISSUE 12 contract: the multi-replica router is pure host-side
    scheduling over supervised engines — no host-sync in traced code
    (TL001) and no silent broad excepts (TL006; a swallowed death /
    drain / re-placement error would strand streams the fleet layer
    exists to keep alive) — live scan AND committed ledger."""
    files = ("paddle_tpu/serving/fleet.py",)
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_serving_resilience_has_zero_tl001_tl006():
    """ISSUE 11 contract: the resilience layer (KV spill/restore +
    supervised recovery) is host-side scheduler code around compiled
    programs — no host-sync in traced code (TL001) and no silent broad
    excepts (TL006; a swallowed restore/replay error would silently
    lose a stream the whole subsystem exists to preserve) — live scan
    AND committed ledger."""
    files = ("paddle_tpu/serving/resilience.py",)
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_prefix_cache_has_zero_tl001_tl006():
    """ISSUE 14 contract: the cross-request prefix cache is host-side
    scheduler state around the paged pool — no host-sync in traced
    code (TL001; the radix tree must never be consulted from inside a
    compiled program) and no silent broad excepts (TL006; a swallowed
    offload/restore error would silently serve corrupt KV bytes as a
    cache hit) — live scan AND committed ledger."""
    files = ("paddle_tpu/serving/prefix_cache.py",)
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_quantization_serve_has_zero_tl001_tl006():
    """ISSUE 16 contract: the serving PTQ export path is host-side
    numpy by design (a traced quantize would recompile every engine
    construction — the serve_quant_warm budget row pins zero) — no
    host-sync in traced code (TL001) and no silent broad excepts
    (TL006; a swallowed export error would silently serve unquantized
    or half-quantized weights) — live scan AND committed ledger."""
    files = ("paddle_tpu/quantization/serve.py",)
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_decode_block_has_zero_tl001_tl006():
    """ISSUE 9 contract: the fused decode-block op (dispatch module AND
    Pallas kernel) sits on the hottest serve path — no host-sync in
    traced code (TL001; one ``.item()`` in the layer body would sync
    every layer of every decode step) and no silent broad excepts
    (TL006; a swallowed dispatch error would silently serve the wrong
    tier) — live scan AND committed ledger."""
    files = ("paddle_tpu/ops/decode_block.py",
             "paddle_tpu/ops/pallas/decode_block.py")
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_prefill_block_has_zero_tl001_tl006():
    """ISSUE 18 contract: the fused chunked-prefill kernel shares the
    decode megakernel's bar — no host-sync in traced code (TL001; a
    ``.item()`` in the chunk-fill body would sync every layer of every
    prefill chunk) and no silent broad excepts (TL006; a swallowed
    dispatch error would silently serve the wrong tier) — live scan
    AND committed ledger."""
    files = ("paddle_tpu/ops/pallas/prefill_block.py",)
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_parallel_elastic_has_zero_tl001_tl006():
    """ISSUE 17 contract: the elastic trainer is host-side supervision
    around the engine's compiled step — no host-sync in traced code
    (TL001; the SDC guard must stay an in-graph where-select, never a
    host check per step) and no silent broad excepts (TL006; a
    swallowed reshape/restore error would resume training on corrupt
    or stale state) — live scan AND committed ledger."""
    files = ("paddle_tpu/parallel/elastic.py",)
    live = [f for f in _current_findings()
            if f.rule in ("TL001", "TL006") and f.path.endswith(files)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule in ("TL001", "TL006") and path.endswith(files):
            assert n == 0, f"baseline carries {rule} debt in {path}"


def test_core_subsystems_have_zero_tl006():
    """The ISSUE 4 triage contract: checkpoint/, io/, optimizer/ and
    parallel/ carry NO un-triaged silent-except debt — in the live scan
    AND in the committed ledger."""
    findings = _current_findings()
    live = [f for f in findings if f.rule == "TL006"
            and f.path.startswith(CORE_TREES)]
    assert live == [], [f.format() for f in live]
    for (rule, path), n in baseline_mod.load().items():
        if rule == "TL006" and path.startswith(CORE_TREES):
            assert n == 0, f"baseline carries TL006 debt in {path}"


def test_ratchet_fails_on_injected_violation(tmp_path):
    """A synthetic violation in the analyzed tree must trip the
    comparison: the ratchet is live, not vacuously green."""
    bad = tmp_path / "injected.py"
    bad.write_text(
        "def leaky(q):\n"
        "    try:\n"
        "        q.get_nowait()\n"
        "    except Exception:\n"
        "        pass\n")
    findings = _current_findings() + core.run([str(bad)])
    assert any(f.rule == "TL006" and "injected.py" in f.path
               for f in findings)
    regressions = baseline_mod.compare(baseline_mod.counts(findings),
                                       baseline_mod.load())
    assert regressions, "injected TL006 violation did not trip the ratchet"


def test_standalone_checker_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "tracelint_baseline.py"),
         "--check"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ratchet OK" in proc.stdout


def test_module_cli_reports_zero_above_baseline():
    """Acceptance criterion: `python -m paddle_tpu.analysis paddle_tpu/`
    reports zero above-baseline findings."""
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis",
         os.path.join(REPO, "paddle_tpu")],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 above baseline" in proc.stdout
