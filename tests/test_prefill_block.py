"""Fused chunked-prefill block op (ISSUE 18): XLA tier bit-identity vs
the inline per-op chain, Pallas interpret-tier value parity (eager and
jitted), typed geometry/VMEM/MoE fallbacks, the "prefill_block"
autotune cache roundtrip, quantized-weight and int8-KV parity, and the
serve-path acceptance pins — engine greedy, frontend stream,
spec-decode, HTTP/SSE wire, prefix-cache suffix fill, and the AOT
``fused_prefill`` knob — all bit-identical with fusion on and off."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.flags import FLAGS, set_flags
from paddle_tpu.ops.decode_block import (DecodeBlockSpec,
                                         PrefillBlockUnsupportedError,
                                         prefill_block,
                                         prefill_block_unsupported_reason,
                                         prefill_block_xla)
from paddle_tpu.ops.paged_kv import (QuantizedKVPool, is_quantized_pool,
                                     quantize_kv)
from paddle_tpu.ops.pallas import prefill_block as ppf
from paddle_tpu.ops.pallas.prefill_block import (prefill_block_pallas,
                                                 tune_prefill_block,
                                                 unsupported_reason)

rng = np.random.default_rng(18)


def _w(*shape, dtype=np.float32, scale=0.1):
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32)
                       * scale, dtype=dtype)


def _llama_layer(H, Hq, Hkv, D, F, dtype):
    return {"ln1_w": _w(H, dtype=dtype, scale=1.0) + 1.0,
            "q_w": _w(H, Hq * D, dtype=dtype),
            "k_w": _w(H, Hkv * D, dtype=dtype),
            "v_w": _w(H, Hkv * D, dtype=dtype),
            "o_w": _w(Hq * D, H, dtype=dtype),
            "ln2_w": _w(H, dtype=dtype, scale=1.0) + 1.0,
            "gate_w": _w(H, F, dtype=dtype), "up_w": _w(H, F, dtype=dtype),
            "down_w": _w(F, H, dtype=dtype)}


def _gpt_layer(H, Hq, D, F, dtype):
    return {"ln1_w": _w(H, dtype=dtype, scale=1.0) + 1.0,
            "ln1_b": _w(H, dtype=dtype),
            "qkv_w": _w(H, 3 * H, dtype=dtype),
            "qkv_b": _w(3 * H, dtype=dtype),
            "proj_w": _w(H, H, dtype=dtype), "proj_b": _w(H, dtype=dtype),
            "ln2_w": _w(H, dtype=dtype, scale=1.0) + 1.0,
            "ln2_b": _w(H, dtype=dtype),
            "fc1_w": _w(H, F, dtype=dtype), "fc1_b": _w(F, dtype=dtype),
            "fc2_w": _w(F, H, dtype=dtype), "fc2_b": _w(H, dtype=dtype)}


def _case(kind, dtype, Ts=7, start=5, MB=6, NB=16, BS=4):
    """One sequence's chunk fill: ``Ts`` prompt tokens at absolute
    positions ``start + [0, Ts)`` against a pool holding ``start``
    committed tokens in the sequence's block-table row (plus unrelated
    junk everywhere else — both tiers must ignore it)."""
    H, D = 32, 8
    if kind == "llama_gqa":
        Hq, Hkv, F = 4, 2, 48
        spec = DecodeBlockSpec(hidden=H, num_heads=Hq, kv_heads=Hkv,
                               head_dim=D, block_size=BS, norm="rms",
                               activation="swiglu", eps=1e-5, rope=True)
        lp = _llama_layer(H, Hq, Hkv, D, F, dtype)
    else:                                        # gpt: ln + gelu + bias
        Hq = Hkv = 4
        spec = DecodeBlockSpec(hidden=H, num_heads=Hq, kv_heads=Hq,
                               head_dim=D, block_size=BS, norm="ln",
                               activation="gelu", eps=1e-5, rope=False,
                               fused_qkv=True, bias=True)
        lp = _gpt_layer(H, Hq, D, 48, dtype)
    pool_k = _w(NB, BS, Hkv, D, dtype=dtype)
    pool_v = _w(NB, BS, Hkv, D, dtype=dtype)
    bt_row = np.full((MB,), -1, np.int32)
    nb = -(-(start + Ts) // BS)
    bt_row[:nb] = [2, 5, 7, 9, 11, 13][:nb]
    bt_row = jnp.asarray(bt_row)
    pos = start + jnp.arange(Ts)
    blk = jnp.take(jnp.maximum(bt_row, 0), pos // BS)
    off = pos % BS
    jpos = jnp.arange(MB * BS)[None, None, None, :]
    mask = jpos <= pos[None, None, :, None]
    x = _w(1, Ts, H, dtype=dtype, scale=0.5)
    cos = _w(Ts, D, dtype=dtype, scale=1.0) if spec.rope else None
    sin = _w(Ts, D, dtype=dtype, scale=1.0) if spec.rope else None
    return spec, lp, x, pool_k, pool_v, blk, off, bt_row, mask, cos, sin


def _per_op_reference(x, lp, pool_k, pool_v, blk, off, bt_row, mask, cos,
                      sin, spec):
    """The pre-ISSUE-18 per-op chunk-fill chain, written out
    independently of the op module — what prefill_block must
    reproduce bit-for-bit at the XLA tier."""
    _, Ts, _ = x.shape
    Hq, Hkv, D = spec.num_heads, spec.kv_heads, spec.head_dim

    def norm(x_, w, b=None):
        if spec.norm == "rms":
            ms = jnp.mean(jnp.square(x_.astype(jnp.float32)), -1,
                          keepdims=True)
            return (x_ * jax.lax.rsqrt(ms + spec.eps).astype(x_.dtype)) * w
        x32 = x_.astype(jnp.float32)
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + spec.eps)
                ).astype(x_.dtype) * w + b

    y = norm(x, lp["ln1_w"], lp.get("ln1_b"))
    if spec.fused_qkv:
        qkv = (y @ lp["qkv_w"] + lp["qkv_b"]).reshape(1, Ts, Hq, 3 * D)
        q, k, v = jnp.split(qkv, 3, axis=-1)
    else:
        q = (y @ lp["q_w"]).reshape(1, Ts, Hq, D)
        k = (y @ lp["k_w"]).reshape(1, Ts, Hkv, D)
        v = (y @ lp["v_w"]).reshape(1, Ts, Hkv, D)
    if spec.rope:
        def rot(t):
            d2 = t.shape[-1] // 2
            return jnp.concatenate([-t[..., d2:], t[..., :d2]], -1)

        q = q * cos[None, :, None, :] + rot(q) * sin[None, :, None, :]
        k = k * cos[None, :, None, :] + rot(k) * sin[None, :, None, :]
    pool_k = pool_k.at[blk, off].set(k[0])
    pool_v = pool_v.at[blk, off].set(v[0])
    k_all = jnp.take(pool_k, jnp.maximum(bt_row, 0),
                     axis=0).reshape(1, -1, Hkv, D)
    v_all = jnp.take(pool_v, jnp.maximum(bt_row, 0),
                     axis=0).reshape(1, -1, Hkv, D)
    rep = Hq // Hkv
    if rep > 1:
        k_all = jnp.repeat(k_all, rep, axis=2)
        v_all = jnp.repeat(v_all, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_all) * (1.0 / D ** 0.5)
    logits = jnp.where(mask, logits.astype(jnp.float32), -1e30)
    p = jax.nn.softmax(logits, -1).astype(q.dtype)
    attn = jnp.einsum("bhqk,bkhd->bqhd", p, v_all).reshape(1, Ts, -1)
    proj = attn @ (lp["proj_w"] if spec.fused_qkv else lp["o_w"])
    x = x + (proj + lp["proj_b"] if spec.bias else proj)
    y2 = norm(x, lp["ln2_w"], lp.get("ln2_b"))
    if spec.activation == "swiglu":
        f = (jax.nn.silu(y2 @ lp["gate_w"]) * (y2 @ lp["up_w"])) \
            @ lp["down_w"]
    else:
        f = jax.nn.gelu(y2 @ lp["fc1_w"] + lp["fc1_b"],
                        approximate=True) @ lp["fc2_w"] + lp["fc2_b"]
    return x + f, pool_k, pool_v


VARIANTS = ("llama_gqa", "gpt")
DTYPES = (np.float32, jnp.bfloat16)


# ---------------------------------------------------------------------------
# tier parity
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("kind", VARIANTS)
@pytest.mark.parametrize("dtype", DTYPES, ids=("fp32", "bf16"))
def test_xla_tier_bit_identical_to_per_op(kind, dtype):
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(kind, dtype)
    ref = _per_op_reference(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec)
    got = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                        spec=spec, start=5, backend="xla")
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r, np.float32),
                                      np.asarray(g, np.float32))


@pytest.mark.parametrize("kind", VARIANTS)
@pytest.mark.parametrize("dtype", DTYPES, ids=("fp32", "bf16"))
def test_pallas_tier_value_parity(kind, dtype):
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(kind, dtype)
    ref = _per_op_reference(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec)
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        got = prefill_block_pallas(x, lp, pk, pv, blk, off, bt, mask,
                                   cos, sin, spec=spec, start=5)
        # the traced path the engine's scan takes
        jit_got = jax.jit(lambda *a: prefill_block(
            *a, spec=spec, start=5, backend="pallas"))(
                x, lp, pk, pv, blk, off, bt, mask, cos, sin)
    finally:
        set_flags({"pallas_interpret": old})
    tol = dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=1e-5, atol=1e-5)
    for r, g, jg in zip(ref, got, jit_got):
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(r, np.float32), **tol)
        np.testing.assert_allclose(np.asarray(jg, np.float32),
                                   np.asarray(r, np.float32), **tol)


@pytest.mark.parametrize("start,Ts", [(0, 8), (3, 1), (11, 9)])
def test_pallas_tier_parity_across_chunk_geometries(start, Ts):
    """Cold prefill (start=0), a single-token tail chunk, and a chunk
    crossing several page boundaries all agree with the per-op chain."""
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32, Ts=Ts, start=start)
    ref = _per_op_reference(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec)
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        got = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec=spec, start=start, backend="pallas")
    finally:
        set_flags({"pallas_interpret": old})
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-5, atol=1e-5)


def test_auto_dispatch_off_tpu_is_reference_tier():
    """With no TPU and no interpret flag, auto dispatch must take the
    per-op tier — the CPU tier-1 bit-identity story."""
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32)
    ref = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                        spec=spec, start=5, backend="xla")
    got = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                        spec=spec, start=5)
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


# ---------------------------------------------------------------------------
# geometry limits / typed fallback
# ---------------------------------------------------------------------------
def test_unsupported_head_dim_reason_and_raise():
    H, Hq, Hkv, D, F = 16, 2, 2, 512, 24     # D past the kernel cap
    spec = DecodeBlockSpec(hidden=H, num_heads=Hq, kv_heads=Hkv,
                           head_dim=D, block_size=4, norm="rms",
                           activation="swiglu", eps=1e-5, rope=True)
    lp = _llama_layer(H, Hq, Hkv, D, F, np.float32)
    pk = _w(16, 4, Hkv, D)
    pv = _w(16, 4, Hkv, D)
    bt = jnp.asarray(np.array([2, 5, 7, -1, -1, -1], np.int32))
    Ts, start = 7, 5
    pos = start + jnp.arange(Ts)
    blk, off = jnp.take(jnp.maximum(bt, 0), pos // 4), pos % 4
    mask = jnp.arange(6 * 4)[None, None, None, :] \
        <= pos[None, None, :, None]
    x = _w(1, Ts, H)
    cos, sin = _w(Ts, D), _w(Ts, D)
    reason = unsupported_reason(spec, lp, pk, Ts)
    assert reason is not None and "head_dim" in reason
    assert prefill_block_unsupported_reason(spec, lp, pk, Ts) == reason
    with pytest.raises(PrefillBlockUnsupportedError, match="head_dim"):
        prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                      spec=spec, start=start, backend="pallas")
    # auto dispatch silently takes the reference tier instead
    ref = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                        spec=spec, start=start, backend="xla")
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        got = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec=spec, start=start)
    finally:
        set_flags({"pallas_interpret": old})
    np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(got[0]))


def test_unsupported_vmem_budget(monkeypatch):
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32)
    assert unsupported_reason(spec, lp, pk, x.shape[1]) is None
    monkeypatch.setattr(ppf, "VMEM_BUDGET_BYTES", 128)
    reason = unsupported_reason(spec, lp, pk, x.shape[1])
    assert reason is not None and "VMEM" in reason
    with pytest.raises(PrefillBlockUnsupportedError, match="VMEM"):
        prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                      spec=spec, start=5, backend="pallas")
    # auto dispatch silently falls back to the reference tier
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        got = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec=spec, start=5)
    finally:
        set_flags({"pallas_interpret": old})
    ref = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                        spec=spec, start=5, backend="xla")
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_moe_ffn_override_forces_reference_tier():
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32)
    with pytest.raises(PrefillBlockUnsupportedError, match="FFN"):
        prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                      spec=spec, start=5, ffn=lambda lp_, y: y,
                      backend="pallas")
    # auto dispatch with an FFN override silently runs the reference
    ref = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                        spec=spec, start=5, ffn=lambda lp_, y: y * 0,
                        backend="xla")
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        got = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec=spec, start=5, ffn=lambda lp_, y: y * 0)
    finally:
        set_flags({"pallas_interpret": old})
    np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(got[0]))


def test_missing_start_forces_reference_tier():
    """The kernel derives causality from the committed-prefix length;
    forcing the Pallas tier without it is a typed error, and auto
    dispatch runs the reference tier."""
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32)
    with pytest.raises(PrefillBlockUnsupportedError,
                       match="committed-prefix"):
        prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                      spec=spec, backend="pallas")
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        got = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec=spec)
    finally:
        set_flags({"pallas_interpret": old})
    ref = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                        spec=spec, backend="xla")
    np.testing.assert_array_equal(np.asarray(ref[0]), np.asarray(got[0]))


# ---------------------------------------------------------------------------
# quantized weights / int8 KV pages
# ---------------------------------------------------------------------------
QUANT_CASES = (("int8", -1), ("int8", 64), ("int4", 64))


@pytest.mark.parametrize("wd,gs", QUANT_CASES,
                         ids=lambda v: str(v))
def test_quant_weights_pallas_matches_xla(wd, gs):
    """Dequant-in-kernel == dequant-in-XLA for every storage layout the
    weight-only decode path ships (per-channel int8, grouped int8,
    int4 nibbles)."""
    from paddle_tpu.ops.pallas.decode_block import _MATMUL_NAMES
    from paddle_tpu.quantization import ServeQuantConfig
    from paddle_tpu.quantization.serve import _quantize_matrix
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32)
    qspec = DecodeBlockSpec(
        hidden=spec.hidden, num_heads=spec.num_heads,
        kv_heads=spec.kv_heads, head_dim=spec.head_dim,
        block_size=spec.block_size, norm=spec.norm,
        activation=spec.activation, eps=spec.eps, rope=spec.rope,
        weight_dtype=wd, group_size=gs)
    qc = ServeQuantConfig(weight_dtype=wd, group_size=gs)
    qlp = {}
    for n, v in lp.items():
        if n in _MATMUL_NAMES:
            q, s = _quantize_matrix(np.asarray(v, np.float32), qc)
            qlp[n + "__q"], qlp[n + "__s"] = jnp.asarray(q), jnp.asarray(s)
        else:
            qlp[n] = v
    assert unsupported_reason(qspec, qlp, pk, x.shape[1]) is None
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        a = prefill_block(x, qlp, pk, pv, blk, off, bt, mask, cos, sin,
                          spec=qspec, start=5, backend="pallas")
    finally:
        set_flags({"pallas_interpret": old})
    b = prefill_block(x, qlp, pk, pv, blk, off, bt, mask, cos, sin,
                      spec=qspec, start=5, backend="xla")
    for g, r in zip(a, b):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=1e-5, atol=1e-5)


def test_int8_kv_pool_pallas_matches_xla():
    """Quantized pool: the kernel dequantizes staged pages with their
    scales AND quantize-roundtrips the in-chunk k/v exactly as the
    XLA tier's scatter-then-gather does; the host-side scatter writes
    identical codes."""
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32)
    pk = QuantizedKVPool(*quantize_kv(pk))
    pv = QuantizedKVPool(*quantize_kv(pv))
    assert is_quantized_pool(pk)
    old = FLAGS.pallas_interpret
    set_flags({"pallas_interpret": True})
    try:
        a, ak, av = prefill_block(x, lp, pk, pv, blk, off, bt, mask,
                                  cos, sin, spec=spec, start=5,
                                  backend="pallas")
    finally:
        set_flags({"pallas_interpret": old})
    b, bk, bv = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos,
                              sin, spec=spec, start=5, backend="xla")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)
    # identical int8 codes and scales in the committed pool
    np.testing.assert_array_equal(np.asarray(ak.data), np.asarray(bk.data))
    np.testing.assert_array_equal(np.asarray(av.data), np.asarray(bv.data))
    np.testing.assert_allclose(np.asarray(ak.scale), np.asarray(bk.scale),
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# autotune
# ---------------------------------------------------------------------------
def test_autotune_cache_roundtrip(tmp_path):
    from paddle_tpu.ops.pallas import autotune
    spec, lp, x, pk, pv, blk, off, bt, mask, cos, sin = _case(
        "llama_gqa", np.float32)
    path = tmp_path / "at.json"
    old = FLAGS.pallas_interpret
    set_flags({"use_autotune": True, "autotune_cache_file": str(path),
               "pallas_interpret": True})
    try:
        autotune.clear_cache()
        out = tune_prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos,
                                 sin, spec=spec, start=5)
        key = (x.shape[1], spec.hidden, spec.num_heads, spec.kv_heads,
               spec.head_dim, spec.block_size, bt.shape[0],
               spec.activation, str(pk.dtype), None, -1)
        won = autotune.lookup("prefill_block", key, None)
        assert won is not None and int(won) >= 1
        # the winner persisted to disk for later processes
        with open(path) as f:
            on_disk = json.load(f)
        assert any(k.startswith("prefill_block|") for k in on_disk), \
            on_disk
        assert int(won) in [int(v) for k, v in on_disk.items()
                            if k.startswith("prefill_block|")]
        ref = prefill_block(x, lp, pk, pv, blk, off, bt, mask, cos, sin,
                            spec=spec, start=5, backend="xla")
        np.testing.assert_allclose(np.asarray(out[0]),
                                   np.asarray(ref[0]), rtol=1e-5,
                                   atol=1e-5)
    finally:
        set_flags({"use_autotune": False, "autotune_cache_file": "",
                   "pallas_interpret": old})
        autotune.clear_cache()


# ---------------------------------------------------------------------------
# engine / serve-path bit-identity (the acceptance pins)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_serving():
    from paddle_tpu import parallel as dist
    from paddle_tpu.models.llama import build_llama_train_step, llama_tiny
    from paddle_tpu.parallel.topology import HybridTopology, set_topology
    cfg = llama_tiny()
    topo = dist.init_topology(devices=jax.devices()[:1])
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in (5, 9, 17)]
    return cfg, params, prompts


def _engine(cfg, params, fused, spec=False, **kw):
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    spec_config = None
    if spec:
        from paddle_tpu.spec_decode import SpecDecodeConfig
        spec_config = SpecDecodeConfig(draft_cfg=cfg, draft_params=params,
                                       k=2, window=8)
    return ContinuousBatchingEngine(
        cfg, params, max_batch=2, block_size=8, num_blocks=64,
        fused_prefill=fused, spec_config=spec_config, **kw)


def _drain(eng, prompts, sampled=False):
    for i, p in enumerate(prompts):
        eng.add_request(p, 6,
                        temperature=0.7 if (sampled and i == 1) else 0.0,
                        top_k=8 if (sampled and i == 1) else None,
                        seed=i)
    out = eng.run_to_completion()
    rep = eng.kv_leak_report()
    assert rep["leaked"] == 0 and rep["unaccounted"] == 0, rep
    return out


def test_engine_greedy_bit_identity_fused_on_off(tiny_serving):
    cfg, params, prompts = tiny_serving
    a = _drain(_engine(cfg, params, fused=True), prompts, sampled=True)
    b = _drain(_engine(cfg, params, fused=False), prompts, sampled=True)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def test_frontend_stream_bit_identity_fused_on_off(tiny_serving):
    from paddle_tpu.serving import ServingFrontend
    cfg, params, prompts = tiny_serving

    def stream(fused):
        fe = ServingFrontend(_engine(cfg, params, fused=fused))
        handles = [fe.submit(p, max_new_tokens=6) for p in prompts]
        return [list(h) for h in handles]

    assert stream(True) == stream(False)


def test_spec_decode_bit_identity_on_fused_prefill(tiny_serving):
    """Greedy speculative output must stay bit-identical to baseline
    decode — fused prefill on and off, spec on and off: all four
    agree."""
    cfg, params, prompts = tiny_serving
    runs = {(fused, spec): _drain(_engine(cfg, params, fused=fused,
                                          spec=spec), prompts)
            for fused in (True, False) for spec in (True, False)}
    base = runs[(False, False)]
    for key, out in runs.items():
        assert set(out) == set(base), key
        for k in base:
            np.testing.assert_array_equal(out[k], base[k],
                                          err_msg=str(key))


def test_http_sse_wire_bit_identity_fused_on_off(tiny_serving):
    """The wire pin: token streams served over real localhost HTTP/SSE
    from a fused-prefill engine == the unfused in-process engine."""
    from paddle_tpu.serving import HttpServingServer, ServingFrontend
    from paddle_tpu.serving.http import iter_sse
    import http.client
    cfg, params, prompts = tiny_serving
    ref_eng = _engine(cfg, params, fused=False)
    rids = [ref_eng.add_request(p, 6) for p in prompts[:2]]
    ref = ref_eng.run_to_completion()

    fe = ServingFrontend(_engine(cfg, params, fused=True))
    srv = HttpServingServer(fe, heartbeat_s=0.1)
    with srv:
        for rid, p in zip(rids, prompts[:2]):
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=120.0)
            try:
                conn.request("POST", "/v1/generate",
                             json.dumps({"prompt_ids": p.tolist(),
                                         "max_new_tokens": 6}),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200, resp.read()
                toks, done = {}, None
                for event, data in iter_sse(resp):
                    if event == "token":
                        toks[data["i"]] = data["t"]
                    else:
                        done = (event, data)
                        break
            finally:
                conn.close()
            assert done is not None and done[0] == "done" \
                and done[1]["state"] == "FINISHED"
            full = np.concatenate(
                [p, np.asarray([toks[i] for i in sorted(toks)],
                               np.int32)])
            np.testing.assert_array_equal(full, ref[rid])
        rep = fe.engine.kv_leak_report()
        assert rep["leaked"] == 0 and rep["unaccounted"] == 0, rep


def test_prefix_cache_suffix_fill_bit_identity(tiny_serving):
    """A prefix-cache hit runs ONLY the suffix through the chunk fill
    (start > 0) — the path the megakernel's committed-page pass serves.
    Hits must stay bit-identical with fusion on and off."""
    cfg, params, _ = tiny_serving
    base = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    suffixes = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
                for n in (5, 9)]

    def run(fused):
        eng = _engine(cfg, params, fused=fused)
        warm = eng.add_request(np.concatenate([base, suffixes[0]]), 6)
        out = {warm: eng.run_to_completion()[warm]}
        hits = [eng.add_request(np.concatenate([base, s]), 6)
                for s in suffixes]
        res = eng.run_to_completion()
        out.update({r: res[r] for r in hits})
        assert eng.stats["prefix_blocks_reused"] >= 2, eng.stats
        rep = eng.kv_leak_report()
        assert rep["leaked"] == 0 and rep["unaccounted"] == 0, rep
        return out

    a, b = run(True), run(False)
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def test_aot_warm_start_covers_prefill_knob(tiny_serving, tmp_path):
    """The artifact config hash covers ``fused_prefill``: a fused
    export warm starts a fused engine bit-identically, and an engine
    with the knob FLIPPED refuses the artifact (no half-warm fused
    engine serving unfused-compiled programs or vice versa)."""
    from paddle_tpu.aot.serve import export_engine
    cfg, params, prompts = tiny_serving
    eng = _engine(cfg, params, fused=True, prefill_buckets=(8,))
    export_engine(eng, str(tmp_path))
    warm = _engine(cfg, params, fused=True, prefill_buckets=(8,),
                   aot_dir=str(tmp_path))
    assert warm.aot_loaded
    a = _drain(warm, prompts)
    b = _drain(_engine(cfg, params, fused=True, prefill_buckets=(8,)),
               prompts)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
    cold = _engine(cfg, params, fused=False, prefill_buckets=(8,),
                   aot_dir=str(tmp_path))
    assert not cold.aot_loaded
    assert cold.aot_error is not None
