"""Static-graph passes + dygraph-vs-static equivalence (VERDICT r3 item
8; reference python/paddle/distributed/passes/ auto_parallel_amp +
auto_parallel_gradient_merge, and the reference's core static guarantee
that a program trains identically to eager)."""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu import static


def _mlp_train_prog(lr=0.1, opt_cls=optim.SGD, seed=0):
    pt.seed(seed)
    pt.enable_static()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        y = static.data("y", [None, 1], "float32")
        lin1 = nn.Linear(8, 16)
        lin2 = nn.Linear(16, 1)
        pred = lin2(pt.tanh(lin1(x)))
        loss = pt.mean((pred - y) ** 2)
        opt = opt_cls(learning_rate=lr)
        opt.minimize(loss)
    pt.disable_static()
    return main, loss, pred, (lin1, lin2)


def _reg_data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 8).astype(np.float32)
    W = rng.randn(8, 1).astype(np.float32)
    return X, np.tanh(X @ W) * 0.7


class TestAmpPass:
    def test_matmul_runs_bf16_softmax_fp32(self):
        pt.enable_static()
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            w = static.data("w", [8, 8], "float32")
            h = pt.matmul(x, w)
            s = pt.softmax(h)
        pt.disable_static()
        static.apply_amp_pass(main, level="O1")
        exe = static.Executor()
        rng = np.random.RandomState(0)
        hv, sv = exe.run(main, feed={"x": rng.randn(4, 8).astype("f4"),
                                     "w": rng.randn(8, 8).astype("f4")},
                         fetch_list=[h, s])
        assert hv.dtype == np.dtype("bfloat16") or str(hv.dtype) == \
            "bfloat16", hv.dtype                  # white op output
        assert sv.dtype == np.float32             # black op back to fp32
        np.testing.assert_allclose(sv.sum(axis=1), 1.0, rtol=1e-3)

    def test_amp_training_tracks_fp32(self):
        X, Y = _reg_data()
        main32, loss32, _, _ = _mlp_train_prog(seed=7)
        main16, loss16, _, _ = _mlp_train_prog(seed=7)
        static.apply_amp_pass(main16, level="O1")
        e32, e16 = static.Executor(), static.Executor()
        l32 = [float(e32.run(main32, feed={"x": X, "y": Y},
                             fetch_list=[loss32])[0]) for _ in range(20)]
        l16 = [float(e16.run(main16, feed={"x": X, "y": Y},
                             fetch_list=[loss16])[0]) for _ in range(20)]
        assert l16[-1] < l16[0] * 0.7             # AMP program trains
        assert abs(l16[-1] - l32[-1]) < 0.1 * max(l32[0], 1e-3), \
            (l32[-1], l16[-1])

    def test_bad_level_rejected(self):
        main, *_ = _mlp_train_prog()
        with pytest.raises(ValueError):
            static.apply_amp_pass(main, level="O3")


class TestGradientMergePass:
    def test_k_step_merge_equals_big_batch(self):
        # k accumulation micro-steps over shards == one step on the full
        # batch (SGD linearity makes this exact)
        X, Y = _reg_data(n=32, seed=1)
        merged, lossm, _, _ = _mlp_train_prog(lr=0.2, seed=11)
        static.apply_gradient_merge_pass(merged, k_steps=2)
        full, lossf, _, _ = _mlp_train_prog(lr=0.2, seed=11)
        em, ef = static.Executor(), static.Executor()
        for _ in range(3):                        # 3 optimizer updates
            em.run(merged, feed={"x": X[:16], "y": Y[:16]},
                   fetch_list=[lossm])
            em.run(merged, feed={"x": X[16:], "y": Y[16:]},
                   fetch_list=[lossm])
        for _ in range(3):
            ef.run(full, feed={"x": X, "y": Y}, fetch_list=[lossf])
        lm = float(em.run(merged, feed={"x": X, "y": Y},
                          fetch_list=[lossm])[0])
        lf = float(ef.run(full, feed={"x": X, "y": Y},
                          fetch_list=[lossf])[0])
        # mean over 2 half-batches == mean over full batch -> identical
        # trajectories up to fp noise
        assert lm == pytest.approx(lf, rel=1e-3), (lm, lf)

    def test_params_frozen_within_window(self):
        X, Y = _reg_data(n=32, seed=2)
        main, loss, _, (lin1, _) = _mlp_train_prog(lr=0.2, seed=3)
        static.apply_gradient_merge_pass(main, k_steps=3)
        exe = static.Executor()
        w0 = np.asarray(lin1.weight._value).copy()
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        np.testing.assert_array_equal(w0,
                                      np.asarray(lin1.weight._value))
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        assert not np.array_equal(w0, np.asarray(lin1.weight._value))

    def test_bad_k_rejected(self):
        main, *_ = _mlp_train_prog()
        with pytest.raises(ValueError):
            static.apply_gradient_merge_pass(main, k_steps=0)


class TestDygraphStaticEquivalence:
    """The reference's core static guarantee on a REAL model: GPT-tiny
    trains to the same loss curve eager and via the static Executor."""

    def test_gpt_tiny_loss_curves_match(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        pt.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_position_embeddings=32)
        net = GPTForCausalLM(cfg)
        params0 = {n: np.asarray(p._value).copy()
                   for n, p in net.named_parameters()}
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (4, 16)).astype(np.int64)
        labels = np.roll(ids, -1, axis=1)
        steps, lr = 6, 0.05

        # --- eager ---
        opt = optim.SGD(learning_rate=lr, parameters=net.parameters())
        eager_losses = []
        for _ in range(steps):
            loss = net(pt.to_tensor(ids), labels=pt.to_tensor(labels))
            if isinstance(loss, tuple):
                loss = loss[0]
            loss.backward()
            opt.step()
            opt.clear_grad()
            eager_losses.append(float(loss))

        # --- reset params, record static program over the SAME layer ---
        for n, p in net.named_parameters():
            p.set_value(params0[n])
        pt.enable_static()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            v_ids = static.data("ids", [4, 16], "int64")
            v_lab = static.data("labels", [4, 16], "int64")
            loss_v = net(v_ids, labels=v_lab)
            if isinstance(loss_v, tuple):
                loss_v = loss_v[0]
            sopt = optim.SGD(learning_rate=lr)
            sopt.minimize(loss_v)
        pt.disable_static()

        exe = static.Executor()
        static_losses = [
            float(exe.run(main, feed={"ids": ids, "labels": labels},
                          fetch_list=[loss_v])[0])
            for _ in range(steps)]

        np.testing.assert_allclose(eager_losses, static_losses,
                                   rtol=2e-4, atol=2e-4)
        assert static_losses[-1] < static_losses[0]


class TestStaticCondVariablePredicate:
    def test_cond_respects_runtime_predicate(self):
        # review finding: a Variable predicate must NOT be Python-truthy
        pt.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                p = static.data("p", [], "bool")
                out = static.nn.cond(
                    p, lambda: pt.ones((2,)), lambda: pt.zeros((2,)))
            exe = static.Executor()
            hi = exe.run(main, feed={"p": np.array(True)},
                         fetch_list=[out])[0]
            lo = exe.run(main, feed={"p": np.array(False)},
                         fetch_list=[out])[0]
        finally:
            pt.disable_static()
        np.testing.assert_allclose(hi, [1, 1])
        np.testing.assert_allclose(lo, [0, 0])


class TestPassManager:
    """Pass registry/manager + DRR-style chain rewrite (reference
    pass_base.py PassManager + pir/drr fusion rules)."""

    def _matmul_add_prog(self):
        pt.enable_static()
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            w = static.data("w", [8, 8], "float32")
            b = static.data("b", [8], "float32")
            h = pt.add(pt.matmul(x, w), b)
            out = pt.sum(h)
        pt.disable_static()
        return main, h, out

    def test_registry_and_names(self):
        pm = static.PassManager(["fuse_matmul_add",
                                 "dead_code_elimination"])
        assert pm.names == ["fuse_matmul_add", "dead_code_elimination"]
        with pytest.raises(KeyError):
            static.PassManager(["not_a_pass"])

    def test_fuse_matmul_add_preserves_results(self):
        main, h, out = self._matmul_add_prog()
        n0 = len(main.nodes)
        static.PassManager(["fuse_matmul_add"]).apply(main)
        assert len(main.nodes) == n0 - 1
        assert any(n.name == "linear" for n in main.nodes)
        exe = static.Executor()
        rng = np.random.RandomState(0)
        X, W, B = (rng.randn(4, 8).astype("f4"),
                   rng.randn(8, 8).astype("f4"),
                   rng.randn(8).astype("f4"))
        hv, ov = exe.run(main, feed={"x": X, "w": W, "b": B},
                         fetch_list=[h, out])
        np.testing.assert_allclose(hv, X @ W + B, rtol=1e-5)

    def test_custom_pass_registration(self):
        from paddle_tpu.static.pass_manager import register_pass

        @register_pass("test_count_nodes")
        def count_pass(program):
            program._node_count = len(program.nodes)
            return program

        main, _, _ = self._matmul_add_prog()
        static.PassManager(["test_count_nodes"]).apply(main)
        assert main._node_count == 3

    def test_dce_requires_anchor(self):
        main, h, out = self._matmul_add_prog()
        n0 = len(main.nodes)
        from paddle_tpu.static.pass_manager import dead_code_elimination
        dead_code_elimination(main)          # no loss, no keep: no-op
        assert len(main.nodes) == n0
        dead_code_elimination(main, keep=[h])
        assert len(main.nodes) == 2          # sum(out) dropped

    def test_pipeline_with_amp(self):
        main, h, out = self._matmul_add_prog()
        pm = static.PassManager(["fuse_matmul_add", "amp"],
                                opts={"amp": {"level": "O1"}})
        pm.apply(main)
        exe = static.Executor()
        rng = np.random.RandomState(1)
        hv, = exe.run(main, feed={"x": rng.randn(4, 8).astype("f4"),
                                  "w": rng.randn(8, 8).astype("f4"),
                                  "b": rng.randn(8).astype("f4")},
                      fetch_list=[h])
        assert str(hv.dtype) == "bfloat16"   # fused linear is white-listed

    def test_fuse_handles_repeated_intermediate(self):
        # review finding: add(m, m) must wire BOTH slots to the chained
        # output, not create a self-dependency
        pt.enable_static()
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x2", [4, 8], "float32")
            w = static.data("w2", [8, 8], "float32")
            m = pt.matmul(x, w)
            h = pt.add(m, m)
        pt.disable_static()
        static.PassManager(["fuse_matmul_add"]).apply(main)
        exe = static.Executor()
        rng = np.random.RandomState(2)
        X, W = rng.randn(4, 8).astype("f4"), rng.randn(8, 8).astype("f4")
        hv, = exe.run(main, feed={"x2": X, "w2": W}, fetch_list=[h])
        np.testing.assert_allclose(hv, 2 * (X @ W), rtol=1e-5)

    def test_unknown_opts_rejected(self):
        main, _, _ = self._matmul_add_prog()
        pm = static.PassManager(["fuse_matmul_add"],
                                opts={"not_in_pipeline": {"x": 1}})
        with pytest.raises(KeyError):
            pm.apply(main)
