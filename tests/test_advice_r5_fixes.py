"""ADVICE r5 satellite fixes.

#1 — proactive graph-break trigger narrowed to bare/Exception/BaseException
     handlers (``except TypeError`` keeps whole-graph jit);
#2 — segment jit caches are LRU-bounded and int/float scalar live-ins ride
     as ARRAY inputs (a varying step counter no longer recompiles);
#4 — ``tuned_flash``'s dispatched backend call falls back to the in-tree
     ``ours`` kernel when a platform kernel rejects the signature;
#5 — ``masked_multihead_attention`` validates the beam-offset table covers
     exactly the cache capacity.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.graph_break import build_hybrid, needs_proactive_break

rng = np.random.default_rng(0)


# ---------------------------------------------------------------------------
# ADVICE #2 — scalar live-ins + bounded caches
# ---------------------------------------------------------------------------

def _jit_segments(hf):
    return [seg for kind, seg in hf.segments if kind == "jit"]


def test_varying_scalar_live_in_does_not_recompile():
    def f(x, n):
        import math  # noqa: F401  — static break splits the function
        y = x * n + 1.0
        return y

    hf = build_hybrid(f)
    assert hf is not None
    for i in range(6):
        out = hf(Tensor(jnp.ones((3,))), i)
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.full((3,), i + 1.0), rtol=1e-6)
    seg = _jit_segments(hf)[-1]
    # one compiled program serves every value of the scalar
    assert len(seg._jit_cache) == 1
    assert seg.compiled_calls == 6
    assert seg.eager_calls == 0


def test_scalar_used_statically_falls_back_and_stays_correct():
    def g(x, n):
        import math  # noqa: F401
        y = x.reshape([n, 2]) * 1.0  # n must be CONCRETE: shape argument
        return y

    hf = build_hybrid(g)
    assert hf is not None
    out2 = hf(Tensor(jnp.zeros((4,))), 2)
    out3 = hf(Tensor(jnp.zeros((6,))), 3)
    assert np.asarray(getattr(out2, "_value", out2)).shape == (2, 2)
    assert np.asarray(getattr(out3, "_value", out3)).shape == (3, 2)
    # the failed scalar-as-array trace memoized scalars back to static
    assert any(getattr(seg, "_scalars_static", False) or seg._eager
               for seg in _jit_segments(hf))


def test_segment_jit_cache_is_lru_bounded():
    from paddle_tpu.utils.lru import LRUCache

    def f(x, tag):
        import math  # noqa: F401
        y = x + (1.0 if tag == "a" else 2.0)
        return y

    hf = build_hybrid(f)
    seg = _jit_segments(hf)[-1]
    assert isinstance(seg._jit_cache, LRUCache)
    for i in range(40):          # distinct static signatures
        hf(Tensor(jnp.ones(())), f"t{i}")
    assert len(seg._jit_cache) <= seg._jit_cache.maxsize


# ---------------------------------------------------------------------------
# ADVICE #1 — narrowed proactive-break trigger
# ---------------------------------------------------------------------------

def test_proactive_break_trigger_narrowed():
    def broad_exc(x):
        try:
            return x + 1
        except Exception:
            return x

    def broad_bare(x):
        try:
            return x + 1
        except:  # noqa: E722
            return x

    def broad_base(x):
        try:
            return x + 1
        except BaseException:
            return x

    def narrow_type(x):
        try:
            return x + 1
        except TypeError:
            return x

    def narrow_key(x):
        try:
            return x + 1
        except (KeyError, ValueError):
            return x

    assert needs_proactive_break(broad_exc)
    assert needs_proactive_break(broad_bare)
    assert needs_proactive_break(broad_base)
    assert not needs_proactive_break(narrow_type)
    assert not needs_proactive_break(narrow_key)


# ---------------------------------------------------------------------------
# ADVICE #4 — tuned_flash platform-backend fallback
# ---------------------------------------------------------------------------

def test_tuned_flash_falls_back_to_ours_on_backend_failure(monkeypatch):
    from paddle_tpu.ops.pallas import flash_backends as fb

    def boom(*a, **k):
        raise RuntimeError("platform kernel rejected signature")

    monkeypatch.setitem(fb._IMPLS, "boom", boom)
    monkeypatch.setattr(fb, "_pick_backend",
                        lambda *a, **k: "boom")
    q = jnp.asarray(rng.standard_normal((1, 8, 2, 16)).astype(np.float32))
    k_ = jnp.asarray(rng.standard_normal((1, 8, 2, 16)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((1, 8, 2, 16)).astype(np.float32))
    out = fb.tuned_flash(q, k_, v, causal=True)
    ref = fb.run_backend("ours", q, k_, v,
                         1.0 / np.sqrt(16), True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_tuned_flash_ours_failure_still_raises(monkeypatch):
    from paddle_tpu.ops.pallas import flash_backends as fb

    def boom(*a, **k):
        raise RuntimeError("ours broke")

    monkeypatch.setitem(fb._IMPLS, "ours", boom)
    monkeypatch.setattr(fb, "_pick_backend", lambda *a, **k: "ours")
    q = jnp.ones((1, 4, 1, 8), jnp.float32)
    with pytest.raises(RuntimeError, match="ours broke"):
        fb.tuned_flash(q, q, q, causal=True)


# ---------------------------------------------------------------------------
# ADVICE #5 — mmha beam-offset capacity validation
# ---------------------------------------------------------------------------

def test_mmha_beam_offset_capacity_mismatch_raises():
    from paddle_tpu.incubate.nn import functional as IF
    bbz, bw, H, D, T = 1, 2, 2, 8, 16
    B = bbz * bw
    x = rng.standard_normal((B, 3 * H * D)).astype(np.float32)
    cache = np.zeros((2, B, H, T, D), np.float32)
    lens = np.full((B,), 4, np.int32)
    off_short = np.zeros((bbz, bw, T - 4), np.int32)
    with pytest.raises(ValueError, match="cache capacity"):
        IF.masked_multihead_attention(
            pt.to_tensor(x), pt.to_tensor(cache),
            sequence_lengths=pt.to_tensor(lens),
            beam_cache_offset=pt.to_tensor(off_short))
    off_long = np.zeros((bbz, bw, T + 4), np.int32)
    with pytest.raises(ValueError, match="cache capacity"):
        IF.masked_multihead_attention(
            pt.to_tensor(x), pt.to_tensor(cache),
            sequence_lengths=pt.to_tensor(lens),
            beam_cache_offset=pt.to_tensor(off_long))
    # exact capacity still works
    off_ok = np.zeros((bbz, bw, T), np.int32)
    out, new_cache, off_out = IF.masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(lens),
        beam_cache_offset=pt.to_tensor(off_ok))
    assert np.asarray(off_out).shape == (bbz, bw, T)
