"""Native C++ data-loader core tests (the [NATIVE] requirement — SURVEY §2:
buffered readers/BlockingQueue equivalents must be real native code)."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu import native


def test_library_builds():
    assert native.available(), "C++ core failed to build (g++ is baked in)"


def test_shuffle_indices_permutation():
    idx = native.shuffle_indices(1000, seed=42)
    assert sorted(idx.tolist()) == list(range(1000))
    idx2 = native.shuffle_indices(1000, seed=42)
    np.testing.assert_array_equal(idx, idx2)  # deterministic per seed
    idx3 = native.shuffle_indices(1000, seed=43)
    assert not np.array_equal(idx, idx3)


def test_collate_stack_matches_numpy():
    rng = np.random.default_rng(0)
    samples = [rng.standard_normal((64, 64)).astype(np.float32)
               for _ in range(16)]
    out = native.collate_stack(samples)
    np.testing.assert_array_equal(out, np.stack(samples))
    # non-contiguous input still correct
    nc = [s.T for s in samples]
    np.testing.assert_array_equal(native.collate_stack(nc), np.stack(nc))


def test_token_ring_fifo_and_blocking():
    ring = native.TokenRing(4)
    for i in range(4):
        assert ring.push(i)
    assert len(ring) == 4
    got = [ring.pop() for _ in range(4)]
    assert got == [0, 1, 2, 3]

    # producer blocks when full until consumer pops
    ring2 = native.TokenRing(1)
    ring2.push(0)
    state = {"pushed": False}

    def producer():
        ring2.push(1)
        state["pushed"] = True

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.1)
    assert not state["pushed"]  # blocked on full ring
    assert ring2.pop() == 0
    t.join(timeout=2)
    assert state["pushed"]
    assert ring2.pop() == 1


def test_token_ring_close_drains():
    ring = native.TokenRing(4)
    ring.push(7)
    ring.close()
    assert ring.pop() == 7   # drained after close
    assert ring.pop() is None
    assert not ring.push(9)  # push after close fails


def test_dataloader_uses_native(tmp_path):
    import paddle_tpu as pt
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import TensorDataset

    rng = np.random.default_rng(1)
    xs = rng.standard_normal((32, 8)).astype(np.float32)
    ys = rng.integers(0, 4, (32,)).astype(np.int64)
    ds = TensorDataset([pt.to_tensor(xs), pt.to_tensor(ys)])
    dl = DataLoader(ds, batch_size=8, shuffle=True)
    seen = 0
    for xb, yb in dl:
        assert tuple(xb.shape) == (8, 8)
        seen += 1
    assert seen == 4
