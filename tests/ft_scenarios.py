"""Fault-tolerance end-to-end scenarios, run in a FRESH subprocess by
tests/test_fault_tolerance.py (``python ft_scenarios.py <name> <tmpdir>``).

Why a subprocess: the scenarios assert BIT-EXACT equality between an
interrupted+resumed run and an uninterrupted one.  The pinned jax
0.4.37 XLA:CPU build mis-executes donated programs deserialized from
the persistent compilation cache (see test_fault_tolerance's module
fixture) — and inside a long pytest process the heap may already carry
damage from earlier warm-cache modules, which flips these comparisons
nondeterministically.  A fresh process compiles everything cold, where
the numerics are reliably bit-exact; each scenario prints ``OK <name>``
and exits 0, or dies with the failing assert.
"""

import os
import signal
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import paddle_tpu as pt                                   # noqa: E402
from paddle_tpu import nn                                 # noqa: E402
from paddle_tpu.checkpoint import (TrainingPreempted,     # noqa: E402
                                   latest_checkpoint)
from paddle_tpu.hapi.callbacks import Callback            # noqa: E402
from paddle_tpu.io.dataset import TensorDataset           # noqa: E402


def make_model(scaler=None):
    net = nn.Sequential(nn.Flatten(), nn.Linear(16, 8), nn.ReLU(),
                        nn.Linear(8, 4))
    m = pt.Model(net)
    m.prepare(
        optimizer=pt.optimizer.Adam(1e-2, parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(), amp_configs=scaler)
    return m


def dataset(n=64):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 16)).astype(np.float32)
    Y = rng.integers(0, 4, size=(n,)).astype(np.int64)
    return TensorDataset([X, Y])


def net_state(m):
    return {k: v.numpy().copy() for k, v in m.network.state_dict().items()}


def opt_slots(m):
    per = m._optimizer.unflatten_state(m._opt_state)
    return {f"{p}/{s}": np.asarray(v).copy()
            for p, slots in per.items() for s, v in slots.items()}


def assert_states_equal(a, b):
    assert a.keys() == b.keys(), (sorted(a), sorted(b))
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def reference_run(epochs=4):
    pt.seed(7)
    ref = make_model()
    ref.fit(dataset(), batch_size=16, epochs=epochs, verbose=0,
            shuffle=True)
    return ref


# ---------------------------------------------------------------------------
def epoch_boundary(d):
    """Interrupt at an epoch boundary; resume must be bit-exact."""
    ref = reference_run()
    pt.seed(7)
    first = make_model()
    first.fit(dataset(), batch_size=16, epochs=2, verbose=0,
              shuffle=True, save_dir=d)
    resumed = make_model()
    resumed.fit(dataset(), batch_size=16, epochs=4, verbose=0,
                shuffle=True, save_dir=d, resume="auto")
    assert resumed._step_count == ref._step_count, (
        resumed._step_count, ref._step_count)
    assert_states_equal(net_state(ref), net_state(resumed))
    assert_states_equal(opt_slots(ref), opt_slots(resumed))


def sigterm_midepoch(d):
    """SIGTERM mid-epoch flushes a checkpoint; resume replays the
    epoch's shuffle, fast-forwards, and continues bit-exact."""
    ref = reference_run(epochs=3)
    pt.seed(7)
    victim = make_model()

    class Preempt(Callback):
        def on_train_batch_end(self, step, logs=None):
            if self.model._step_count == 6:       # mid epoch 1
                os.kill(os.getpid(), signal.SIGTERM)

    try:
        victim.fit(dataset(), batch_size=16, epochs=3, verbose=0,
                   shuffle=True, save_dir=d, callbacks=[Preempt()])
        raise AssertionError("fit was not preempted")
    except TrainingPreempted:
        pass
    assert latest_checkpoint(d) is not None
    resumed = make_model()
    resumed.fit(dataset(), batch_size=16, epochs=3, verbose=0,
                shuffle=True, save_dir=d, resume="auto")
    assert resumed._step_count == ref._step_count, (
        resumed._step_count, ref._step_count)
    assert_states_equal(net_state(ref), net_state(resumed))
    assert_states_equal(opt_slots(ref), opt_slots(resumed))


def crash_mid_checkpoint(d):
    """Every save of the second run dies mid-write: ``latest`` must keep
    naming the last good checkpoint, and a third run resumes from it."""
    from paddle_tpu.framework import io as fio

    pt.seed(9)
    m = make_model()
    m.fit(dataset(), batch_size=16, epochs=2, verbose=0, save_dir=d)
    good = latest_checkpoint(d)
    assert good is not None

    real = fio._write_bytes

    def dying(f, data):
        real(f, data[:48])
        f.flush()
        raise RuntimeError("simulated kill mid checkpoint write")

    fio._write_bytes = dying
    try:
        m.fit(dataset(), batch_size=16, epochs=3, verbose=0,
              save_dir=d, resume="auto")
        raise AssertionError("crashing save did not propagate")
    except RuntimeError:
        pass
    finally:
        fio._write_bytes = real
    assert latest_checkpoint(d) == good, (latest_checkpoint(d), good)
    resumed = make_model()
    resumed.fit(dataset(), batch_size=16, epochs=3, verbose=0,
                save_dir=d, resume="auto")
    assert resumed._step_count == 12, resumed._step_count


def async_resume(d):
    """async_save=True writes usable checkpoints; resume continues."""
    pt.seed(11)
    m = make_model()
    m.fit(dataset(), batch_size=16, epochs=2, verbose=0,
          save_dir=d, async_save=True)
    assert latest_checkpoint(d) is not None
    resumed = make_model()
    resumed.fit(dataset(), batch_size=16, epochs=4, verbose=0,
                save_dir=d, resume="auto", async_save=True)
    assert resumed._step_count == 16, resumed._step_count


def loss_scale_resume(d):
    """The dynamic loss scale survives checkpoint/resume."""
    pt.seed(5)
    m = make_model(scaler=pt.amp.GradScaler(init_loss_scaling=4096.0))
    m._scaler._scale = 128.0          # pretend backoffs happened
    m.fit(dataset(), batch_size=16, epochs=1, verbose=0, save_dir=d)
    resumed = make_model(
        scaler=pt.amp.GradScaler(init_loss_scaling=4096.0))
    resumed.fit(dataset(), batch_size=16, epochs=1, verbose=0,
                save_dir=d, resume="auto")
    # 4 good steps at incr_every=1000 leave the restored scale untouched
    assert resumed._scaler.get_loss_scaling() == 128.0, \
        resumed._scaler.get_loss_scaling()


SCENARIOS = {
    "epoch_boundary": epoch_boundary,
    "sigterm_midepoch": sigterm_midepoch,
    "crash_mid_checkpoint": crash_mid_checkpoint,
    "async_resume": async_resume,
    "loss_scale_resume": loss_scale_resume,
}


if __name__ == "__main__":
    name, tmpdir = sys.argv[1], sys.argv[2]
    SCENARIOS[name](os.path.join(tmpdir, "run"))
    print(f"OK {name}")
