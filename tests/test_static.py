"""paddle.static Program/Executor facade (reference
test/legacy_test/test_executor_and_use_program_cache.py flavor: build a
program with static.data, run it with Executor over feeds)."""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu import static


@pytest.fixture(autouse=True)
def static_mode():
    pt.enable_static()
    yield
    pt.disable_static()


def test_build_and_run_program():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 3], "float32")
        y = static.data("y", [4, 3], "float32")
        z = pt.add(pt.multiply(x, y), pt.ones((4, 3)))
        out = pt.sum(z)
    exe = static.Executor()
    xs = np.full((4, 3), 2.0, np.float32)
    ys = np.full((4, 3), 3.0, np.float32)
    res = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[z, out])
    np.testing.assert_allclose(res[0], 7.0)
    assert res[1] == pytest.approx(84.0)


def test_program_records_not_executes():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        h = pt.exp(x)
    assert isinstance(h, static.Variable)
    assert h.shape == (2, 2)
    assert len(main.nodes) == 1


def test_nn_layer_in_static_program():
    from paddle_tpu import nn
    lin = nn.Linear(4, 2)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3, 4], "float32")
        y = lin(x)
        assert isinstance(y, static.Variable) and y.shape == (3, 2)
    exe = static.Executor()
    xs = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    res = exe.run(main, feed={"x": xs}, fetch_list=[y])
    w = np.asarray(lin.weight._value)
    b = np.asarray(lin.bias._value)
    np.testing.assert_allclose(res[0], xs @ w + b, rtol=1e-5)


def test_executor_caches_compiled_program():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2], "float32")
        y = x * 2.0
    exe = static.Executor()
    r1 = exe.run(main, feed={"x": np.ones(2, np.float32)}, fetch_list=[y])
    r2 = exe.run(main, feed={"x": np.full(2, 3.0, np.float32)},
                 fetch_list=[y])
    np.testing.assert_allclose(r1[0], 2.0)
    np.testing.assert_allclose(r2[0], 6.0)
    assert len(exe._cache) == 1


def test_default_main_program_guarded():
    base = static.default_main_program()
    p = static.Program()
    with static.program_guard(p):
        assert static.default_main_program() is p
    assert static.default_main_program() is base


def test_chained_softmax_matmul():
    from paddle_tpu.nn import functional as F
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 5], "float32")
        w = static.data("w", [5, 5], "float32")
        h = pt.matmul(x, w)
        p = F.softmax(h, axis=-1)
    exe = static.Executor()
    rng = np.random.default_rng(1)
    xs = rng.normal(size=(2, 5)).astype(np.float32)
    ws = rng.normal(size=(5, 5)).astype(np.float32)
    (got,) = exe.run(main, feed={"x": xs, "w": ws}, fetch_list=[p])
    ref = xs @ ws
    ref = np.exp(ref - ref.max(-1, keepdims=True))
    ref = ref / ref.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


class TestStaticTraining:
    """Static-graph training path (VERDICT r2 missing #8; reference:
    base/backward.py append_backward + optimizer ops + Executor): one
    fused jitted step of loss + grads + optimizer update per run()."""

    def _build(self, lr=0.1, opt_cls=None):
        import paddle_tpu.optimizer as optim
        pt.enable_static()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            y = static.data("y", [None, 1], "float32")
            lin = nn.Linear(4, 1)
            pred = lin(x)
            loss = pt.mean((pred - y) ** 2)
            opt = (opt_cls or optim.SGD)(learning_rate=lr)
            opt.minimize(loss)
        pt.disable_static()
        return main, startup, lin, pred, loss

    def _data(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 4).astype(np.float32)
        W = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
        return X, X @ W + 0.3

    def test_sgd_training_converges(self):
        main, startup, lin, pred, loss = self._build()
        exe = static.Executor()
        exe.run(startup)
        X, Y = self._data()
        losses = [float(exe.run(main, feed={"x": X, "y": Y},
                                fetch_list=[loss])[0])
                  for _ in range(50)]
        assert losses[-1] < losses[0] * 0.01, (losses[0], losses[-1])

    def test_adam_training_and_updated_weights_inference(self):
        import paddle_tpu.optimizer as optim
        main, startup, lin, pred, loss = self._build(
            lr=0.05, opt_cls=optim.Adam)
        exe = static.Executor()
        X, Y = self._data()
        for _ in range(60):
            exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        # inference clone replays with the UPDATED live parameters
        test_prog = main.clone(for_test=True)
        (p0,) = exe.run(test_prog, feed={"x": X[:4], "y": Y[:4]},
                        fetch_list=[pred])
        want = X[:4] @ np.asarray(lin.weight._value) \
            + np.asarray(lin.bias._value)
        np.testing.assert_allclose(p0, want, rtol=1e-4)

    def test_append_backward_lists_params(self):
        main, startup, lin, pred, loss = self._build()
        pairs = static.append_backward(loss)
        names = {p.name for p, _ in pairs}
        assert lin.weight.name in names and lin.bias.name in names

    def test_program_records_parameters(self):
        main, *_ = self._build()
        assert len(main.params) == 2       # weight + bias
