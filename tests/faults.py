"""Fault-injection harness: checkpoint durability (ISSUE 2) and the
serve-path chaos injectors (ISSUE 11).

Checkpoint side — simulates the two ways a preemption can interrupt
``framework.io.save``:

* :func:`crash_mid_write` — the process dies while the checkpoint's temp
  file is being written: only the first ``at_bytes`` bytes ever reach the
  file and ``os.replace`` never runs (a truncated ``.tmp-*`` straggler is
  all that's left).
* :func:`fail_replace` — the write completes but the atomic rename
  itself fails/never happens (kill between fsync and rename, or an
  ENOSPC/EIO at publish time).

Both patch the narrow seams ``framework.io`` exposes for exactly this
purpose (``_write_bytes`` / ``_replace``) rather than global ``os``
state, so the rest of the test process keeps working.  ``corrupt_file``
models post-crash bit-rot on an already-published checkpoint.

Serve side — chaos injectors for the continuous-batching engine and its
resilience supervisor (``paddle_tpu/serving/resilience.py``).  Each
wraps a narrow instance seam (``engine.step``, the extracted
``engine._prefill_into_slot``, the spec runner's ``run_decode``) so one
engine misbehaves while the rest of the process keeps working:

* :func:`fail_step_n` — declared crash (or any exception) at decode
  step N, before or after the real step runs (``where="after"`` models
  a crash that loses the step's return value but not its committed
  tokens).
* :func:`transient_step_faults` — the next ``n`` steps raise
  :class:`~paddle_tpu.serving.resilience.TransientStepError` before any
  work happens; the supervisor's retry/backoff path must absorb them.
* :func:`exhaust_kv_pool` — steals free pool blocks (down to ``leave``)
  so admission saturates and priority preemption has to fire.
* :func:`slow_steps` — adds latency to the next ``n`` steps (drives the
  supervisor's slow-step escalation).
* :func:`crash_mid_prefill` — raises inside the prefill AFTER the
  request's pages are mapped; the admission path must release them
  exactly once (the ISSUE 11 engine-hardening regression).
* :func:`crash_mid_speculation` — raises inside the spec-decode
  draft/verify round.

Fleet side (ISSUE 12, ``serving/fleet.py``):

* :func:`kill_replica_after_steps` — kill one router replica at a
  deterministic fleet-step count (mid-stream re-placement).
* :func:`persistent_replica_crash` — a replica that crashes on every
  step, rebuilds included, until its circuit breaker opens — the
  organic ``RecoveryExhaustedError`` death the router absorbs.

Wire side (ISSUE 13, ``serving/http.py``) — raw-socket chaos clients
for the HTTP/SSE front door.  These speak TCP directly (no
``http.client``) so they can misbehave in ways a well-formed client
cannot:

* :func:`http_disconnect_mid_stream` — open an SSE stream, read N
  token events, then close the socket hard (optionally with an RST via
  ``SO_LINGER``) — the server must cancel the request and free its KV
  pages.
* :func:`http_stalled_reader` — open a stream with a tiny receive
  buffer and never read: the TCP window closes and the server's write
  deadline must isolate the stall without touching batchmates.
* :func:`http_partial_line_writes` — dribble the request bytes a few
  at a time (slow/fragmenting client); the server must parse it like
  any other request.
* :func:`connect_then_abandon_flood` — open many connections that send
  little or nothing and vanish; the server must shed them without
  leaking threads or submitting anything.

Training side (ISSUE 17, ``parallel/elastic.py``) — chaos injectors for
the elastic trainer, all deterministic in the trainer's global step:

* :func:`kill_worker_at_step` — a typed ``WorkerLostError`` (with the
  lost device's flat mesh index) when the trainer reaches step N: the
  reshape-with-carryover / restore-and-replay path must fire.
* :func:`slow_worker` — adds host latency to the next ``n`` steps
  (drives the straggler DEGRADED state and, past the step deadline,
  the deadline-strike escalation).
* :func:`transient_collective_failure` — ``CollectiveTimeoutError``
  for the first ``failures`` attempts of step N; the bounded-backoff
  retry path must absorb them without advancing state.
* :func:`flip_gradient_bits` — silent data corruption: at step N one
  gradient element's exponent field is forced to all-ones INSIDE the
  traced step (worst-case SDC); the StepGuard composition must skip
  the update, not commit it.

The serve exceptions are ordinary ``Exception`` subclasses (unlike
:class:`SimulatedCrash`): a supervisor is SUPPOSED to catch and recover
from them, while the checkpoint kill must never be swallowed.
"""

from __future__ import annotations

import contextlib
import os
import time

from paddle_tpu.framework import io as fio

__all__ = ["InjectedEngineCrash", "SimulatedCrash",
           "connect_then_abandon_flood", "corrupt_file",
           "corrupt_offloaded_prefix", "crash_mid_prefill",
           "crash_mid_speculation",
           "crash_mid_write", "exhaust_kv_pool", "fail_replace",
           "fail_step_n", "flip_gradient_bits",
           "http_disconnect_mid_stream",
           "http_partial_line_writes", "http_stalled_reader",
           "kill_replica_after_steps", "kill_worker_at_step",
           "persistent_replica_crash", "slow_steps", "slow_worker",
           "transient_collective_failure", "transient_step_faults",
           "truncate_file"]


class SimulatedCrash(BaseException):
    """Stands in for the process dying mid-write.  Derives from
    BaseException so production code's ``except Exception`` recovery
    paths cannot accidentally swallow the injected kill."""


@contextlib.contextmanager
def crash_mid_write(monkeypatch, at_bytes: int = 64, crashes: int = 1):
    """Kill the checkpoint writer after ``at_bytes`` bytes of the temp
    file for the next ``crashes`` saves; later saves succeed.  Yields a
    stats dict (``stats['crashed']`` = number of injected kills)."""
    stats = {"crashed": 0}
    real = fio._write_bytes

    def patched(f, data):
        if stats["crashed"] < crashes:
            stats["crashed"] += 1
            real(f, data[:at_bytes])
            f.flush()
            raise SimulatedCrash(
                f"simulated kill after {at_bytes} bytes of "
                f"{len(data)}-byte checkpoint write")
        real(f, data)

    monkeypatch.setattr(fio, "_write_bytes", patched)
    try:
        yield stats
    finally:
        monkeypatch.setattr(fio, "_write_bytes", real)


@contextlib.contextmanager
def fail_replace(monkeypatch, failures: int = 1):
    """Make the atomic publish rename fail for the next ``failures``
    saves (completed temp file, no visible checkpoint)."""
    stats = {"failed": 0}
    real = fio._replace

    def patched(tmp, path):
        if stats["failed"] < failures:
            stats["failed"] += 1
            raise SimulatedCrash(
                f"simulated crash before rename {tmp!r} -> {path!r}")
        real(tmp, path)

    monkeypatch.setattr(fio, "_replace", patched)
    try:
        yield stats
    finally:
        monkeypatch.setattr(fio, "_replace", real)


def corrupt_file(path: str, offset: int = 96, garbage: bytes = b"\xde\xad"
                 ) -> None:
    """Flip bytes inside an already-published file (bit-rot model)."""
    size = os.path.getsize(path)
    offset = min(offset, max(size - len(garbage), 0))
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(garbage)


def truncate_file(path: str, keep_bytes: int) -> None:
    """Cut a published file short (torn write / partial flush model)."""
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)


# ---------------------------------------------------------------------
# serve-path chaos injectors (ISSUE 11)
# ---------------------------------------------------------------------
class InjectedEngineCrash(RuntimeError):
    """A declared engine crash injected by the chaos harness.  An
    ordinary ``Exception`` on purpose: the resilience supervisor is
    expected to catch it, tear the engine down, and replay."""


@contextlib.contextmanager
def fail_step_n(engine, n: int = 1, *, exc_type=InjectedEngineCrash,
                where: str = "before"):
    """Raise ``exc_type`` on the ``n``-th ``engine.step()`` call (1-
    based).  ``where="before"`` faults before the step runs (nothing
    committed); ``where="after"`` runs the real step first and then
    raises — the crash loses the step's RETURN VALUE (newly finished
    requests) but not the tokens it committed, the nastiest recovery
    case.  Yields a stats dict (``stats['crashed']``)."""
    assert where in ("before", "after"), where
    real = engine.step
    stats = {"calls": 0, "crashed": 0}

    def patched():
        stats["calls"] += 1
        if stats["calls"] == n:
            stats["crashed"] += 1
            if where == "after":
                real()
            raise exc_type(f"injected crash at step {n} ({where})")
        return real()

    engine.step = patched
    try:
        yield stats
    finally:
        # the engine object may have been torn down and rebuilt by a
        # supervisor; only unpatch if OUR wrapper is still installed
        if getattr(engine, "step", None) is patched:
            engine.step = real


@contextlib.contextmanager
def transient_step_faults(engine, n: int = 1, *, exc_type=None):
    """The next ``n`` ``engine.step()`` calls raise a transient fault
    BEFORE any work happens (a retry re-runs the identical step).
    Defaults to :class:`TransientStepError` so the supervisor's
    bounded-backoff retry path absorbs them."""
    if exc_type is None:
        from paddle_tpu.serving.resilience import TransientStepError
        exc_type = TransientStepError
    real = engine.step
    stats = {"raised": 0}

    def patched():
        if stats["raised"] < n:
            stats["raised"] += 1
            raise exc_type(
                f"injected transient fault {stats['raised']}/{n}")
        return real()

    engine.step = patched
    try:
        yield stats
    finally:
        if getattr(engine, "step", None) is patched:
            engine.step = real


@contextlib.contextmanager
def exhaust_kv_pool(engine, *, leave: int = 0):
    """Steal free KV pool blocks (down to ``leave``) for the duration:
    admission saturates, head-of-line requests wait, and priority
    preemption has a reason to fire.  The stolen blocks are returned on
    exit, so drain-time leak checks stay meaningful."""
    n = max(engine.alloc.free_blocks - leave, 0)
    stolen = engine.alloc.acquire(n) if n else []
    try:
        yield {"stolen": len(stolen or [])}
    finally:
        if stolen:
            engine.alloc.release(stolen)


@contextlib.contextmanager
def slow_steps(engine, extra_s: float, n: int = 1):
    """Add ``extra_s`` of host latency to the next ``n`` steps (models
    a hung DMA / a swapping host; drives the supervisor's slow-step
    escalation)."""
    real = engine.step
    stats = {"slowed": 0}

    def patched():
        if stats["slowed"] < n:
            stats["slowed"] += 1
            time.sleep(extra_s)
        return real()

    engine.step = patched
    try:
        yield stats
    finally:
        if getattr(engine, "step", None) is patched:
            engine.step = real


# ---------------------------------------------------------------------
# fleet chaos injectors (ISSUE 12)
# ---------------------------------------------------------------------
@contextlib.contextmanager
def kill_replica_after_steps(router, idx: int, n: int):
    """Kill fleet replica ``idx`` after the router's ``n``-th
    ``step()`` call (1-based) — a replica dying MID-STREAM, the fleet
    analogue of :func:`fail_step_n`.  Deterministic: the trigger is a
    step count, never wall clock.  Yields a stats dict
    (``stats['killed']``)."""
    real = router.step
    stats = {"calls": 0, "killed": 0}

    def patched():
        stats["calls"] += 1
        if stats["calls"] == n:
            stats["killed"] += 1
            router.kill_replica(idx, reason=f"injected kill at fleet "
                                            f"step {n}")
        return real()

    router.step = patched
    try:
        yield stats
    finally:
        if getattr(router, "step", None) is patched:
            router.step = real


def persistent_replica_crash(sup, *, exc_type=InjectedEngineCrash):
    """Make a supervised replica crash on every step FOREVER: the
    current engine faults, and the supervisor's rebuild factory is
    wrapped so every fresh engine faults too — the supervisor burns
    through its restart budget until the circuit breaker opens
    (``RecoveryExhaustedError``), the organic replica-death path the
    fleet router must absorb.  Returns a stats dict
    (``stats['crashes']``).  Permanently poisons the supervisor (this
    models a dead host, not a transient)."""
    stats = {"crashes": 0}

    def boom():
        stats["crashes"] += 1
        raise exc_type("persistent injected fault")

    real_factory = sup._factory

    def crashing_factory():
        eng = real_factory()
        eng.step = boom
        return eng

    sup.engine.step = boom
    sup._factory = crashing_factory
    return stats


# ---------------------------------------------------------------------
# wire chaos clients (ISSUE 13): raw-socket misbehavior against the
# HTTP/SSE front door
# ---------------------------------------------------------------------
def _generate_request_bytes(payload: dict, path: str = "/v1/generate"
                            ) -> bytes:
    import json
    body = json.dumps(payload).encode()
    return (f"POST {path} HTTP/1.1\r\n"
            f"Host: chaos\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n").encode() + body


def _read_sse_tokens(f, n_tokens: int, *, collect=None):
    """Read a raw HTTP response stream until ``n_tokens`` SSE ``token``
    events arrived (headers are skipped); returns the token ids read."""
    import json
    # headers
    while True:
        line = f.readline()
        if not line or line in (b"\r\n", b"\n"):
            break
    toks = []
    event = None
    while len(toks) < n_tokens:
        line = f.readline()
        if not line:
            break
        line = line.rstrip(b"\r\n")
        if line.startswith(b"event:"):
            event = line.split(b":", 1)[1].strip()
        elif line.startswith(b"data:") and event == b"token":
            toks.append(int(json.loads(line.split(b":", 1)[1])["t"]))
            if collect is not None:
                collect.append(toks[-1])
            event = None
    return toks


def http_disconnect_mid_stream(host: str, port: int, payload: dict, *,
                               after_tokens: int = 2,
                               rst: bool = False,
                               timeout_s: float = 30.0):
    """Open an SSE generate stream, read ``after_tokens`` token events,
    then vanish: plain ``close()`` (FIN) or, with ``rst``, an abortive
    close (``SO_LINGER`` 0 → RST, which fails the server's very next
    write).  Returns the token ids read before the disconnect."""
    import socket as _socket
    s = _socket.create_connection((host, port), timeout=timeout_s)
    try:
        s.sendall(_generate_request_bytes(payload))
        f = s.makefile("rb")
        toks = _read_sse_tokens(f, after_tokens)
        if rst:
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_LINGER,
                         __import__("struct").pack("ii", 1, 0))
    finally:
        s.close()
    return toks


def http_stalled_reader(host: str, port: int, payload: dict, *,
                        rcvbuf: int = 1024, timeout_s: float = 30.0):
    """Open a generate stream and STOP READING: the tiny ``SO_RCVBUF``
    (set before connect so the window is small from the handshake)
    fills, the TCP window closes, and the server's per-connection write
    deadline has to fire.  Returns the open socket — the caller owns
    closing it (keeping it open is the whole point of the stall)."""
    import socket as _socket
    s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    s.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, rcvbuf)
    s.settimeout(timeout_s)
    s.connect((host, port))
    s.sendall(_generate_request_bytes(payload))
    return s


def http_partial_line_writes(host: str, port: int, payload: dict, *,
                             chunk: int = 7, delay_s: float = 0.002,
                             timeout_s: float = 30.0):
    """Send a well-formed generate request a few bytes at a time
    (request line, headers, and body all fragmented mid-line — the
    slow/fragmenting-client model).  Reads the full response; returns
    ``(status_code, raw_response_bytes)``."""
    import socket as _socket
    data = _generate_request_bytes(payload)
    s = _socket.create_connection((host, port), timeout=timeout_s)
    try:
        for i in range(0, len(data), chunk):
            s.sendall(data[i:i + chunk])
            time.sleep(delay_s)
        raw = b""
        while True:
            got = s.recv(65536)
            if not got:
                break
            raw += got
    finally:
        s.close()
    status = int(raw.split(b" ", 2)[1]) if raw.startswith(b"HTTP/") else 0
    return status, raw


def connect_then_abandon_flood(host: str, port: int, n: int = 20, *,
                               partial_bytes: bytes = b"POST /v1/gen",
                               timeout_s: float = 5.0) -> int:
    """Open ``n`` connections that send at most a partial request line
    and disappear (half the flood sends nothing at all).  Returns the
    number of sockets opened; the server must shed every one without
    submitting a request or wedging a handler thread."""
    import socket as _socket
    opened = 0
    for i in range(n):
        try:
            s = _socket.create_connection((host, port),
                                          timeout=timeout_s)
        except OSError:
            continue
        opened += 1
        try:
            if i % 2 == 0 and partial_bytes:
                s.sendall(partial_bytes)
        except OSError:
            pass          # flood sockets are fire-and-forget by design
        finally:
            s.close()
    return opened


def corrupt_offloaded_prefix(engine, n: int = 1) -> int:
    """Flip bytes inside up to ``n`` of the prefix cache's OFFLOADED
    host-RAM blocks (oldest first) — the bit-rot model for the ISSUE 14
    offload tier, mirroring :func:`corrupt_file` for checkpoints.  The
    CRCs are left stale, so the next restore of a corrupted block must
    fail typed (``SpillCorruptError`` internally, a ``prefix_bitrot``
    event + ``restore_failures`` counter externally) and fall back to
    recomputing the suffix.  Returns the number of blocks corrupted."""
    done = 0
    for node in engine.prefix_cache._host_lru.values():
        if done >= n:
            break
        node.k_bytes.view("uint8").reshape(-1)[:2] ^= 0xAD
        done += 1
    return done


@contextlib.contextmanager
def crash_mid_prefill(engine, *, exc_type=InjectedEngineCrash,
                      crashes: int = 1):
    """Raise from inside the prefill of the next ``crashes`` admissions
    — AFTER the request's pages are mapped into the slot, the exact
    window where a sloppy scheduler would leak or double-free them.
    The admission path must release the pages exactly once and keep
    the request waiting (regression-pinned in test_serving_engine)."""
    real = engine._prefill_into_slot
    stats = {"crashed": 0}

    def patched(slot, req, L):
        if stats["crashed"] < crashes:
            stats["crashed"] += 1
            raise exc_type(
                f"injected crash mid-prefill of request {req.req_id}")
        return real(slot, req, L)

    engine._prefill_into_slot = patched
    try:
        yield stats
    finally:
        if getattr(engine, "_prefill_into_slot", None) is patched:
            engine._prefill_into_slot = real


@contextlib.contextmanager
def crash_mid_speculation(engine, *, exc_type=InjectedEngineCrash,
                          crashes: int = 1):
    """Raise from inside the next ``crashes`` speculative decode rounds
    (the engine must have a ``spec_config``).  Fires before the round
    commits, so recovery replays from the last committed prefix."""
    runner = engine._spec
    assert runner is not None, "engine is not speculating"
    real = runner.run_decode
    stats = {"crashed": 0}

    def patched(active):
        if stats["crashed"] < crashes:
            stats["crashed"] += 1
            raise exc_type("injected crash mid-speculation")
        return real(active)

    runner.run_decode = patched
    try:
        yield stats
    finally:
        if getattr(runner, "run_decode", None) is patched:
            runner.run_decode = real


# ---------------------------------------------------------------------
# training chaos injectors (ISSUE 17, parallel/elastic.py)
# ---------------------------------------------------------------------
@contextlib.contextmanager
def kill_worker_at_step(trainer, step: int, *, lost_index: int = 0,
                        axis: str = "dp", once: bool = True):
    """Raise a typed ``WorkerLostError`` (flat mesh index
    ``lost_index`` on ``axis``) when the trainer dispatches the step
    whose global index is ``step`` — before any state commits, so the
    reshaped mesh re-executes the identical step.  The patch rides the
    CURRENT engine instance; the post-reshape engine is a new object
    and comes up clean (exactly one worker dies)."""
    from paddle_tpu.parallel.elastic import WorkerLostError
    eng = trainer.engine
    real = eng.train_batch
    stats = {"fired": 0}

    def patched(inputs, labels=None, rng=None):
        if eng._step_count == step and (not once or stats["fired"] == 0):
            stats["fired"] += 1
            raise WorkerLostError(
                f"injected device loss at step {step}",
                lost_index=lost_index, axis=axis)
        return real(inputs, labels, rng=rng)

    eng.train_batch = patched
    try:
        yield stats
    finally:
        if getattr(eng, "train_batch", None) is patched:
            eng.train_batch = real


@contextlib.contextmanager
def slow_worker(trainer, extra_s: float, n: int = 1):
    """Add ``extra_s`` of host latency to the next ``n`` training steps
    (a swapping host / thermally-throttled chip): the straggler window
    must flag DEGRADED, and past the step deadline the strike counter
    escalates to a declared loss."""
    eng = trainer.engine
    real = eng.train_batch
    stats = {"slowed": 0}

    def patched(inputs, labels=None, rng=None):
        if stats["slowed"] < n:
            stats["slowed"] += 1
            time.sleep(extra_s)
        return real(inputs, labels, rng=rng)

    eng.train_batch = patched
    try:
        yield stats
    finally:
        if getattr(eng, "train_batch", None) is patched:
            eng.train_batch = real


@contextlib.contextmanager
def transient_collective_failure(trainer, step: int, *, failures: int = 1,
                                 lost_index=None, axis: str = "dp"):
    """``CollectiveTimeoutError`` for the first ``failures`` attempts of
    global step ``step``, then the real step runs: the bounded-backoff
    retry path must absorb the fault without advancing any state and
    without a reshape."""
    from paddle_tpu.parallel.elastic import CollectiveTimeoutError
    eng = trainer.engine
    real = eng.train_batch
    stats = {"raised": 0}

    def patched(inputs, labels=None, rng=None):
        if eng._step_count == step and stats["raised"] < failures:
            stats["raised"] += 1
            raise CollectiveTimeoutError(
                f"injected collective timeout "
                f"{stats['raised']}/{failures} at step {step}",
                lost_index=lost_index, axis=axis)
        return real(inputs, labels, rng=rng)

    eng.train_batch = patched
    try:
        yield stats
    finally:
        if getattr(eng, "train_batch", None) is patched:
            eng.train_batch = real


@contextlib.contextmanager
def flip_gradient_bits(trainer, step: int):
    """Silent data corruption INSIDE the traced step: at global step
    ``step`` the first gradient leaf's element [0,...] has its fp32
    exponent field forced to all-ones (→ ±inf/NaN — the worst-case
    undetected bit-flip).  Gated on the traced ``step_no`` operand, so
    the injection costs zero recompiles; the engine's in-graph
    StepGuard must where-select the poisoned update away and report
    ``last_skipped``.  The step program is rebuilt on entry AND exit so
    no artifact or live executable retains the poison; the trainer's
    AOT warm path is suspended for the duration (a loaded artifact has
    no hook woven in — and a poisoned program must never be exported)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    eng = trainer.engine
    aot_dir = trainer.aot_dir
    trainer.aot_dir = None
    stats = {"step": step}

    def hook(grads, step_no):
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        x = leaves[0].astype(jnp.float32)
        idx = (0,) * x.ndim
        bits = lax.bitcast_convert_type(x, jnp.uint32)
        poisoned_bits = bits.at[idx].set(
            bits[idx] | jnp.uint32(0x7F800000))
        poisoned = lax.bitcast_convert_type(
            poisoned_bits, jnp.float32).astype(leaves[0].dtype)
        leaves = [jnp.where(step_no == step + 1, poisoned, leaves[0])
                  ] + leaves[1:]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    eng.grad_hook = hook
    eng._step_fn = None     # retrace with the hook woven in
    try:
        yield stats
    finally:
        trainer.aot_dir = aot_dir
        if trainer.engine is eng and eng.grad_hook is hook:
            eng.grad_hook = None
            eng._step_fn = None
