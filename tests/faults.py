"""Fault-injection harness for checkpoint durability tests (ISSUE 2).

Simulates the two ways a preemption can interrupt ``framework.io.save``:

* :func:`crash_mid_write` — the process dies while the checkpoint's temp
  file is being written: only the first ``at_bytes`` bytes ever reach the
  file and ``os.replace`` never runs (a truncated ``.tmp-*`` straggler is
  all that's left).
* :func:`fail_replace` — the write completes but the atomic rename
  itself fails/never happens (kill between fsync and rename, or an
  ENOSPC/EIO at publish time).

Both patch the narrow seams ``framework.io`` exposes for exactly this
purpose (``_write_bytes`` / ``_replace``) rather than global ``os``
state, so the rest of the test process keeps working.  ``corrupt_file``
models post-crash bit-rot on an already-published checkpoint.
"""

from __future__ import annotations

import contextlib
import os

from paddle_tpu.framework import io as fio

__all__ = ["SimulatedCrash", "crash_mid_write", "fail_replace",
           "corrupt_file", "truncate_file"]


class SimulatedCrash(BaseException):
    """Stands in for the process dying mid-write.  Derives from
    BaseException so production code's ``except Exception`` recovery
    paths cannot accidentally swallow the injected kill."""


@contextlib.contextmanager
def crash_mid_write(monkeypatch, at_bytes: int = 64, crashes: int = 1):
    """Kill the checkpoint writer after ``at_bytes`` bytes of the temp
    file for the next ``crashes`` saves; later saves succeed.  Yields a
    stats dict (``stats['crashed']`` = number of injected kills)."""
    stats = {"crashed": 0}
    real = fio._write_bytes

    def patched(f, data):
        if stats["crashed"] < crashes:
            stats["crashed"] += 1
            real(f, data[:at_bytes])
            f.flush()
            raise SimulatedCrash(
                f"simulated kill after {at_bytes} bytes of "
                f"{len(data)}-byte checkpoint write")
        real(f, data)

    monkeypatch.setattr(fio, "_write_bytes", patched)
    try:
        yield stats
    finally:
        monkeypatch.setattr(fio, "_write_bytes", real)


@contextlib.contextmanager
def fail_replace(monkeypatch, failures: int = 1):
    """Make the atomic publish rename fail for the next ``failures``
    saves (completed temp file, no visible checkpoint)."""
    stats = {"failed": 0}
    real = fio._replace

    def patched(tmp, path):
        if stats["failed"] < failures:
            stats["failed"] += 1
            raise SimulatedCrash(
                f"simulated crash before rename {tmp!r} -> {path!r}")
        real(tmp, path)

    monkeypatch.setattr(fio, "_replace", patched)
    try:
        yield stats
    finally:
        monkeypatch.setattr(fio, "_replace", real)


def corrupt_file(path: str, offset: int = 96, garbage: bytes = b"\xde\xad"
                 ) -> None:
    """Flip bytes inside an already-published file (bit-rot model)."""
    size = os.path.getsize(path)
    offset = min(offset, max(size - len(garbage), 0))
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(garbage)


def truncate_file(path: str, keep_bytes: int) -> None:
    """Cut a published file short (torn write / partial flush model)."""
    with open(path, "r+b") as f:
        f.truncate(keep_bytes)
