"""tracelint unit tests: per-rule fixtures, suppressions, CLI modes.

Fixture files under tests/tracelint_fixtures/ are ANALYZED, never
imported — each rule has a positive (must fire) and negative (must stay
quiet) snippet.  CPU-only, no jax execution anywhere.
"""

import json
import os
import subprocess
import sys

import pytest

from paddle_tpu.analysis import core
from paddle_tpu.analysis import baseline as baseline_mod
from paddle_tpu.analysis.cli import main as cli_main

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "tracelint_fixtures")
REPO = os.path.dirname(HERE)

RULE_IDS = ("TL001", "TL002", "TL003", "TL004", "TL005", "TL006",
            "TL007", "TL008", "TL009")


def run_fixture(name, select=None):
    return core.run([os.path.join(FIXTURES, name)], select=select)


def rules_hit(findings):
    return {f.rule for f in findings}


# -- rule registry ------------------------------------------------------

def test_all_rules_registered():
    ids = [r.id for r in core.all_rules()]
    assert ids == sorted(ids)
    for rid in RULE_IDS:
        assert rid in ids


def test_rules_carry_metadata():
    for rule in core.all_rules():
        assert rule.severity in core.SEVERITIES
        assert rule.doc and rule.hint and rule.name


# -- per-rule positive/negative fixtures --------------------------------

@pytest.mark.parametrize("rid", RULE_IDS)
def test_rule_fires_on_positive_fixture(rid):
    findings = run_fixture(f"{rid.lower()}_pos.py", select={rid})
    assert findings, f"{rid} found nothing in its positive fixture"
    assert rules_hit(findings) == {rid}


@pytest.mark.parametrize("rid", RULE_IDS)
def test_rule_quiet_on_negative_fixture(rid):
    findings = run_fixture(f"{rid.lower()}_neg.py", select={rid})
    assert not findings, [f.format() for f in findings]


def test_tl001_counts_each_sync_site():
    findings = run_fixture("tl001_pos.py", select={"TL001"})
    assert len(findings) >= 5           # float/item/asarray/device_get +
    assert any("tolist" in f.message for f in findings)   # transitive


def test_tl004_flags_loop_without_rebind():
    findings = run_fixture("tl004_pos.py", select={"TL004"})
    lines = {f.line for f in findings}
    assert len(findings) >= 3
    # the loop body call site itself is the iteration-2 read
    assert any("params" in f.message for f in findings)
    assert any("state" in f.message for f in findings)
    assert all(f.severity == "error" for f in findings)
    assert lines


def test_tl005_names_the_drifted_axis():
    findings = run_fixture("tl005_pos.py", select={"TL005"})
    msgs = " ".join(f.message for f in findings)
    assert "'modelp'" in msgs and "'tensor'" in msgs
    assert len(findings) == 2


def test_tl009_names_the_drifted_spec_axis():
    findings = run_fixture("tl009_pos.py", select={"TL009"})
    msgs = " ".join(f.message for f in findings)
    assert "'modelp'" in msgs and "'tensor'" in msgs
    assert len(findings) == 2           # the declared P("dp") passes
    assert {"in_specs" in f.message or "out_specs" in f.message
            for f in findings} == {True}


# -- suppressions -------------------------------------------------------

def test_inline_suppression_silences_one_site_only():
    findings = run_fixture("suppressed.py", select={"TL006"})
    assert len(findings) == 1
    assert "unjustified" in "".join(
        open(os.path.join(FIXTURES, "suppressed.py")).readlines()
        [findings[0].line - 4:findings[0].line])


def test_file_level_suppression():
    findings = run_fixture("suppressed.py", select={"TL007"})
    assert findings == []


# -- engine plumbing ----------------------------------------------------

def test_collect_files_skips_pycache_and_dedups():
    files = core.collect_files([FIXTURES, os.path.join(FIXTURES,
                                                       "tl001_pos.py")])
    assert all("__pycache__" not in f for f in files)
    assert len(files) == len(set(map(os.path.abspath, files)))


def test_load_module_survives_syntax_error(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    assert core.load_module(str(bad)) is None
    assert core.run([str(bad)]) == []


def test_findings_sorted_and_json_roundtrip():
    findings = run_fixture("tl006_pos.py")
    assert findings == sorted(findings, key=lambda f: f.sort_key)
    for f in findings:
        d = f.to_json()
        assert {"rule", "severity", "path", "line", "col", "message",
                "hint"} <= set(d)


# -- baseline render/parse/compare --------------------------------------

def test_baseline_roundtrip_and_compare():
    findings = run_fixture("tl006_pos.py") + run_fixture("tl007_pos.py")
    md = baseline_mod.render_md(findings)
    parsed = baseline_mod.parse_md(md)
    assert parsed == baseline_mod.counts(findings)
    # identical findings: no regression
    assert baseline_mod.compare(baseline_mod.counts(findings),
                                parsed) == []
    # one extra finding in a known file: regression
    grown = dict(parsed)
    key = next(iter(grown))
    grown[key] += 1
    assert baseline_mod.compare(grown, parsed)
    # a brand-new (rule, file) pair: regression
    fresh = dict(parsed)
    fresh[("TL001", "somewhere/new.py")] = 1
    assert baseline_mod.compare(fresh, parsed)


def test_baseline_parse_rejects_blockless_text():
    with pytest.raises(ValueError):
        baseline_mod.parse_md("# not a baseline\n")


# -- CLI ----------------------------------------------------------------

def test_cli_json_schema(capsys):
    rc = cli_main([os.path.join(FIXTURES, "tl006_pos.py"), "--json",
                   "--no-baseline"])
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == 1
    assert rc == 1                       # findings, no baseline
    assert payload["counts"].get("TL006", 0) >= 3
    for f in payload["findings"]:
        assert {"rule", "severity", "path", "line", "col",
                "message", "hint"} <= set(f)
    assert payload["above_baseline"] == []


def test_cli_select_filters_rules(capsys):
    rc = cli_main([os.path.join(FIXTURES, "tl007_pos.py"),
                   "--select", "TL006", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 0 and "0 findings" in out


def test_cli_clean_file_exits_zero(capsys):
    rc = cli_main([os.path.join(FIXTURES, "tl006_neg.py"),
                   "--select", "TL006", "--no-baseline"])
    assert rc == 0


def test_cli_baseline_gates_exit_code(tmp_path, capsys):
    target = os.path.join(FIXTURES, "tl006_pos.py")
    findings = core.run([target])
    base = tmp_path / "TRACELINT.md"
    base.write_text(baseline_mod.render_md(findings))
    # findings == baseline: ratchet passes
    assert cli_main([target, "--baseline", str(base)]) == 0
    capsys.readouterr()
    # empty baseline: everything is above it
    empty = tmp_path / "EMPTY.md"
    empty.write_text(baseline_mod.render_md([]))
    rc = cli_main([target, "--baseline", str(empty)])
    out = capsys.readouterr().out
    assert rc == 2 and "ABOVE BASELINE" in out


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULE_IDS:
        assert rid in out


def test_cli_diff_mode_runs_and_emits_json():
    # diff vs HEAD exercises the git plumbing end-to-end; the changed
    # set varies with workspace state, so only the contract is checked
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--diff", "HEAD",
         "--json", "--no-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode in (0, 1)
    payload = json.loads(proc.stdout)
    assert payload["version"] == 1


def test_cli_diff_bad_ref_fails_cleanly():
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--diff",
         "no-such-ref-xyz"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode not in (0, None)
    assert "git diff" in proc.stderr


# -- notimpl backend fold-in --------------------------------------------

def test_notimpl_classifier_matches_rule():
    from paddle_tpu.analysis.notimpl import classify_module
    mod = core.load_module(os.path.join(FIXTURES, "tl008_neg.py"))
    kinds = sorted(s["kind"] for s in classify_module(mod))
    assert kinds == ["abstract", "guard", "guard"]


def test_notimpl_shim_cli_ratchet_green():
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "notimpl_inventory.py"),
         "--check", "0"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stubs=0" in proc.stdout
