"""KL002 negative: arities, coordinate counts and program_id axes all
agree with the grid."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    j = pl.program_id(1)
    o_ref[:] = x_ref[:] * j


def good(x):
    return pl.pallas_call(
        _kernel,
        grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((32, 512), jnp.float32),
    )(x)
