"""KL004 negative: fp32-accumulated dot, fp32 scratch carry, and a
bf16 scratch that is only STORED to (no reduction) is fine."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, o_ref, acc, stage):
    part = jax.lax.dot_general(x_ref[:], w_ref[:],
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    acc[:] += part
    stage[:] = x_ref[:]              # plain store, not a reduction
    o_ref[:] = acc[:].astype(o_ref.dtype)


def good_accum(x, w):
    return pl.pallas_call(
        _kernel,
        grid=(1, 4),
        in_specs=[pl.BlockSpec((128, 128), lambda i, j: (0, j)),
                  pl.BlockSpec((128, 128), lambda i, j: (j, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i, j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((128, 128), jnp.float32),
                        pltpu.VMEM((128, 128), jnp.bfloat16)],
    )(x, w)
