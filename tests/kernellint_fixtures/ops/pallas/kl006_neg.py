"""KL006 negative: the public entry point is referenced from tests/
(``decode_attention`` has interpret-tier coverage), and non-function
``__all__`` names (re-exported constants) are out of scope."""

SOME_EXPORTED_CONSTANT = 7

__all__ = ["decode_attention", "SOME_EXPORTED_CONSTANT"]


def decode_attention(q, k_cache, v_cache, lengths):
    return q
