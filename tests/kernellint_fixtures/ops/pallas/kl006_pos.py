"""KL006 positive: a public kernel entry point no tests/ module
references (the fixture path contains ops/pallas/ so the rule is in
scope; *_fixtures trees are excluded from the coverage corpus)."""

__all__ = ["totally_unreferenced_kernel_entry"]


def totally_unreferenced_kernel_entry(x):
    return x
