"""KL005 negative: the candidates tuple is registered — pick at
warmup, lookup at trace time, one key string."""
import jax

_BLOCK_CANDIDATES = ((128, 128), (256, 128), (256, 256))
DEFAULT_BLOCK = (128, 128)


def tuned_block(x, args):
    from paddle_tpu.ops.pallas.autotune import lookup, pick
    key = (x.shape, str(x.dtype))
    if isinstance(x, jax.core.Tracer):
        return lookup("fixture_kernel", key, DEFAULT_BLOCK)
    return pick("fixture_kernel", key, _BLOCK_CANDIDATES,
                lambda c: (lambda *a: None), args, DEFAULT_BLOCK)
