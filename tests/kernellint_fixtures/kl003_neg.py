"""KL003 negative: the same ceil-divided grid, but the kernel masks
the overhang with an iota position stream (the linear_ce pattern);
and a non-cdiv grid needs no mask at all."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _masked_kernel(x_ref, o_ref, acc, *, V, chunk):
    j = pl.program_id(1)
    x = x_ref[:]
    cols = j * chunk + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    x = jnp.where(cols < V, x, 0.0)
    acc[:] += jnp.sum(x, axis=1, keepdims=True)
    o_ref[:] = acc[:]


def masked_sum(x, chunk):
    import functools
    R, V = x.shape
    nv = pl.cdiv(V, chunk)
    return pl.pallas_call(
        functools.partial(_masked_kernel, V=V, chunk=chunk),
        grid=(1, nv),
        in_specs=[pl.BlockSpec((R, chunk), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((R, 1), lambda i, j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((R, 1), jnp.float32)],
    )(x)


def _copy_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def dividing_grid(x):
    R, V = x.shape
    return pl.pallas_call(
        _copy_kernel,
        grid=(R // 8,),
        in_specs=[pl.BlockSpec((8, V), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, V), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, V), x.dtype),
    )(x)
