"""KL005 positive: a candidates tuple with no autotune registration —
the knob can never leave its default."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_CANDIDATES = ((128, 128), (256, 128), (256, 256))
DEFAULT_BLOCK = 128


def _kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def run(x):
    b = DEFAULT_BLOCK               # the sweep above is dead code
    return pl.pallas_call(
        _kernel,
        grid=(x.shape[0] // b,),
        in_specs=[pl.BlockSpec((b, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((b, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
