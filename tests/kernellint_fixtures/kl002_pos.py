"""KL002 positive: index-map arity vs grid rank, index-map coordinate
count vs block rank, and an out-of-range program_id."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    k = pl.program_id(2)          # grid is rank 2
    o_ref[:] = x_ref[:] * k


def bad(x):
    return pl.pallas_call(
        _kernel,
        grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],       # arity 1
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0, 0)),  # 3 coords
        out_shape=jax.ShapeDtypeStruct((32, 512), jnp.float32),
    )(x)
