"""KL004 positive: a kernel dot with no preferred_element_type, and a
reduction carried in a bf16 VMEM scratch."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, o_ref, acc):
    part = jax.lax.dot_general(x_ref[:], w_ref[:],
                               (((1,), (0,)), ((), ())))   # input dtype!
    acc[:] += part.astype(acc.dtype)                       # bf16 carry
    o_ref[:] = acc[:]


def bad_accum(x, w):
    return pl.pallas_call(
        _kernel,
        grid=(1, 4),
        in_specs=[pl.BlockSpec((128, 128), lambda i, j: (0, j)),
                  pl.BlockSpec((128, 128), lambda i, j: (j, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i, j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((128, 128), jnp.bfloat16)],
    )(x, w)
