"""KL001 negative: small constant blocks fit easily, and
runtime-dependent dims must never be guessed into a finding."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BM = 256


def _kernel(x_ref, o_ref, acc):
    o_ref[:] = x_ref[:]


def small(x):
    return pl.pallas_call(
        _kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((BM, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((BM, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((4 * BM, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((BM, 128), jnp.float32)],
    )(x)


def runtime_shaped(x):
    # H is runtime-dependent: provable lower bound stays tiny even if
    # the true working set could be huge — no finding, by design
    R, H = x.shape
    return pl.pallas_call(
        _kernel,
        grid=(R // 8,),
        in_specs=[pl.BlockSpec((8, H), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, H), x.dtype),
        scratch_shapes=[pltpu.VMEM((8, H), jnp.float32)],
    )(x)
