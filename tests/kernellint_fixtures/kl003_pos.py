"""KL003 positive: ceil-divided grid, kernel folds the tile with no
mask — the overhang rows silently enter the sum."""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, o_ref, acc):
    acc[:] += jnp.sum(x_ref[:], axis=1, keepdims=True)
    o_ref[:] = acc[:]


def unmasked_sum(x, chunk):
    R, V = x.shape
    nv = pl.cdiv(V, chunk)
    return pl.pallas_call(
        _kernel,
        grid=(1, nv),
        in_specs=[pl.BlockSpec((R, chunk), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((R, 1), lambda i, j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((R, 1), jnp.float32)],
    )(x)
