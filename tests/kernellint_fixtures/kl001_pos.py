"""KL001 positive: constant-folded working set provably past the
budget — 4 x (4096, 4096) fp32 scratch is 256 MB against a 12 MB
budget, and the blocks are constant too."""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BM, BN = 4096, 4096


def _kernel(x_ref, o_ref, a_scr, b_scr, c_scr, d_scr):
    o_ref[:] = x_ref[:]


def oversized(x):
    return pl.pallas_call(
        _kernel,
        grid=(4, 4),
        in_specs=[pl.BlockSpec((BM, BN), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((BM, BN), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((4 * BM, 4 * BN), jnp.float32),
        scratch_shapes=[pltpu.VMEM((BM, BN), jnp.float32)] * 4,
    )(x)
