"""Enforce-style error layer (VERDICT r3 item 9; reference
common/enforce.h EnforceNotMet): dispatch failures carry op name, mode,
and input shapes/dtypes; the NaN checker names the producing op."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.enforce import EnforceNotMet


def ten(x):
    return pt.to_tensor(np.asarray(x, "float32"))


class TestEnforceNotMet:
    def test_shape_mismatch_carries_context(self):
        a = ten(np.zeros((2, 3)))
        b = ten(np.zeros((4, 5)))
        with pytest.raises(EnforceNotMet) as ei:
            pt.matmul(a, b)
        msg = str(ei.value)
        assert "matmul" in msg
        assert "eager mode" in msg
        assert "(2, 3)" in msg and "(4, 5)" in msg
        assert "float32" in msg
        assert ei.value.op_name == "matmul"

    def test_traced_mode_tagged(self):
        import paddle_tpu.jit as jit

        @jit.to_static(full_graph=True)
        def f(a, b):
            return pt.matmul(a, b)

        with pytest.raises(EnforceNotMet) as ei:
            f(ten(np.zeros((2, 3))), ten(np.zeros((4, 5))))
        assert "traced mode" in str(ei.value)

    def test_cause_chained(self):
        with pytest.raises(EnforceNotMet) as ei:
            pt.matmul(ten(np.zeros((2, 3))), ten(np.zeros((4, 5))))
        assert ei.value.__cause__ is not None
        assert ei.value.cause_type == type(ei.value.__cause__).__name__

    def test_no_double_wrap(self):
        # composite ops dispatch through nested run_op calls; the message
        # must name ONE op, not a matryoshka of EnforceNotMet
        with pytest.raises(EnforceNotMet) as ei:
            pt.matmul(ten(np.zeros((2, 3))), ten(np.zeros((4, 5))))
        assert str(ei.value).count("PreconditionNotMet") == 1


class TestTypePreservation:
    def test_original_exception_type_still_catchable(self):
        # the wrapper subclasses the cause's type: existing
        # `except TypeError` / ValueError call sites keep working
        a = ten(np.zeros((2, 3)))
        b = ten(np.zeros((4, 5)))
        try:
            pt.matmul(a, b)
            assert False, "should have raised"
        except EnforceNotMet as e:
            assert isinstance(e, type(e.__cause__))


class TestIndexContract:
    def test_float_tensor_index_raises(self):
        with pytest.raises(TypeError):
            range(pt.to_tensor(np.float32(2.9)))

    def test_int_tensor_index_works(self):
        assert list(range(pt.to_tensor(np.int32(3)))) == [0, 1, 2]


class TestNaNCheckerNamesOp:
    def test_nan_reports_op_and_shape(self):
        from paddle_tpu.core.flags import FLAGS
        old = FLAGS.check_nan_inf
        FLAGS.check_nan_inf = True
        try:
            with pytest.raises(FloatingPointError) as ei:
                pt.log(ten([-1.0, 2.0]))
            msg = str(ei.value)
            assert "log" in msg
            assert "non-finite" in msg
            assert "(2,)" in msg
        finally:
            FLAGS.check_nan_inf = old
