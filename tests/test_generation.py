"""Decode path: KV-cache generation must match the eager full-forward
argmax (reference MMHA kernel semantics + model-zoo generate()), and
jit.save/jit.load must round-trip a layer without its Python class
(reference jit/translated_layer.py role)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.models.generation import (build_gpt_decoder,
                                          build_llama_decoder,
                                          gpt_generate, llama_generate,
                                          sample_logits)

rng = np.random.default_rng(0)


def _gpt_setup():
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
    from paddle_tpu import parallel as dist
    from paddle_tpu.parallel.topology import HybridTopology, set_topology
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64)
    topo = dist.init_topology()
    _, init_fn = build_gpt_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    return cfg, params


def _gpt_full_logits(cfg, params, ids):
    """Reference: full (non-cached) forward via the decoder's prefill."""
    prefill, _ = build_gpt_decoder(cfg, ids.shape[1], use_pallas=False)
    _, logits = prefill(params, jnp.asarray(ids))
    return logits


def test_gpt_decode_step_matches_full_forward():
    """Cached decode logits at position t == full forward logits of the
    prefix of length t+1."""
    cfg, params = _gpt_setup()
    ids = rng.integers(0, cfg.vocab_size, (2, 12)).astype(np.int32)
    prefill, step = build_gpt_decoder(cfg, 16, use_pallas=False)
    cache, logits = prefill(params, jnp.asarray(ids[:, :8]))
    np.testing.assert_allclose(
        np.asarray(logits),
        np.asarray(_gpt_full_logits(cfg, params, ids[:, :8])),
        rtol=2e-4, atol=2e-4)
    # feed the true next tokens, compare each cached step vs full forward
    for t in range(8, 12):
        cache, logits = step(params, cache, jnp.asarray(ids[:, t]), t)
        exp = _gpt_full_logits(cfg, params, ids[:, :t + 1])
        np.testing.assert_allclose(np.asarray(logits), np.asarray(exp),
                                   rtol=2e-3, atol=2e-3)


def test_gpt_greedy_generate_matches_no_cache():
    """Greedy rollout with the KV cache == greedy rollout recomputing the
    full prefix each step."""
    cfg, params = _gpt_setup()
    ids = rng.integers(0, cfg.vocab_size, (2, 6)).astype(np.int32)
    out = gpt_generate(params, cfg, ids, max_new_tokens=6, temperature=0.0,
                       use_pallas=False)
    assert out.shape == (2, 12)
    # no-cache reference rollout
    cur = jnp.asarray(ids)
    for _ in range(6):
        logits = _gpt_full_logits(cfg, params, cur)
        nxt = jnp.argmax(logits, -1).astype(cur.dtype)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_llama_greedy_generate_matches_no_cache():
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step
    from paddle_tpu import parallel as dist
    from paddle_tpu.parallel.topology import HybridTopology, set_topology
    cfg = llama_tiny()
    topo = dist.init_topology()
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())

    ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
    out = llama_generate(params, cfg, ids, max_new_tokens=5,
                         temperature=0.0, use_pallas=False)
    assert out.shape == (2, 10)

    cur = jnp.asarray(ids)
    for t in range(5):
        prefill, _ = build_llama_decoder(cfg, cur.shape[1],
                                         use_pallas=False)
        _, logits = prefill(params, cur)
        nxt = jnp.argmax(logits, -1).astype(cur.dtype)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_gpt_moe_greedy_generate_matches_no_cache():
    """GPT-MoE decode through the grouped-GEMM (ragged_dot) serving FFN:
    KV-cache rollout == full-prefix recompute rollout."""
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
    from paddle_tpu import parallel as dist
    from paddle_tpu.parallel.topology import HybridTopology, set_topology
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    moe_num_experts=4)
    topo = dist.init_topology()
    _, init_fn = build_gpt_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    ids = rng.integers(0, cfg.vocab_size, (2, 6)).astype(np.int32)
    out = gpt_generate(params, cfg, ids, max_new_tokens=6, temperature=0.0,
                       use_pallas=False)
    cur = jnp.asarray(ids)
    for _ in range(6):
        logits = _gpt_full_logits(cfg, params, cur)
        nxt = jnp.argmax(logits, -1).astype(cur.dtype)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_llama_moe_greedy_generate_matches_no_cache():
    """Mixtral-style MoE decode: the KV-cache prefill+step loop must
    reproduce repeated full-forward greedy decoding exactly (capacity is
    overridden to the token count at inference, so routing never drops —
    a drop would break this equality)."""
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step
    from paddle_tpu import parallel as dist
    from paddle_tpu.parallel.topology import HybridTopology, set_topology
    cfg = llama_tiny(moe_num_experts=4)
    topo = dist.init_topology()
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())

    ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
    out = llama_generate(params, cfg, ids, max_new_tokens=5,
                         temperature=0.0, use_pallas=False)
    assert out.shape == (2, 10)

    cur = jnp.asarray(ids)
    for t in range(5):
        prefill, _ = build_llama_decoder(cfg, cur.shape[1],
                                         use_pallas=False)
        _, logits = prefill(params, cur)
        nxt = jnp.argmax(logits, -1).astype(cur.dtype)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_llama_moe_quant_decode_guard():
    from paddle_tpu.models.generation import build_llama_decoder
    from paddle_tpu.models.llama import llama_tiny
    with pytest.raises(NotImplementedError):
        build_llama_decoder(llama_tiny(moe_num_experts=4), 16,
                            quant="weight_only_int8")


def test_decode_attention_pallas_matches_ref():
    from paddle_tpu.core.flags import FLAGS, set_flags
    from paddle_tpu.ops.pallas.decode_attention import (
        decode_attention, decode_attention_ref)
    B, Hq, Hkv, D, T = 2, 8, 2, 64, 300
    q = rng.normal(size=(B, Hq, D)).astype(np.float32)
    kc = rng.normal(size=(B, T, Hkv, D)).astype(np.float32)
    vc = rng.normal(size=(B, T, Hkv, D)).astype(np.float32)
    lens = np.array([211, 97], np.int32)
    old = FLAGS.pallas_interpret
    try:
        set_flags({"pallas_interpret": True})
        got = decode_attention(q, kc, vc, lens, use_pallas=True)
    finally:
        set_flags({"pallas_interpret": old})
    exp = decode_attention_ref(q, kc, vc, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-4, atol=2e-4)


def test_masked_multihead_attention_api():
    from paddle_tpu.incubate.nn import functional as IF
    B, H, D, T = 2, 4, 16, 32
    x = rng.normal(size=(B, 3 * H * D)).astype(np.float32)
    cache = np.zeros((2, B, H, T, D), np.float32)
    cache[:, :, :, :5] = rng.normal(size=(2, B, H, 5, D))
    lens = np.full((B,), 5, np.int32)
    out, new_cache = IF.masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(lens))
    assert tuple(out.shape) == (B, H * D)
    assert tuple(new_cache.shape) == (2, B, H, T, D)
    assert np.isfinite(np.asarray(out)).all()
    # the step's k (qkv order: q, k, v) must land at row position 5
    k_step = np.asarray(x).reshape(B, 3, H, D)[:, 1]
    np.testing.assert_allclose(np.asarray(new_cache)[0][:, :, 5], k_step,
                               rtol=1e-6)


def test_sample_logits_top_k_top_p():
    logits = jnp.asarray(rng.normal(size=(4, 50)).astype(np.float32))
    g = sample_logits(logits, jax.random.key(0), temperature=0.0)
    np.testing.assert_array_equal(np.asarray(g),
                                  np.asarray(jnp.argmax(logits, -1)))
    for kw in (dict(top_k=5), dict(top_p=0.9), dict(top_k=8, top_p=0.5)):
        s = sample_logits(logits, jax.random.key(1), temperature=1.0, **kw)
        assert s.shape == (4,)
        if "top_k" in kw:   # sampled ids must be within the top-k set
            topk = np.argsort(np.asarray(logits), -1)[:, -kw["top_k"]:]
            assert all(s_i in row for s_i, row in zip(np.asarray(s), topk))


def test_jit_save_load_roundtrip(tmp_path):
    """jit.save serializes STABLEHLO + params; jit.load runs without the
    original class (reference TranslatedLayer role)."""
    from paddle_tpu import jit

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    x = pt.to_tensor(rng.normal(size=(3, 8)).astype(np.float32))
    expect = np.asarray(net(x))

    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[jit.InputSpec((3, 8), "float32")])
    assert os.path.exists(path + ".pdmodel")
    assert os.path.exists(path + ".pdparams")

    loaded = jit.load(path)
    got = np.asarray(loaded(x))
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)


def test_jit_save_load_dynamic_batch(tmp_path):
    """InputSpec with None dims (paddle dynamic-batch idiom) exports with
    symbolic shapes and serves any batch size."""
    from paddle_tpu import jit

    net = nn.Sequential(nn.Linear(8, 4))
    net.eval()
    path = str(tmp_path / "dyn")
    jit.save(net, path, input_spec=[jit.InputSpec((None, 8), "float32")])
    loaded = jit.load(path)
    for b in (1, 5):
        x = pt.to_tensor(rng.normal(size=(b, 8)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(loaded(x)),
                                   np.asarray(net(x)), rtol=1e-5, atol=1e-5)

def test_mmha_src_mask_matches_reference_naive():
    """src_mask path == reference test_masked_multihead_attention_op.py
    mmha_naive: scores + src_mask before softmax over the cache."""
    from paddle_tpu.incubate.nn import functional as IF
    B, H, D, T = 2, 3, 8, 12
    L = 6                                       # filled cache length
    x = rng.normal(size=(B, 3 * H * D)).astype(np.float32)
    cache = np.zeros((2, B, H, T, D), np.float32)
    cache[:, :, :, :L] = rng.normal(size=(2, B, H, L, D))
    lens = np.full((B,), L, np.int32)
    mask = rng.normal(size=(B, 1, 1, L + 1)).astype(np.float32)

    out, _ = IF.masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        src_mask=pt.to_tensor(mask),
        sequence_lengths=pt.to_tensor(lens))

    # naive: concat step k/v after the filled cache, full softmax
    qkv = x.reshape(B, 3, H, D)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    kc = np.concatenate([cache[0][:, :, :L], k[:, :, None]], axis=2)
    vc = np.concatenate([cache[1][:, :, :L], v[:, :, None]], axis=2)
    scores = np.einsum("bhd,bhtd->bht", q, kc) * (D ** -0.5)
    scores = scores + mask[:, 0]
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bht,bhtd->bhd", p, vc).reshape(B, H * D)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("neox", [False, True])
def test_mmha_rotary(neox):
    """In-op rotary (reference mmha kernel :247-): cos/sin planes applied
    to q and k before the cache scatter; verified against a hand-rolled
    rotation + the no-rotary op on pre-rotated inputs."""
    from paddle_tpu.incubate.nn import functional as IF
    B, H, D, T = 2, 2, 8, 10
    L = 3
    x = rng.normal(size=(B, 3 * H * D)).astype(np.float32)
    cache = np.zeros((2, B, H, T, D), np.float32)
    cache[:, :, :, :L] = rng.normal(size=(2, B, H, L, D))
    lens = np.full((B,), L, np.int32)
    theta = rng.normal(size=(B, D)).astype(np.float32)
    rot = np.stack([np.cos(theta), np.sin(theta)])    # [2, B, D]

    out, nc = IF.masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(lens),
        rotary_tensor=pt.to_tensor(rot.reshape(2, B, 1, 1, D)),
        use_neox_rotary_style=neox, rotary_emb_dims=1)

    # rotate q/k by hand, run the op WITHOUT rotary on the edited qkv
    qkv = x.reshape(B, 3, H, D).copy()
    cos, sin = rot[0][:, None], rot[1][:, None]       # [B, 1, D]
    for i in (0, 1):
        t = qkv[:, i]
        if not neox:
            xs, ys = t[..., 0::2], t[..., 1::2]
            x2 = xs * cos[..., 0::2] - ys * sin[..., 0::2]
            y2 = ys * cos[..., 1::2] + xs * sin[..., 1::2]
            qkv[:, i] = np.stack([x2, y2], -1).reshape(B, H, D)
        else:
            h = D // 2
            xs, ys = t[..., :h], t[..., h:]
            x2 = xs * cos[..., :h] - ys * sin[..., :h]
            y2 = ys * cos[..., h:] + xs * sin[..., h:]
            qkv[:, i] = np.concatenate([x2, y2], -1)
    out2, nc2 = IF.masked_multihead_attention(
        pt.to_tensor(qkv.reshape(B, -1)), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(lens))
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(nc), np.asarray(nc2),
                               rtol=2e-4, atol=2e-4)


def test_mmha_rotary_full_table_gathers_at_position():
    """A reference-shaped full rotary table [2, B, S, 1, D] is gathered at
    each row's current length — same result as pre-gathering by hand."""
    from paddle_tpu.incubate.nn import functional as IF
    B, H, D, T, S = 2, 2, 8, 10, 6
    L = np.array([3, 5], np.int32)
    x = rng.normal(size=(B, 3 * H * D)).astype(np.float32)
    cache = np.zeros((2, B, H, T, D), np.float32)
    cache[:, :, :, :5] = rng.normal(size=(2, B, H, 5, D))
    theta = rng.normal(size=(B, S, D)).astype(np.float32)
    table = np.stack([np.cos(theta), np.sin(theta)])  # [2, B, S, D]

    out_full, _ = IF.masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(L),
        rotary_tensor=pt.to_tensor(table.reshape(2, B, S, 1, D)),
        rotary_emb_dims=1)
    pre = table[:, np.arange(B), L]                   # [2, B, D]
    out_pre, _ = IF.masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(L),
        rotary_tensor=pt.to_tensor(pre.reshape(2, B, 1, 1, D)),
        rotary_emb_dims=1)
    np.testing.assert_allclose(np.asarray(out_full), np.asarray(out_pre),
                               rtol=1e-5, atol=1e-5)


class TestSpeculativeDecoding:
    def _setup(self, draft_same=False):
        from paddle_tpu.models.llama import llama_tiny, \
            build_llama_train_step
        from paddle_tpu import parallel as dist
        from paddle_tpu.parallel.topology import HybridTopology, \
            set_topology
        cfg = llama_tiny()
        topo = dist.init_topology()
        _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
        params = init_fn(0)["params"]
        if draft_same:
            dcfg, dparams = cfg, params
        else:
            dcfg = llama_tiny(hidden_size=32, intermediate_size=64,
                              num_heads=2, num_kv_heads=2, num_layers=2)
            _, dinit = build_llama_train_step(dcfg, topo,
                                              num_microbatches=1)
            dparams = dinit(1)["params"]
        set_topology(HybridTopology())
        return cfg, params, dcfg, dparams

    def test_speculative_exact_match_random_draft(self):
        """Greedy speculative decode == plain greedy decode regardless of
        draft quality (the acceptance rule guarantees it)."""
        from paddle_tpu.models.generation import (llama_generate,
                                                  llama_speculative_generate)
        cfg, params, dcfg, dparams = self._setup(draft_same=False)
        ids = rng.integers(0, cfg.vocab_size, (1, 6)).astype(np.int32)
        want = np.asarray(llama_generate(params, cfg, ids,
                                         max_new_tokens=10,
                                         temperature=0.0,
                                         use_pallas=False))
        got, stats = llama_speculative_generate(
            params, cfg, dparams, dcfg, ids, 10, num_draft=3,
            use_pallas=False)
        np.testing.assert_array_equal(np.asarray(got), want)
        assert stats["rounds"] >= 1

    def test_speculative_perfect_draft_accepts(self):
        """With draft == target every proposal is accepted: far fewer
        verify rounds than tokens."""
        from paddle_tpu.models.generation import (llama_generate,
                                                  llama_speculative_generate)
        cfg, params, dcfg, dparams = self._setup(draft_same=True)
        ids = rng.integers(0, cfg.vocab_size, (1, 6)).astype(np.int32)
        want = np.asarray(llama_generate(params, cfg, ids,
                                         max_new_tokens=12,
                                         temperature=0.0,
                                         use_pallas=False))
        got, stats = llama_speculative_generate(
            params, cfg, dparams, dcfg, ids, 12, num_draft=4,
            use_pallas=False)
        np.testing.assert_array_equal(np.asarray(got), want)
        # random-init logits are near-uniform, so fp differences between
        # the single-token decode path (draft) and the dense chunk verify
        # frequently flip an argmax even with draft == target — the
        # accept RATE is noise on random weights.  The robust claims:
        # some drafts were accepted, so rounds < tokens (speculation
        # saved verify passes), while the output stayed exact.
        assert stats["accepted_drafts"] > 0
        assert stats["rounds"] < 12

    def test_speculative_batched_matches_greedy(self):
        """Batched (B=3) speculative decode: per-row acceptance lengths
        diverge, yet every row equals the plain greedy rollout (VERDICT
        r4 item 6 — per-row cache position vectors)."""
        from paddle_tpu.models.generation import (llama_generate,
                                                  llama_speculative_generate)
        cfg, params, dcfg, dparams = self._setup(draft_same=False)
        ids = rng.integers(0, cfg.vocab_size, (3, 6)).astype(np.int32)
        want = np.asarray(llama_generate(params, cfg, ids,
                                         max_new_tokens=8,
                                         temperature=0.0,
                                         use_pallas=False))
        got, stats = llama_speculative_generate(
            params, cfg, dparams, dcfg, ids, 8, num_draft=3,
            use_pallas=False)
        assert np.asarray(got).shape == (3, 14)
        np.testing.assert_array_equal(np.asarray(got), want)
        assert stats["rounds"] >= 1

    def test_speculative_batched_rows_match_single(self):
        """Row independence: each row of a batched speculative run equals
        the same prompt run alone (frozen finished rows and per-row
        positions must not leak across the batch)."""
        from paddle_tpu.models.generation import llama_speculative_generate
        cfg, params, dcfg, dparams = self._setup(draft_same=True)
        ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
        got, _ = llama_speculative_generate(
            params, cfg, dparams, dcfg, ids, 7, num_draft=3,
            use_pallas=False)
        for b in range(2):
            solo, _ = llama_speculative_generate(
                params, cfg, dparams, dcfg, ids[b:b + 1], 7, num_draft=3,
                use_pallas=False)
            np.testing.assert_array_equal(np.asarray(got)[b],
                                          np.asarray(solo)[0])


def test_gpt_speculative_exact_match():
    """GPT speculative decode == plain GPT greedy decode (random tiny
    draft; learned-position chunk verify)."""
    from paddle_tpu.models.generation import gpt_speculative_generate
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
    from paddle_tpu import parallel as dist
    from paddle_tpu.parallel.topology import HybridTopology, set_topology
    cfg, params = _gpt_setup()
    dcfg = GPTConfig(vocab_size=97, hidden_size=16, num_layers=1,
                     num_heads=2, max_position_embeddings=64)
    topo = dist.init_topology()
    _, dinit = build_gpt_train_step(dcfg, topo, num_microbatches=1)
    dparams = dinit(1)["params"]
    set_topology(HybridTopology())
    ids = rng.integers(0, cfg.vocab_size, (1, 5)).astype(np.int32)
    want = np.asarray(gpt_generate(params, cfg, ids, max_new_tokens=9,
                                   temperature=0.0, use_pallas=False))
    got, stats = gpt_speculative_generate(params, cfg, dparams, dcfg,
                                          ids, 9, num_draft=3,
                                          use_pallas=False)
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["rounds"] >= 1


def test_mmha_beam_cache_offset_gather():
    """Beam path: per past position t, row (bb, beam) reads the cache row
    of beam beam_cache_offset[bb, beam, t] (reference
    masked_multihead_attention_kernel.cu:417-441 k_cache_batch indexing)."""
    from paddle_tpu.incubate.nn import functional as IF
    bbz, bw, H, D, T = 1, 2, 2, 8, 16
    B = bbz * bw
    x = rng.normal(size=(B, 3 * H * D)).astype(np.float32)
    cache = np.zeros((2, B, H, T, D), np.float32)
    cache[:, :, :, :4] = rng.normal(size=(2, B, H, 4, D))
    lens = np.full((B,), 4, np.int32)
    # beam 1 reads all past positions from beam 0's cache
    off = np.zeros((bbz, bw, T), np.int32)
    out, new_cache, off_out = IF.masked_multihead_attention(
        pt.to_tensor(x), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(lens),
        beam_cache_offset=pt.to_tensor(off))
    assert np.asarray(off_out).shape == (bbz, bw, T)
    # manual reference: every row attends to beam-0's past KV + its OWN
    # current step (scattered at position 4 of its own row)
    xr = np.asarray(x).reshape(B, 3, H, D)
    q, k, v = xr[:, 0], xr[:, 1], xr[:, 2]
    kc = cache[0].copy()
    vc = cache[1].copy()
    for b in range(B):
        kc[b, :, 4] = k[b]
        vc[b, :, 4] = v[b]
    want = np.zeros((B, H, D), np.float32)
    for b in range(B):
        src = (b // bw) * bw + off.reshape(B, T)[b]     # [T]
        src[4] = b          # current step always reads the own row
        k_eff = kc[src, :, np.arange(T)]                # [T, H, D]
        v_eff = vc[src, :, np.arange(T)]
        sc = np.einsum("hd,thd->ht", q[b], k_eff) / np.sqrt(D)
        sc[:, 5:] = -np.inf                             # lens+1 positions
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want[b] = np.einsum("ht,thd->hd", p, v_eff)
    np.testing.assert_allclose(np.asarray(out).reshape(B, H, D), want,
                               rtol=2e-4, atol=2e-4)


def test_mmha_quant_in_out():
    """int32 dequant in (qkv_out_scale) + int8 quant out (out_scale with
    shift/smooth), reference MMHALoad<T,int32>/QuantHelperFunc formulas."""
    from paddle_tpu.incubate.nn import functional as IF
    B, H, D, T = 2, 2, 8, 16
    x_int = rng.integers(-1000, 1000, (B, 3 * H * D)).astype(np.int32)
    qkv_scale = rng.uniform(1e-4, 1e-3, (3, H, D)).astype(np.float32)
    cache = np.zeros((2, B, H, T, np.int32(D)), np.float32)
    cache[:, :, :, :3] = rng.normal(size=(2, B, H, 3, D)) * 0.1
    lens = np.full((B,), 3, np.int32)
    shift = rng.normal(size=(H * D,)).astype(np.float32) * 0.01
    smooth = rng.uniform(0.9, 1.1, (H * D,)).astype(np.float32)
    out, _ = IF.masked_multihead_attention(
        pt.to_tensor(x_int), pt.to_tensor(cache),
        sequence_lengths=pt.to_tensor(lens),
        qkv_out_scale=pt.to_tensor(qkv_scale),
        out_shift=pt.to_tensor(shift), out_smooth=pt.to_tensor(smooth),
        out_scale=0.05, quant_round_type=1)
    out = np.asarray(out)
    assert out.dtype == np.int8
    # reference float path, then quantize by hand
    ref_f, _ = IF.masked_multihead_attention(
        pt.to_tensor((x_int.astype(np.float32)
                      * qkv_scale.reshape(-1)[None, :])),
        pt.to_tensor(cache), sequence_lengths=pt.to_tensor(lens))
    v = (np.asarray(ref_f) + shift[None]) * smooth[None]
    qv = 127.0 * 0.05 * v
    qv = np.sign(qv) * np.floor(np.abs(qv) + 0.5)
    want = np.clip(qv, -127.0, 127.0).astype(np.int8)
    # rounding at the .5 boundary may differ by 1 ulp on accumulated sums
    assert (np.abs(out.astype(np.int32) - want.astype(np.int32)) <= 1).all()


def test_fused_multi_transformer_pre_caches():
    """Context phase with prefix-tuning pre_caches: queries see prefix +
    causal current, and the cache holds [prefix, context] (reference
    fused_multi_transformer_op.cu cache_offset path)."""
    from paddle_tpu.incubate.nn import functional as IF
    B, S, H, D, P = 2, 4, 2, 8, 3
    E = H * D
    Tmax = 16
    ln_s = np.ones((E,), np.float32)
    ln_b = np.zeros((E,), np.float32)
    qkvw = rng.normal(size=(3, H, D, E)).astype(np.float32) * 0.05
    lw = rng.normal(size=(E, E)).astype(np.float32) * 0.05
    f1 = rng.normal(size=(E, 2 * E)).astype(np.float32) * 0.05
    f2 = rng.normal(size=(2 * E, E)).astype(np.float32) * 0.05
    x = rng.normal(size=(B, S, E)).astype(np.float32)
    pre = rng.normal(size=(2, B, H, P, D)).astype(np.float32) * 0.3
    cache = np.zeros((2, B, H, Tmax, D), np.float32)
    t = pt.to_tensor
    out, caches = IF.fused_multi_transformer(
        t(x), [t(ln_s)], [t(ln_b)], [t(qkvw)], [None], [t(lw)], [None],
        [t(ln_s)], [t(ln_b)], [t(f1)], [None], [t(f2)], [None],
        cache_kvs=[t(cache)], pre_caches=[t(pre)])
    got_cache = np.asarray(caches[0])
    # prefix occupies cache[:P], context KV comes next
    np.testing.assert_allclose(got_cache[0][:, :, :P],
                               pre[0], rtol=1e-5, atol=1e-5)
    # manual: q from LN(x); attends over [pre_k, k]
    y = np.asarray(x)
    mu = y.mean(-1, keepdims=True)
    var = y.var(-1, keepdims=True)
    h = (y - mu) / np.sqrt(var + 1e-5)
    qkv = np.einsum("bse,thde->bsthd", h, qkvw)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    kf = np.concatenate([np.swapaxes(pre[0], 1, 2), k], 1)  # [B,P+S,H,D]
    vf = np.concatenate([np.swapaxes(pre[1], 1, 2), v], 1)
    np.testing.assert_allclose(got_cache[0][:, :, P:P + S],
                               np.swapaxes(k, 1, 2), rtol=1e-5, atol=1e-5)
    sc = np.einsum("bqhd,bkhd->bhqk", q, kf) / np.sqrt(D)
    mask = np.tril(np.ones((S, P + S)), P).astype(bool)
    sc = np.where(mask[None, None], sc, -1e30)
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    attn = np.einsum("bhqk,bkhd->bqhd", p, vf).reshape(B, S, E)
    resid = np.asarray(x) + attn @ lw
    hh = (resid - resid.mean(-1, keepdims=True)) / np.sqrt(
        resid.var(-1, keepdims=True) + 1e-5)
    act = 0.5 * (hh @ f1) * (1 + np.tanh(np.sqrt(2 / np.pi)
                                         * ((hh @ f1)
                                            + 0.044715 * (hh @ f1) ** 3)))
    want = resid + act @ f2
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-3, atol=2e-3)


def test_fused_multi_transformer_pre_caches_decode():
    """Decode convention (pinned): re-pass pre_caches each step;
    time_step counts context+generated tokens EXCLUDING the prefix, so
    the write slot is time_step + P and attention covers the prefix."""
    from paddle_tpu.incubate.nn import functional as IF
    B, S, H, D, P = 1, 4, 2, 8, 3
    E = H * D
    Tmax = 16
    t = pt.to_tensor
    ln_s, ln_b = np.ones((E,), np.float32), np.zeros((E,), np.float32)
    qkvw = rng.normal(size=(3, H, D, E)).astype(np.float32) * 0.05
    lw = rng.normal(size=(E, E)).astype(np.float32) * 0.05
    f1 = rng.normal(size=(E, 2 * E)).astype(np.float32) * 0.05
    f2 = rng.normal(size=(2 * E, E)).astype(np.float32) * 0.05
    pre = rng.normal(size=(2, B, H, P, D)).astype(np.float32) * 0.3
    cache = np.zeros((2, B, H, Tmax, D), np.float32)
    x_ctx = rng.normal(size=(B, S, E)).astype(np.float32)
    args = ([t(ln_s)], [t(ln_b)], [t(qkvw)], [None], [t(lw)], [None],
            [t(ln_s)], [t(ln_b)], [t(f1)], [None], [t(f2)], [None])
    _, caches = IF.fused_multi_transformer(
        t(x_ctx), *args, cache_kvs=[t(cache)], pre_caches=[t(pre)])
    # decode one token at time_step = S (context length, prefix excluded)
    x_dec = rng.normal(size=(B, 1, E)).astype(np.float32)
    out_d, caches2 = IF.fused_multi_transformer(
        t(x_dec), *args, cache_kvs=[t(np.asarray(caches[0]))],
        pre_caches=[t(pre)], time_step=S)
    c2 = np.asarray(caches2[0])
    # new token lands at slot P + S; prefix/context slots untouched
    np.testing.assert_allclose(c2[0][:, :, :P + S],
                               np.asarray(caches[0])[0][:, :, :P + S],
                               rtol=1e-6)
    assert np.abs(c2[0][:, :, P + S]).sum() > 0
    assert np.abs(c2[0][:, :, P + S + 1:]).sum() == 0
    assert np.isfinite(np.asarray(out_d)).all()
