"""Context parallelism: ring attention + Ulysses vs full-attention reference.

The reference snapshot lacks CP entirely (SURVEY §2.5); these tests pin our
implementation to the mathematically exact answer: shard the sequence over a
mesh axis, run ring/Ulysses inside shard_map, compare output AND input grads
against single-device full softmax attention.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.context_parallel import (
    ring_flash_attention, ulysses_attention, zigzag_permutation,
    zigzag_positions, zigzag_ring_flash_attention)

B, S, H, D = 2, 64, 4, 8
CP = 4


def _ref_attention(q, k, v, causal):
    scale = 1.0 / math.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


def _mesh():
    return Mesh(np.array(jax.devices()[:CP]).reshape(CP), ("sep",))


def _rand():
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, H, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, H, D), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_attention_matches_reference(impl, causal):
    q, k, v = _rand()
    mesh = _mesh()

    if impl == "ring":
        def attn(q, k, v):
            return ring_flash_attention(q, k, v, "sep", causal)
    else:
        def attn(q, k, v):
            return ulysses_attention(q, k, v, "sep", causal)

    spec = P(None, "sep", None, None)
    sharded = jax.jit(jax.shard_map(
        attn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))

    def loss_cp(q, k, v):
        return jnp.sum(jnp.sin(sharded(q, k, v).astype(jnp.float32)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_ref_attention(q, k, v, causal)))

    out = sharded(q, k, v)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    g_cp = jax.jit(jax.grad(loss_cp, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_cp, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name} ({impl})")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gqa_and_unaligned_shard(causal):
    """GQA kv heads ride the ring natively, and a local shard length that
    is >128 and block-unaligned exercises the kernel's padding + lse
    slicing (regression: lse was returned at padded length)."""
    S_un, Hkv, cp = 2 * 200, 2, 2
    key = jax.random.key(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S_un, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S_un, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, S_un, Hkv, D), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:cp]).reshape(cp), ("sep",))
    spec = P(None, "sep", None, None)
    sharded = jax.jit(jax.shard_map(
        lambda q, k, v: ring_flash_attention(q, k, v, "sep", causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))

    def ref(q, k, v):
        kf = jnp.repeat(k, H // Hkv, axis=2)
        vf = jnp.repeat(v, H // Hkv, axis=2)
        scale = 1.0 / math.sqrt(D)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kf).astype(
            jnp.float32) * scale
        if causal:
            mask = jnp.tril(jnp.ones((S_un, S_un), bool))
            logits = jnp.where(mask[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, -1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vf.astype(jnp.float32))

    np.testing.assert_allclose(np.asarray(sharded(q, k, v)),
                               np.asarray(ref(q, k, v)),
                               rtol=2e-5, atol=2e-5)

    g_cp = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(jnp.sin(sharded(q, k, v))),
        argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(jnp.sin(ref(q, k, v))),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_cp, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-4, err_msg=f"d{name}")


def test_ring_bf16_runs():
    q, k, v = (x.astype(jnp.bfloat16) for x in _rand())
    mesh = _mesh()
    spec = P(None, "sep", None, None)
    out = jax.jit(jax.shard_map(
        lambda q, k, v: ring_flash_attention(q, k, v, "sep", True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))(q, k, v)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, np.float32)).all()


class TestZigzag:
    def test_permutation_and_positions_agree(self):
        """zigzag_positions == the slice of zigzag_permutation this rank
        receives under contiguous sharding of the permuted sequence."""
        R, S = 4, 64
        perm = zigzag_permutation(S, R)
        assert sorted(perm.tolist()) == list(range(S))
        s_l = S // R
        mesh = Mesh(np.array(jax.devices()[:R]).reshape(R), ("sep",))
        pos = jax.jit(jax.shard_map(
            lambda: zigzag_positions(s_l, "sep")[None],
            mesh=mesh, in_specs=(), out_specs=P("sep"),
            check_vma=False))()
        np.testing.assert_array_equal(np.asarray(pos).reshape(-1), perm)

    def test_zigzag_matches_reference(self):
        """Balanced zigzag ring == full causal attention on the
        un-permuted sequence (output AND grads)."""
        q, k, v = _rand()
        perm = zigzag_permutation(S, CP)
        inv = np.argsort(perm)
        mesh = _mesh()
        spec = P(None, "sep", None, None)
        sharded = jax.jit(jax.shard_map(
            lambda q, k, v: zigzag_ring_flash_attention(q, k, v, "sep"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))

        def loss_zz(q, k, v):
            out_p = sharded(q[:, perm], k[:, perm], v[:, perm])
            return jnp.sum(jnp.sin(out_p[:, inv].astype(jnp.float32)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(_ref_attention(q, k, v, True)))

        out = sharded(q[:, perm], k[:, perm], v[:, perm])[:, inv]
        ref = _ref_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        g_zz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_zz, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{name} (zigzag)")

    def test_zigzag_gqa(self):
        Hkv = 2
        key = jax.random.key(2)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
        k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
        v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)
        perm = zigzag_permutation(S, CP)
        inv = np.argsort(perm)
        mesh = _mesh()
        spec = P(None, "sep", None, None)
        sharded = jax.jit(jax.shard_map(
            lambda q, k, v: zigzag_ring_flash_attention(q, k, v, "sep"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
        out = sharded(q[:, perm], k[:, perm], v[:, perm])[:, inv]
        kr = jnp.repeat(k, H // Hkv, axis=2)
        vr = jnp.repeat(v, H // Hkv, axis=2)
        ref = _ref_attention(q, kr, vr, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


    def test_zigzag_full_mesh_r8(self):
        """Degree-8 zigzag (every virtual device): 16 blocks, balanced
        pair counts on all ranks, still exact."""
        R = 8
        q, k, v = _rand()
        perm = zigzag_permutation(S, R)
        inv = np.argsort(perm)
        mesh = Mesh(np.array(jax.devices()[:R]).reshape(R), ("sep",))
        spec = P(None, "sep", None, None)
        sharded = jax.jit(jax.shard_map(
            lambda q, k, v: zigzag_ring_flash_attention(q, k, v, "sep"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
        out = sharded(q[:, perm], k[:, perm], v[:, perm])[:, inv]
        ref = _ref_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
