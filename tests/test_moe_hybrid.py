"""MoE expert parallelism in the compiled hybrid step.

Reference surface: incubate MoE layer + EP process groups
(python/paddle/incubate/distributed/models/moe/moe_layer.py:263,
distributed/utils/moe_utils.py global_scatter/global_gather).  TPU-native
design under test: experts sharded over the dp mesh axis with one
lax.all_to_all each way inside the all-axes-manual shard_map
(parallel/moe.py), GShard aux loss entering training via gradient
injection (inject_aux_grad), and dp-exempt grad reduction + dp-sharded
optimizer moments for expert leaves (parallel/manual.py ep_leaves).

Equivalence pins: any EP/TP/PP/sharding layout must reproduce the
single-device loss trajectory (capacity_factor set high enough that no
tokens drop, so routing is layout-invariant)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from paddle_tpu import parallel as dist
from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
from paddle_tpu.parallel.moe import (inject_aux_grad, moe_ffn_ep,
                                     topk_scatter_routing)
from paddle_tpu.parallel.topology import HybridTopology, set_topology
from paddle_tpu.incubate.distributed.models.moe.gating import (
    compute_capacity, topk_capacity_gating)


@pytest.fixture(autouse=True)
def reset_topology():
    yield
    set_topology(HybridTopology())


def _cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                max_position_embeddings=64, moe_num_experts=4,
                moe_capacity_factor=2.0, moe_aux_coef=0.0)
    base.update(kw)
    return GPTConfig(**base)


def _losses(cfg, steps=3, batch=8, seq=32, **kw):
    axes = {k: kw.pop(k) for k in ("dp", "mp", "pp", "sep", "sharding")
            if k in kw}
    topo = dist.init_topology(**axes)
    kw.setdefault("num_microbatches", 2 if axes.get("pp", 1) > 1 else 1)
    step_fn, init_fn = build_gpt_train_step(cfg, topo, **kw)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    out = []
    for _ in range(steps):
        state, loss = step_fn(state, ids, labels)
        out.append(float(np.asarray(jax.device_get(loss))))
    return out


_BASE = {}


def _base(aux=0.0):
    if aux not in _BASE:
        _BASE[aux] = _losses(_cfg(moe_aux_coef=aux))
    return _BASE[aux]


def test_moe_single_device_trains():
    losses = _base()
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("axes,extra", [
    (dict(dp=4), {}),                             # pure EP (1 expert/rank)
    (dict(dp=2, mp=2), {}),                       # EP x expert-TP
    (dict(dp=2, mp=2), dict(sequence_parallel=True)),   # EP x TP-SP
    (dict(dp=2, pp=2), {}),                       # EP x pipeline (1f1b)
    (dict(dp=2, sharding=2), dict(sharding_stage=2)),
    (dict(dp=2, sharding=2), dict(sharding_stage=3)),
    (dict(dp=2, sep=2), {}),                      # EP x context parallel
])
def test_moe_layout_equivalence(axes, extra):
    losses = _losses(_cfg(), **axes, **extra)
    np.testing.assert_allclose(losses, _base(), rtol=2e-3)


def test_moe_aux_coef_changes_training():
    """aux injection must alter the trajectory (gradients) while leaving
    the step-0 forward loss untouched (inject_aux_grad is identity fwd)."""
    on = _losses(_cfg(moe_aux_coef=1e-1))
    off = _base()
    assert on[0] == pytest.approx(off[0], rel=1e-6)
    assert any(abs(a - b) > 1e-6 for a, b in zip(on[1:], off[1:]))


@pytest.mark.parametrize("axes,extra", [
    (dict(dp=4), {}),
    (dict(dp=2, mp=2), dict(sequence_parallel=True)),
    (dict(dp=2, sharding=2), dict(sharding_stage=2)),
    (dict(dp=2, sep=2), {}),
])
def test_moe_aux_equivalence_across_layouts(axes, extra):
    """With aux ON, every layout must track the single-device run: pins
    the injection-coefficient normalization per path (value_and_grad vs
    manual-vjp /norm), the sharding-axis completion via psum_scatter,
    the sep site-count factor, and the SP no-mp-reduce gate-grad
    assumption."""
    losses = _losses(_cfg(moe_aux_coef=1e-2), **axes, **extra)
    np.testing.assert_allclose(losses, _base(1e-2), rtol=2e-3)


def test_moe_pp_aux_equivalence():
    """Manual-vjp pipeline path normalizes grads by /norm AFTER the vjp;
    the injected coefficient compensates (models/gpt.py _moe_coef)."""
    losses = _losses(_cfg(moe_aux_coef=1e-2), dp=2, pp=2)
    np.testing.assert_allclose(losses, _base(1e-2), rtol=2e-3)


def test_moe_checkpoint_reshard_ep_dp2_to_dp4(tmp_path):
    """EP-sharded state (expert params + dp-sharded moments) must survive
    sharded save on dp2 and reshard-on-load into a dp4 topology, then
    continue the exact single-device trajectory (SURVEY §5 checkpoint
    resume; reference semi-auto checkpoint reshard tests)."""
    from paddle_tpu.parallel import checkpoint as ck
    from paddle_tpu.models.gpt import build_gpt_train_step
    cfg = _cfg()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)

    topo2 = dist.init_topology(dp=2)
    step2, init2 = build_gpt_train_step(cfg, topo2, num_microbatches=1)
    state = init2(0)
    for _ in range(2):
        state, _ = step2(state, ids, labels)
    ck.save_state_dict(state, str(tmp_path))

    topo4 = dist.init_topology(dp=4)
    step4, init4 = build_gpt_train_step(cfg, topo4, num_microbatches=1)
    state4 = init4(1)          # different seed: load must overwrite all
    ck.load_state_dict(state4, str(tmp_path))
    _, loss = step4(state4, ids, labels)
    np.testing.assert_allclose(float(np.asarray(loss)), _base()[2],
                               rtol=2e-3)


def test_inject_aux_grad_matches_explicit_loss():
    key = jax.random.key(0)
    x = jax.random.normal(key, (4, 3))

    def loss_inject(x):
        aux = jnp.sum(x ** 2)          # stand-in aux depending on params
        y = inject_aux_grad(x * 2.0, aux, 0.3)
        return jnp.sum(y)

    def loss_explicit(x):
        aux = jnp.sum(x ** 2)
        return jnp.sum(x * 2.0) + 0.3 * aux

    g1 = jax.grad(loss_inject)(x)
    g2 = jax.grad(loss_explicit)(x)
    np.testing.assert_allclose(g1, g2, rtol=1e-6)
    # forward value excludes the aux term by design
    assert float(loss_inject(x)) == pytest.approx(
        float(jnp.sum(x * 2.0)), rel=1e-6)


def _llama_losses(cfg, steps=3, batch=8, seq=32, **kw):
    from paddle_tpu.models.llama import build_llama_train_step
    axes = {k: kw.pop(k) for k in ("dp", "mp", "pp", "sep", "sharding")
            if k in kw}
    topo = dist.init_topology(**axes)
    kw.setdefault("num_microbatches", 2 if axes.get("pp", 1) > 1 else 1)
    step_fn, init_fn = build_llama_train_step(cfg, topo, **kw)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
    labels = np.roll(ids, -1, axis=1)
    out = []
    for _ in range(steps):
        state, loss = step_fn(state, ids, labels)
        out.append(float(np.asarray(jax.device_get(loss))))
    return out


@pytest.mark.parametrize("axes,extra", [
    (dict(dp=2, mp=2), {}),                    # Mixtral EP x expert-TP
    (dict(dp=2, pp=2), {}),                    # EP x pipeline
])
def test_llama_moe_layout_equivalence(axes, extra):
    """Mixtral-style SwiGLU MoE (llama builder) reproduces its own
    single-device trajectory under EP layouts."""
    from paddle_tpu.models.llama import llama_tiny
    cfg = llama_tiny(moe_num_experts=4, moe_capacity_factor=2.0,
                     moe_aux_coef=1e-2)
    base = _llama_losses(cfg)
    losses = _llama_losses(cfg, **axes, **extra)
    assert base[-1] < base[0]
    np.testing.assert_allclose(losses, base, rtol=2e-3)


def test_llama_shared_experts_layout_equivalence():
    """DeepSeek-style shared experts (dense always-on SwiGLU added to the
    routed output) must preserve layout equivalence — the shared path
    rides the dense col/row TP machinery incl. SP."""
    from paddle_tpu.models.llama import llama_tiny
    cfg = llama_tiny(moe_num_experts=4, moe_capacity_factor=2.0,
                     moe_aux_coef=0.0, moe_num_shared_experts=2)
    base = _llama_losses(cfg)
    assert base[-1] < base[0]
    for axes, extra in ((dict(dp=2, mp=2), {}),
                        (dict(dp=2, mp=2), dict(sequence_parallel=True))):
        losses = _llama_losses(cfg, **axes, **extra)
        np.testing.assert_allclose(losses, base, rtol=2e-3)


def test_llama_shared_experts_decode_parity():
    """Serving path computes the same shared+routed FFN as training."""
    from paddle_tpu.models.llama import llama_tiny, build_llama_train_step
    from paddle_tpu.models.generation import (build_llama_decoder,
                                              llama_generate)
    cfg = llama_tiny(moe_num_experts=4, moe_num_shared_experts=2)
    topo = dist.init_topology()
    _, init_fn = build_llama_train_step(cfg, topo, num_microbatches=1)
    params = init_fn(0)["params"]
    set_topology(HybridTopology())
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
    out = llama_generate(params, cfg, ids, max_new_tokens=4,
                         temperature=0.0, use_pallas=False)
    cur = jnp.asarray(ids)
    for _ in range(4):
        prefill, _ = build_llama_decoder(cfg, cur.shape[1],
                                         use_pallas=False)
        _, logits = prefill(params, cur)
        cur = jnp.concatenate(
            [cur, jnp.argmax(logits, -1).astype(cur.dtype)[:, None]],
            axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_eager_llama_moe_forward_backward():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    cfg = llama_tiny(moe_num_experts=4)
    net = LlamaForCausalLM(cfg)
    ids = pt.Tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16)).astype(np.int32))
    loss = net(ids, ids)
    loss.backward()
    g = net.llama.layers[0].mlp.e_gate.grad
    arr = np.asarray(g._value if hasattr(g, "_value") else g)
    assert np.isfinite(float(loss._value)) and np.isfinite(arr).all()


def test_eager_gpt_moe_forward_backward():
    """GPTBlock routes its FFN through the incubate MoELayer when
    cfg.moe_num_experts is set (eager parity with the compiled path)."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM
    cfg = _cfg()
    net = GPTForCausalLM(cfg)
    ids = pt.Tensor(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16)).astype(np.int32))
    loss = net(ids, ids)
    loss.backward()
    g = net.gpt.blocks[0].moe.w1.grad
    arr = np.asarray(g._value if hasattr(g, "_value") else g)
    assert np.isfinite(float(loss._value)) and np.isfinite(arr).all()


def test_eager_moe_aux_coef_reaches_gradients():
    """cfg.moe_aux_coef must change eager GPT gradients (MoELayer
    aux_coef injection), matching eager Llama semantics — with identical
    forward loss (the injection is identity on values)."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM

    def gate_grad(aux):
        pt.seed(0)
        net = GPTForCausalLM(_cfg(moe_aux_coef=aux))
        ids = pt.Tensor(np.random.default_rng(0).integers(
            0, 128, (2, 16)).astype(np.int32))
        loss = net(ids, ids)
        loss.backward()
        g = net.gpt.blocks[0].moe.gate.weight.grad
        return float(loss._value), np.asarray(
            g._value if hasattr(g, "_value") else g)

    l0, g0 = gate_grad(0.0)
    l1, g1 = gate_grad(1.0)
    assert l0 == pytest.approx(l1, rel=1e-6)
    assert not np.allclose(g0, g1)


def test_scatter_routing_matches_dense_gating():
    """idx/pos/w reconstruct exactly the dense [T, E, C] combine tensor of
    the eager gate (incubate gating.topk_capacity_gating)."""
    T, E, k = 16, 4, 2
    logits = jax.random.normal(jax.random.key(1), (T, E))
    C = compute_capacity(T, E, k, 1.25)
    combine_ref, dispatch_ref, aux_ref = topk_capacity_gating(logits, k, C)
    idx, pos, w, aux = topk_scatter_routing(logits, k, C)
    combine = jnp.zeros((T, E, C))
    for t in range(T):
        for j in range(k):
            if int(pos[t, j]) < C:
                combine = combine.at[t, int(idx[t, j]),
                                     int(pos[t, j])].set(w[t, j])
    np.testing.assert_allclose(combine, combine_ref, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(aux, aux_ref, rtol=1e-6)


def test_expert_choice_routing_perfect_balance():
    """Every expert selects exactly C tokens — balance by construction
    (Zhou et al. 2022), no aux loss needed."""
    from paddle_tpu.parallel.moe import expert_choice_routing
    T, E, C = 32, 4, 8
    logits = jax.random.normal(jax.random.key(0), (T, E))
    sel, w, probs = expert_choice_routing(logits, C)
    assert sel.shape == (E, C) and w.shape == (E, C)
    # weights are the actual router probs of the selected tokens
    for e in range(E):
        np.testing.assert_allclose(w[e], probs[sel[e], e], rtol=1e-6)
    # per-expert top-C: selected probs >= every unselected prob
    for e in range(E):
        unsel = np.setdiff1d(np.arange(T), np.asarray(sel[e]))
        assert float(np.min(np.asarray(w[e]))) >= \
            float(np.max(np.asarray(probs)[unsel, e]))


def test_expert_choice_gpt_trains():
    """Compiled hybrid step with the expert-choice router (dp2 EP):
    trains without aux loss, loss decreases."""
    losses = _losses(_cfg(moe_router="expert_choice",
                          moe_capacity_factor=2.0), dp=2)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_dropless_training_matches_capacity_path():
    """moe_dropless=True (differentiable ragged_dot experts) must track
    the capacity-dispatch trajectory when capacity is high enough that
    the capacity path drops nothing either."""
    losses = _losses(_cfg(moe_dropless=True))
    np.testing.assert_allclose(losses, _base(), rtol=2e-3)


def test_dropless_requires_local_banks():
    from paddle_tpu.models.gpt import build_gpt_train_step
    topo = dist.init_topology(dp=2)
    with pytest.raises(ValueError, match="local expert banks"):
        build_gpt_train_step(_cfg(moe_dropless=True), topo,
                             num_microbatches=1)


def test_grouped_gemm_matches_nodrop_dispatch():
    """The ragged_dot serving path must equal the capacity=T dispatch
    buffers bit-for-bit in routing semantics (both dropless)."""
    from paddle_tpu.parallel.moe import (moe_swiglu_ffn_ep,
                                         moe_swiglu_ffn_grouped)
    T, h, f, E, k = 20, 8, 16, 4, 2
    ks = jax.random.split(jax.random.key(3), 5)
    x = jax.random.normal(ks[0], (T, h))
    rw = jax.random.normal(ks[1], (h, E)) * 0.1
    wg = jax.random.normal(ks[2], (E, h, f)) * 0.1
    wu = jax.random.normal(ks[3], (E, h, f)) * 0.1
    wd = jax.random.normal(ks[4], (E, f, h)) * 0.1
    a = moe_swiglu_ffn_ep(x, rw, wg, wu, wd, top_k=k, capacity=T)
    b = moe_swiglu_ffn_grouped(x, rw, wg, wu, wd, top_k=k)
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5)


def test_expert_choice_capacity_override_rejected():
    from paddle_tpu.parallel.moe import moe_swiglu_ffn_ep
    x = jnp.zeros((4, 8))
    rw = jnp.zeros((8, 2))
    wg = wu = jnp.zeros((2, 8, 4))
    wd = jnp.zeros((2, 4, 8))
    with pytest.raises(ValueError, match="no-drop"):
        moe_swiglu_ffn_ep(x, rw, wg, wu, wd, capacity=4,
                          router="expert_choice")


def test_expert_choice_decode_guard():
    from paddle_tpu.models.generation import build_llama_decoder
    from paddle_tpu.models.llama import llama_tiny
    with pytest.raises(NotImplementedError, match="expert_choice"):
        build_llama_decoder(llama_tiny(moe_num_experts=4,
                                       moe_router="expert_choice"), 16)


def test_moe_ffn_ep_local_matches_reference():
    """Single-process moe_ffn_ep == a straightforward dense-mask MoE on
    the same params (independent formulation: einsum dispatch/combine)."""
    T, h, f, E, k = 12, 8, 16, 4, 2
    keys = jax.random.split(jax.random.key(2), 6)
    x = jax.random.normal(keys[0], (T, h))
    gate_w = jax.random.normal(keys[1], (h, E)) * 0.1
    w1 = jax.random.normal(keys[2], (E, h, f)) * 0.1
    b1 = jax.random.normal(keys[3], (E, f)) * 0.1
    w2 = jax.random.normal(keys[4], (E, f, h)) * 0.1
    b2 = jax.random.normal(keys[5], (E, h)) * 0.1
    C = compute_capacity(T, E, k, 2.0)

    got = moe_ffn_ep(x, gate_w, w1, b1, w2, b2, top_k=k,
                     capacity_factor=2.0)

    combine, dispatch, _ = topk_capacity_gating(
        (x.astype(jnp.float32) @ gate_w), k, C)
    ein = jnp.einsum("tec,th->ech", dispatch.astype(jnp.float32), x)
    hdn = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", ein, w1)
                      + b1[:, None, :], approximate=True)
    out = jnp.einsum("ecf,efh->ech", hdn, w2) + b2[:, None, :]
    want = jnp.einsum("tec,ech->th", combine, out)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)
