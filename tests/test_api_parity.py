"""API-parity ratchets (VERDICT r2 item 5): assert 100% of the reference's
``__all__`` for nn, nn.functional, optimizer, and distribution so the tail
can't regress.  The reference __init__ files are read directly — if the
snapshot moves, the ratchet moves with it.

ISSUE 9 triage note: this module was on the suspected
compile-cache-flake list (PR 8), but its failures are unrelated — the
``/root/reference`` paddle snapshot does not exist in this container,
so every parametrized read fails with FileNotFoundError before any jax
program compiles.  The donated-deserialize cache opt-out is therefore
NOT applied here (there is nothing for it to fix); the module skips
itself cleanly when the snapshot is absent instead of erroring 35
times.
"""

import re
import pathlib

import pytest

REF = pathlib.Path("/root/reference/python/paddle")


def ref_all(relpath):
    if not REF.exists():
        # same convention as test_aux_packages's reference-tree probe:
        # the ratchet can only measure where the snapshot is mounted
        pytest.skip("reference tree not mounted")
    src = (REF / relpath).read_text()
    m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.S)
    assert m, f"no __all__ in {relpath}"
    return sorted({a or b for a, b in
                   re.findall(r"'([^']+)'|\"([^\"]+)\"", m.group(1))})


@pytest.mark.parametrize("relpath,modname", [
    ("__init__.py", "paddle_tpu"),
    ("nn/__init__.py", "paddle_tpu.nn"),
    ("nn/functional/__init__.py", "paddle_tpu.nn.functional"),
    ("optimizer/__init__.py", "paddle_tpu.optimizer"),
    ("distribution/__init__.py", "paddle_tpu.distribution"),
    ("distributed/__init__.py", "paddle_tpu.distributed"),
    ("static/__init__.py", "paddle_tpu.static"),
    ("static/nn/__init__.py", "paddle_tpu.static.nn"),
    ("jit/__init__.py", "paddle_tpu.jit"),
    ("amp/__init__.py", "paddle_tpu.amp"),
    ("vision/__init__.py", "paddle_tpu.vision"),
    ("io/__init__.py", "paddle_tpu.io"),
    ("sparse/__init__.py", "paddle_tpu.sparse"),
    ("linalg.py", "paddle_tpu.linalg"),
    ("fft.py", "paddle_tpu.fft"),
    ("signal.py", "paddle_tpu.signal"),
    ("metric/__init__.py", "paddle_tpu.metric"),
    ("incubate/nn/functional/__init__.py",
     "paddle_tpu.incubate.nn.functional"),
    ("incubate/__init__.py", "paddle_tpu.incubate"),
    ("distributed/fleet/__init__.py", "paddle_tpu.parallel.fleet"),
    ("vision/transforms/__init__.py", "paddle_tpu.vision.transforms"),
    ("vision/datasets/__init__.py", "paddle_tpu.vision.datasets"),
    ("vision/ops.py", "paddle_tpu.vision.ops"),
    ("profiler/__init__.py", "paddle_tpu.profiler"),
    ("audio/__init__.py", "paddle_tpu.audio"),
    ("geometric/__init__.py", "paddle_tpu.geometric"),
    ("quantization/__init__.py", "paddle_tpu.quantization"),
    ("autograd/__init__.py", "paddle_tpu.autograd"),
    ("nn/initializer/__init__.py", "paddle_tpu.nn.initializer"),
    ("nn/utils/__init__.py", "paddle_tpu.nn.utils"),
    ("device/__init__.py", "paddle_tpu.device"),
    ("regularizer.py", "paddle_tpu.regularizer"),
    ("hub.py", "paddle_tpu.hub"),
    ("sysconfig.py", "paddle_tpu.sysconfig"),
    ("callbacks.py", "paddle_tpu.callbacks"),
])
def test_namespace_parity_100pct(relpath, modname):
    import importlib
    mod = importlib.import_module(modname)
    want = ref_all(relpath)
    missing = [n for n in want if not hasattr(mod, n)]
    assert not missing, (f"{modname}: {len(missing)}/{len(want)} reference "
                         f"names missing: {missing}")


def test_distribution_modules_exist():
    import paddle_tpu.distribution as d
    assert hasattr(d, "constraint") and hasattr(d.constraint, "simplex")
    assert hasattr(d, "variable") and hasattr(d.variable, "real")


def test_optimizer_classes_construct():
    import paddle_tpu as paddle
    w = paddle.create_parameter([2, 2], "float32")
    paddle.optimizer.ASGD(parameters=[w])
    paddle.optimizer.Rprop(parameters=[w])
    paddle.optimizer.LBFGS(parameters=[w])
