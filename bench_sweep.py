"""Scratch perf sweep on the real chip (not committed as part of bench)."""
import sys
import time

import numpy as np


def run(batch, seq, steps, remat, h=768, L=12, V=32768, mbs=1):
    import jax
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
    from paddle_tpu import parallel as dist

    cfg = GPTConfig(vocab_size=V, hidden_size=h, num_layers=L,
                    num_heads=h // 64, max_position_embeddings=seq,
                    dtype="bfloat16")
    topo = dist.init_topology(devices=jax.devices()[:1])
    step_fn, init_fn = build_gpt_train_step(cfg, topo, num_microbatches=mbs,
                                            remat=remat)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    state, loss = step_fn(state, ids, labels)
    jax.device_get(loss)
    state, loss = step_fn(state, ids, labels)
    jax.device_get(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step_fn(state, ids, labels)
    lv = float(np.asarray(jax.device_get(loss)))
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    f = 4 * h
    n_params = V * h + seq * h + L * (4 * h * h + 2 * h * f + 9 * h) + 2 * h
    fpt = 6 * n_params + 12 * L * h * seq
    from bench import peak_flops_per_chip
    mfu = tps * fpt / peak_flops_per_chip(jax.devices()[0])
    print(f"batch={batch} seq={seq} remat={remat} h={h} L={L}: "
          f"{tps:,.0f} tok/s  MFU={mfu:.3f}  loss={lv:.3f}", flush=True)


if __name__ == "__main__":
    import ast
    for args in ast.literal_eval(sys.argv[1]):
        try:
            run(**args)
        except Exception as e:
            print(f"{args}: FAILED {type(e).__name__}: {e}", flush=True)
