"""Perf sweep matrix for the real chip (VERDICT r3 item 1a).

Default matrix (no argv): GPT-125M and GPT-1.3B-width configs x remat
on/off x Pallas-flash on/off, plus one autotuned flash point — each row
printed as a JSON line so ``tools/tpu_probe.py``'s auto-seize archives
the whole table the moment the chip returns.

Explicit override: ``python bench_sweep.py "[{'batch':8,'seq':1024,...}]"``
(the round-3 scratch form, kept for interactive use).
"""

import json
import sys
import time

import numpy as np


def run(batch, seq, steps, remat, h=768, L=12, V=32768, mbs=1,
        flash=None, autotune=False, remat_policy=None, experts=0,
        dropless=False, family="gpt", kv_heads=None):
    import jax
    from paddle_tpu import parallel as dist

    # always assign (not just set-on-True): rows run in one process, so a
    # stale True from an earlier autotune row would mislabel later rows
    from paddle_tpu.core.flags import FLAGS
    FLAGS.use_autotune = bool(autotune)
    if family not in ("gpt", "llama"):
        raise ValueError(f"unknown family {family!r}")
    if family == "gpt" and kv_heads is not None:
        raise ValueError("kv_heads applies to family='llama' only (GQA); "
                         "a GPT row must not silently drop the knob")
    if family == "llama" and (experts or dropless):
        raise ValueError("MoE sweep rows use family='gpt' (the llama "
                         "branch does not thread moe knobs; a row must "
                         "never claim a MoE measurement that did not run)")
    topo = dist.init_topology(devices=jax.devices()[:1])
    if family == "llama":
        # GQA path: flash has native grouped KV, dense repeats kv heads —
        # the tradeoff the GPT rows can't measure
        from paddle_tpu.models.llama import (LlamaConfig,
                                             build_llama_train_step)
        cfg = LlamaConfig(vocab_size=V, hidden_size=h,
                          intermediate_size=int(h * 8 / 3) // 128 * 128,
                          num_layers=L, num_heads=h // 64,
                          num_kv_heads=kv_heads,
                          max_position_embeddings=seq, dtype="bfloat16")
        step_fn, init_fn = build_llama_train_step(
            cfg, topo, num_microbatches=mbs, remat=remat, use_flash=flash,
            remat_policy=remat_policy)
    else:
        from paddle_tpu.models.gpt import GPTConfig, build_gpt_train_step
        cfg = GPTConfig(vocab_size=V, hidden_size=h, num_layers=L,
                        num_heads=h // 64, max_position_embeddings=seq,
                        dtype="bfloat16", moe_num_experts=experts,
                        moe_dropless=dropless)
        step_fn, init_fn = build_gpt_train_step(
            cfg, topo, num_microbatches=mbs, remat=remat, use_flash=flash,
            remat_policy=remat_policy)
    state = init_fn(0)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    state, loss = step_fn(state, ids, labels)
    jax.device_get(loss)
    state, loss = step_fn(state, ids, labels)
    jax.device_get(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = step_fn(state, ids, labels)
    lv = float(np.asarray(jax.device_get(loss)))
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    if family == "llama":
        f = cfg.intermediate_size
        kvd = cfg.kv_heads * cfg.head_dim
        n_params = 2 * V * h + L * (2 * h * h + 2 * h * kvd
                                    + 3 * h * f + 2 * h) + h
    else:
        f = 4 * h
        # ACTIVE params per token (MFU basis): MoE replaces the dense
        # FFN's 2hf with top_k expert FFNs + the router
        ffn_p = (cfg.moe_top_k * 2 * h * f + h * experts) if experts \
            else 2 * h * f
        n_params = V * h + seq * h + L * (4 * h * h + ffn_p + 9 * h) \
            + 2 * h
    fpt = 6 * n_params + 12 * L * h * seq      # MODEL flops (MFU basis,
    # same definition as bench.py / the BASELINE 45% target)
    from bench import peak_flops_per_chip
    peak = peak_flops_per_chip(jax.devices()[0])
    mfu = tps * fpt / peak
    row = {
        "batch": batch, "seq": seq, "h": h, "L": L, "remat": remat,
        "remat_policy": remat_policy, "flash": flash, "autotune": autotune,
        "tokens_per_sec": round(tps, 1), "mfu": round(mfu, 4),
        "loss": round(lv, 4), "device": str(jax.devices()[0]),
    }
    if family != "gpt":
        row["family"] = family
        row["kv_heads"] = cfg.kv_heads
    if experts:
        row["experts"] = experts
        row["dropless"] = dropless
    if remat:
        # hardware FLOP utilization incl. the recompute forward —
        # reported SEPARATELY so mfu stays comparable across rows
        row["hfu"] = round(tps * (fpt * 4 // 3) / peak, 4)
    print(json.dumps(row), flush=True)


# GPT-125M (h768 L12) and a 1.3B-width single-chip config (h2048 L12 —
# the full 24-layer 1.3B wants multi-chip; the 12-layer variant isolates
# per-layer perf at the 1.3B width on one chip)
DEFAULT_MATRIX = [
    dict(batch=8, seq=1024, steps=10, remat=False, flash=False),
    dict(batch=8, seq=1024, steps=10, remat=False, flash=True),
    # flash backend head-to-head at the headline shape (VERDICT r4 item 1:
    # "done = flash >= dense-XLA at s1024 AND s2048"): the in-tree kernel
    # vs the platform-tuned Pallas kernels shipped inside JAX
    dict(batch=8, seq=1024, steps=10, remat=False, flash="ours"),
    dict(batch=8, seq=1024, steps=10, remat=False, flash="jax_flash"),
    dict(batch=8, seq=1024, steps=10, remat=False, flash="splash"),
    dict(batch=4, seq=2048, steps=5, remat=True, flash="ours",
         h=2048, L=12, V=51200),
    dict(batch=4, seq=2048, steps=5, remat=True, flash="jax_flash",
         h=2048, L=12, V=51200),
    dict(batch=4, seq=2048, steps=5, remat=True, flash="splash",
         h=2048, L=12, V=51200),
    dict(batch=8, seq=1024, steps=10, remat=False, flash=None),  # auto
    dict(batch=8, seq=1024, steps=10, remat=True, flash=True),
    dict(batch=8, seq=1024, steps=10, remat=False, flash=True,
         autotune=True),
    # b16 without remat: dense residuals outgrow HBM — the auto policy
    # must flip to flash here (dense OOM'd in the round-4 seize)
    dict(batch=16, seq=1024, steps=10, remat=False, flash=None),
    dict(batch=4, seq=2048, steps=5, remat=True, flash=True,
         h=2048, L=12, V=51200),
    dict(batch=4, seq=2048, steps=5, remat=True, flash=True,
         h=2048, L=12, V=51200, autotune=True),
    dict(batch=4, seq=2048, steps=5, remat=True, flash=False,
         h=2048, L=12, V=51200),
    # selective remat: save projection outputs, recompute attention —
    # targets the measured 25% full-remat tax (HFU 0.378 vs MFU 0.284)
    dict(batch=4, seq=2048, steps=5, remat=True, flash=False,
         h=2048, L=12, V=51200, remat_policy="dots"),
    dict(batch=8, seq=1024, steps=10, remat=True, flash=None,
         remat_policy="dots"),
    # GPT-MoE (E8 top-2, single chip): scatter routing + batched expert
    # einsums; MFU basis = ACTIVE params (top-k experts + router)
    dict(batch=8, seq=1024, steps=10, remat=False, flash=None, experts=8),
    # dropless (sorted ragged_dot / Mosaic grouped-matmul) vs the
    # fixed-capacity dispatch buffers, same model
    dict(batch=8, seq=1024, steps=10, remat=False, flash=None, experts=8,
         dropless=True),
    # llama GQA at 1.3B width: flash (native grouped KV) vs dense
    # (jnp.repeat'ed kv) — the GQA tradeoff the GPT rows can't see
    dict(batch=4, seq=2048, steps=5, remat=True, flash=True, h=2048,
         L=12, V=32000, family="llama", kv_heads=8),
    dict(batch=4, seq=2048, steps=5, remat=True, flash=False, h=2048,
         L=12, V=32000, family="llama", kv_heads=8),
    # GQA backend head-to-head: ours (native grouped KV) vs splash (MQA
    # form) vs jax_flash (KV repeat)
    dict(batch=4, seq=2048, steps=5, remat=True, flash="splash", h=2048,
         L=12, V=32000, family="llama", kv_heads=8),
    dict(batch=4, seq=2048, steps=5, remat=True, flash="jax_flash", h=2048,
         L=12, V=32000, family="llama", kv_heads=8),
]


if __name__ == "__main__":
    if len(sys.argv) > 1:
        import ast
        matrix = ast.literal_eval(sys.argv[1])
    else:
        matrix = DEFAULT_MATRIX
    for args in matrix:
        try:
            run(**args)
        except Exception as e:  # noqa: BLE001 — keep sweeping
            print(json.dumps({"args": {k: str(v) for k, v in args.items()},
                              "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
