"""Functional image transforms over numpy HWC uint8/float arrays.

Reference: python/paddle/vision/transforms/functional*.py.  TPU-native
stance: transforms run on the HOST data path (numpy), feeding the device
pipeline — keeping per-sample branching/resizing off the accelerator, which
only sees fixed-shape batches.
"""

from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "to_tensor", "normalize", "resize", "pad", "crop", "center_crop",
    "hflip", "vflip", "rotate", "adjust_brightness", "adjust_contrast",
    "adjust_saturation", "adjust_hue", "to_grayscale", "erase",
]


def _as_float(img):
    if img.dtype == np.uint8:
        return img.astype(np.float32) / 255.0
    return img.astype(np.float32)


def to_tensor(img, data_format="CHW"):
    """HWC (or HW) uint8/float image -> float32 array in CHW, scaled to [0,1]."""
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    arr = _as_float(arr)
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (arr - mean) / std


def _interp_resize(img, h, w, interpolation="bilinear"):
    """Pure-numpy separable resize (nearest / bilinear)."""
    src_h, src_w = img.shape[:2]
    if interpolation == "nearest":
        ys = np.clip(np.round(np.arange(h) * src_h / h).astype(int), 0, src_h - 1)
        xs = np.clip(np.round(np.arange(w) * src_w / w).astype(int), 0, src_w - 1)
        return img[ys][:, xs]
    # bilinear with align_corners=False convention
    y = (np.arange(h) + 0.5) * src_h / h - 0.5
    x = (np.arange(w) + 0.5) * src_w / w - 0.5
    y0 = np.clip(np.floor(y).astype(int), 0, src_h - 1)
    y1 = np.clip(y0 + 1, 0, src_h - 1)
    x0 = np.clip(np.floor(x).astype(int), 0, src_w - 1)
    x1 = np.clip(x0 + 1, 0, src_w - 1)
    wy = np.clip(y - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(x - x0, 0.0, 1.0)[None, :, None]
    im = _as_float(img if img.ndim == 3 else img[:, :, None])
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if img.ndim == 2:
        out = out[:, :, 0]
    if img.dtype == np.uint8:
        out = np.clip(out * 255.0, 0, 255).astype(np.uint8)
    return out


def resize(img, size, interpolation="bilinear"):
    img = np.asarray(img)
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        # resize shorter side to `size`, keep aspect
        if h < w:
            nh, nw = int(size), int(size * w / h)
        else:
            nh, nw = int(size * h / w), int(size)
    else:
        nh, nw = size
    return _interp_resize(img, nh, nw, interpolation)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = np.asarray(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    pads = [(pt, pb), (pl, pr)] + [(0, 0)] * (img.ndim - 2)
    if padding_mode == "constant":
        return np.pad(img, pads, mode="constant", constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, pads, mode=mode)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = np.asarray(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    return np.asarray(img)[:, ::-1]


def vflip(img):
    return np.asarray(img)[::-1]


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by `angle` degrees counter-clockwise ("nearest" or
    "bilinear"; `expand=True` grows the canvas to hold the whole image)."""
    img = np.asarray(img)
    h, w = img.shape[:2]
    theta = np.deg2rad(angle)
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else \
        (center[1], center[0])
    if expand:
        # output canvas bounding the rotated image; keep rotation center
        oh = int(np.ceil(abs(h * np.cos(theta)) + abs(w * np.sin(theta))))
        ow = int(np.ceil(abs(h * np.sin(theta)) + abs(w * np.cos(theta))))
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    # inverse-map output coords back to source: rotate by -theta (CCW
    # convention, positive angle = counter-clockwise like PIL/reference)
    ys = (yy - ocy) * np.cos(theta) + (xx - ocx) * np.sin(theta) + cy
    xs = -(yy - ocy) * np.sin(theta) + (xx - ocx) * np.cos(theta) + cx
    out_shape = (oh, ow) + img.shape[2:]
    if interpolation == "nearest":
        yi = np.round(ys).astype(int)
        xi = np.round(xs).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = np.full(out_shape, fill, dtype=img.dtype)
        out[valid] = img[yi[valid], xi[valid]]
        return out
    if interpolation != "bilinear":
        raise ValueError(
            f"unsupported rotate interpolation {interpolation!r}; "
            "use 'nearest' or 'bilinear'")
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    _exp = (Ellipsis,) + (None,) * (img.ndim - 2)
    wy = (ys - y0)[_exp]
    wx = (xs - x0)[_exp]
    acc = np.zeros(out_shape, np.float64)
    wsum = np.zeros((oh, ow) + (1,) * (img.ndim - 2), np.float64)
    for dy, dx, wgt in ((0, 0, (1 - wy) * (1 - wx)), (0, 1, (1 - wy) * wx),
                        (1, 0, wy * (1 - wx)), (1, 1, wy * wx)):
        yi, xi = y0 + dy, x0 + dx
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        vv = valid[_exp]
        acc += np.where(vv, img[np.clip(yi, 0, h - 1),
                                np.clip(xi, 0, w - 1)], 0) * wgt * vv
        wsum += wgt * vv
    covered = wsum > 1e-9
    out = np.where(covered, acc / np.maximum(wsum, 1e-9), fill)
    if np.issubdtype(img.dtype, np.integer):
        out = np.rint(out)
    return out.astype(img.dtype)


def adjust_brightness(img, factor):
    arr = _as_float(np.asarray(img)) * factor
    return _restore(arr, img)


def adjust_contrast(img, factor):
    arr = _as_float(np.asarray(img))
    mean = arr.mean()
    return _restore((arr - mean) * factor + mean, img)


def adjust_saturation(img, factor):
    arr = _as_float(np.asarray(img))
    gray = arr.mean(axis=-1, keepdims=True)
    return _restore(gray + (arr - gray) * factor, img)


def adjust_hue(img, factor):
    """Shift hue by `factor` (in [-0.5, 0.5]) via HSV roundtrip."""
    arr = _as_float(np.asarray(img))
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr.max(-1)
    minc = arr.min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0)
    dz = np.maximum(delta, 1e-12)
    rc = (maxc - r) / dz
    gc = (maxc - g) / dz
    bc = (maxc - b) / dz
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = (h + factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(int) % 6
    conds = [i == k for k in range(6)]
    r2 = np.select(conds, [v, q, p, p, t, v])
    g2 = np.select(conds, [t, v, v, q, p, p])
    b2 = np.select(conds, [p, p, t, v, v, q])
    return _restore(np.stack([r2, g2, b2], axis=-1), img)


def to_grayscale(img, num_output_channels=1):
    arr = _as_float(np.asarray(img))
    gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1] + 0.114 * arr[..., 2])
    gray = gray[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    return _restore(gray, img)


def erase(img, i, j, h, w, v, inplace=False):
    arr = np.asarray(img)
    if not inplace:
        arr = arr.copy()
    arr[i:i + h, j:j + w] = v
    return arr


def _restore(arr, ref):
    ref = np.asarray(ref)
    if ref.dtype == np.uint8:
        return np.clip(arr * 255.0, 0, 255).astype(np.uint8)
    return arr.astype(ref.dtype)


def _affine_matrix(angle, translate, scale, shear, center):
    """Inverse affine matrix mapping OUTPUT coords to INPUT coords
    (torchvision/paddle convention: parameters describe the forward
    transform about `center`)."""
    import math as _m
    rot = _m.radians(angle)
    sx, sy = (_m.radians(s) for s in shear)
    cx, cy = center
    tx, ty = translate
    # forward: T(center) R S Sh T(-center) + translate; build inverse
    a = _m.cos(rot - sy) / _m.cos(sy)
    b = -_m.cos(rot - sy) * _m.tan(sx) / _m.cos(sy) - _m.sin(rot)
    c = _m.sin(rot - sy) / _m.cos(sy)
    d = -_m.sin(rot - sy) * _m.tan(sx) / _m.cos(sy) + _m.cos(rot)
    m = np.array([[a * scale, b * scale, 0.0],
                  [c * scale, d * scale, 0.0]], np.float64)
    m[0, 2] = cx + tx - (m[0, 0] * cx + m[0, 1] * cy)
    m[1, 2] = cy + ty - (m[1, 0] * cx + m[1, 1] * cy)
    # invert the 2x3 affine
    det = m[0, 0] * m[1, 1] - m[0, 1] * m[1, 0]
    inv = np.array([[m[1, 1], -m[0, 1], 0.0],
                    [-m[1, 0], m[0, 0], 0.0]], np.float64) / det
    inv[0, 2] = -(inv[0, 0] * m[0, 2] + inv[0, 1] * m[1, 2])
    inv[1, 2] = -(inv[1, 0] * m[0, 2] + inv[1, 1] * m[1, 2])
    return inv


def _sample_hw(img, map_fn, interpolation="nearest", fill=0):
    """Warp an HWC numpy image by sampling input at map_fn(out coords)."""
    arr = np.asarray(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    h, w = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    sx, sy = map_fn(xs.astype(np.float64), ys.astype(np.float64))
    if interpolation == "nearest":
        ix = np.round(sx).astype(np.int64)
        iy = np.round(sy).astype(np.int64)
        valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        out = np.full_like(arr, fill)
        out[valid] = arr[iy[valid], ix[valid]]
    else:                                   # bilinear
        x0 = np.floor(sx); y0 = np.floor(sy)
        out = np.zeros(arr.shape, np.float64)
        wsum = np.zeros((h, w, 1), np.float64)
        for dy in (0, 1):
            for dx in (0, 1):
                ix = (x0 + dx).astype(np.int64)
                iy = (y0 + dy).astype(np.int64)
                wgt = (1 - np.abs(sx - ix)) * (1 - np.abs(sy - iy))
                valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
                wv = np.where(valid, wgt, 0.0)[:, :, None]
                ixc = np.clip(ix, 0, w - 1); iyc = np.clip(iy, 0, h - 1)
                out += arr[iyc, ixc] * wv
                wsum += wv
        out = np.where(wsum > 0, out / np.maximum(wsum, 1e-12), fill)
        out = out.astype(arr.dtype)
    return out[:, :, 0] if squeeze else out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine warp (reference vision/transforms/functional.py affine)."""
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if isinstance(shear, (int, float)):
        shear = (float(shear), 0.0)
    inv = _affine_matrix(angle, translate, scale, shear, center)

    def map_fn(xs, ys):
        sx = inv[0, 0] * xs + inv[0, 1] * ys + inv[0, 2]
        sy = inv[1, 0] * xs + inv[1, 1] * ys + inv[1, 2]
        return sx, sy

    return _sample_hw(img, map_fn, interpolation, fill)


def _perspective_coeffs(startpoints, endpoints):
    """Solve the 8 perspective coefficients mapping endpoints→startpoints
    (the inverse warp, torchvision convention)."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        b.extend([sx, sy])
    coeffs = np.linalg.solve(np.asarray(a, np.float64),
                             np.asarray(b, np.float64))
    return coeffs


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp (reference functional.py perspective)."""
    c = _perspective_coeffs(startpoints, endpoints)

    def map_fn(xs, ys):
        den = c[6] * xs + c[7] * ys + 1.0
        sx = (c[0] * xs + c[1] * ys + c[2]) / den
        sy = (c[3] * xs + c[4] * ys + c[5]) / den
        return sx, sy

    return _sample_hw(img, map_fn, interpolation, fill)
