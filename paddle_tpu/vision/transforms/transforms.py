"""Composable transform classes (reference:
python/paddle/vision/transforms/transforms.py — BaseTransform :139,
Compose :77)."""

from __future__ import annotations

import numbers
import random

import numpy as np

from . import functional as F

__all__ = [
    "BaseTransform", "Compose", "ToTensor", "Normalize", "Resize",
    "CenterCrop", "RandomCrop", "RandomResizedCrop", "RandomHorizontalFlip",
    "RandomVerticalFlip", "RandomRotation", "ColorJitter", "Grayscale",
    "Pad", "RandomErasing", "Transpose", "BrightnessTransform",
    "ContrastTransform", "SaturationTransform", "HueTransform",
    "RandomAffine", "RandomPerspective",
]


class BaseTransform:
    """A transform applied per-sample; call with an image, get an image."""

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img

    def __repr__(self):
        inner = ", ".join(repr(t) for t in self.transforms)
        return f"Compose([{inner}])"


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant"):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        img = np.asarray(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = F.pad(img, (max(0, tw - w), max(0, th - h)), self.fill,
                        self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return F.crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                cropped = F.crop(img, top, left, ch, cw)
                return F.resize(cropped, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(h, w)), self.size,
                        self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * aspect)))
            ew = int(round(np.sqrt(target / aspect)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                return F.erase(img, i, j, eh, ew, self.value, self.inplace)
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        return np.transpose(img, self.order)


class RandomAffine(BaseTransform):
    """Random affine transform (reference transforms.py RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        import numpy as _np
        h, w = _np.asarray(img).shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale) if self.scale else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, numbers.Number):
            sh = (random.uniform(-self.shear, self.shear), 0.0)
        elif len(self.shear) == 2:
            sh = (random.uniform(self.shear[0], self.shear[1]), 0.0)
        else:
            sh = (random.uniform(self.shear[0], self.shear[1]),
                  random.uniform(self.shear[2], self.shear[3]))
        return F.affine(img, angle, (tx, ty), sc, sh, self.interpolation,
                        self.fill, self.center)


class RandomPerspective(BaseTransform):
    """Random perspective (reference transforms.py RandomPerspective)."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        import numpy as _np
        if random.random() >= self.prob:
            return img
        h, w = _np.asarray(img).shape[:2]
        d = self.distortion_scale
        half_h, half_w = h // 2, w // 2
        tl = (random.randint(0, int(d * half_w)),
              random.randint(0, int(d * half_h)))
        tr = (w - 1 - random.randint(0, int(d * half_w)),
              random.randint(0, int(d * half_h)))
        br = (w - 1 - random.randint(0, int(d * half_w)),
              h - 1 - random.randint(0, int(d * half_h)))
        bl = (random.randint(0, int(d * half_w)),
              h - 1 - random.randint(0, int(d * half_h)))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        return F.perspective(img, start, [tl, tr, br, bl],
                             self.interpolation, self.fill)
