from .mnist import MNIST, FashionMNIST  # noqa: F401
from .cifar import Cifar10, Cifar100  # noqa: F401
from .fake import FakeData  # noqa: F401
from .folder import DatasetFolder, Flowers, ImageFolder, VOC2012  # noqa: F401
