"""Folder-based datasets + downloadable-zoo tails (reference:
python/paddle/vision/datasets/{folder,flowers,voc2012}.py).

DatasetFolder/ImageFolder are fully local; Flowers/VOC2012 read an
already-downloaded data_file (this build has zero egress — download=True
raises with instructions, matching the capability minus the network
fetch)."""

from __future__ import annotations

import os
import tarfile
from typing import Callable, List, Optional, Tuple

import numpy as np

from ...io.dataset import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


def has_valid_extension(filename: str, extensions=IMG_EXTENSIONS) -> bool:
    return filename.lower().endswith(tuple(extensions))


class DatasetFolder(Dataset):
    """root/class_x/xxx.ext layout (reference folder.py DatasetFolder)."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS, transform=None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise FileNotFoundError(f"no class folders in {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        check = is_valid_file or (
            lambda p: has_valid_extension(p, extensions))
        self.samples: List[Tuple[str, int]] = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    if check(path):
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise FileNotFoundError(
                f"no valid files under {root!r} (extensions {extensions})")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target


class ImageFolder(Dataset):
    """Flat/recursive image folder WITHOUT labels (reference folder.py
    ImageFolder — returns [img] lists)."""

    def __init__(self, root: str, loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS, transform=None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.loader = loader or _pil_loader
        self.transform = transform
        check = is_valid_file or (
            lambda p: has_valid_extension(p, extensions))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                if check(path):
                    self.samples.append(path)
        if not self.samples:
            raise FileNotFoundError(f"no valid files under {root!r}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]


_NO_EGRESS = ("this build has no network egress; pass data_file= pointing "
              "at the already-downloaded archive (reference dataset URL in "
              "the class docstring)")


class Flowers(Dataset):
    """Oxford 102 Flowers (reference flowers.py; data from
    https://www.robots.ox.ac.uk/~vgg/data/flowers/102/).  Requires local
    ``data_file``/``label_file``/``setid_file`` archives."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend=None):
        if data_file is None:
            raise ValueError(f"Flowers: {_NO_EGRESS}")
        import scipy.io as sio  # scipy is available with jax
        self.transform = transform
        labels = sio.loadmat(label_file)["labels"][0]
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key][0]
        self._tar = tarfile.open(data_file)
        self._names = {os.path.basename(m.name): m
                       for m in self._tar.getmembers() if m.isfile()}
        self._labels = labels

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        img_idx = int(self.indexes[idx])
        name = f"image_{img_idx:05d}.jpg"
        data = self._tar.extractfile(self._names[name]).read()
        img = Image.open(_io.BytesIO(data)).convert("RGB")
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self._labels[img_idx - 1] - 1)


class VOC2012(Dataset):
    """PASCAL VOC2012 segmentation (reference voc2012.py).  Requires the
    local VOCtrainval archive via ``data_file``."""

    _LIST = {"train": "ImageSets/Segmentation/train.txt",
             "valid": "ImageSets/Segmentation/val.txt",
             "test": "ImageSets/Segmentation/val.txt"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None:
            raise ValueError(f"VOC2012: {_NO_EGRESS}")
        self.transform = transform
        self._tar = tarfile.open(data_file)
        members = {m.name: m for m in self._tar.getmembers()}
        root = next(n.split("/")[0] for n in members)
        lst = self._tar.extractfile(
            members[f"{root}/VOCdevkit/VOC2012/{self._LIST[mode]}"]) \
            if f"{root}/VOCdevkit/VOC2012/{self._LIST[mode]}" in members \
            else None
        if lst is None:
            # archives differ in nesting; search for the list file
            cand = [n for n in members if n.endswith(self._LIST[mode])]
            lst = self._tar.extractfile(members[cand[0]])
            root = cand[0][: -len(self._LIST[mode])].rstrip("/")
        else:
            root = f"{root}/VOCdevkit/VOC2012"
        self._root = root
        self._members = members
        self.ids = [l.strip() for l in
                    lst.read().decode().splitlines() if l.strip()]

    def __len__(self):
        return len(self.ids)

    def __getitem__(self, idx):
        import io as _io
        from PIL import Image
        name = self.ids[idx]
        img = Image.open(_io.BytesIO(self._tar.extractfile(
            self._members[f"{self._root}/JPEGImages/{name}.jpg"]).read()))
        lab = Image.open(_io.BytesIO(self._tar.extractfile(
            self._members[
                f"{self._root}/SegmentationClass/{name}.png"]).read()))
        img = np.asarray(img.convert("RGB"))
        lab = np.asarray(lab)
        if self.transform is not None:
            img = self.transform(img)
        return img, lab
