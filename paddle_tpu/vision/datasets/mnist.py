"""MNIST / FashionMNIST (reference: python/paddle/vision/datasets/mnist.py).

No network egress in this environment: ``image_path``/``label_path`` must
point at local IDX files (the standard ubyte.gz format); ``download=True``
raises.  For tests use ``paddle_tpu.vision.datasets.FakeData``.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST"]


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic, = struct.unpack(">I", data[:4])
    ndim = magic & 0xFF
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    arr = np.frombuffer(data, dtype=np.uint8, offset=4 + 4 * ndim)
    return arr.reshape(dims)


class MNIST(Dataset):
    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        if download and (image_path is None or label_path is None):
            raise NotImplementedError(
                f"{self.NAME}: no network egress — pass local "
                "image_path/label_path (IDX ubyte[.gz] files)")
        if image_path is None or label_path is None:
            base = os.environ.get("PADDLE_TPU_DATA_HOME",
                                  os.path.expanduser("~/.cache/paddle_tpu"))
            tag = "train" if mode == "train" else "t10k"
            image_path = os.path.join(base, self.NAME,
                                      f"{tag}-images-idx3-ubyte.gz")
            label_path = os.path.join(base, self.NAME,
                                      f"{tag}-labels-idx1-ubyte.gz")
        self.mode = mode
        self.transform = transform
        self.backend = backend
        self.images = _read_idx(image_path)            # [N, 28, 28] uint8
        self.labels = _read_idx(label_path).astype(np.int64)  # [N]

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None]  # CHW
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
