"""CIFAR-10/100 (reference: python/paddle/vision/datasets/cifar.py).

Reads the standard python-pickle tar archives from a local path
(``data_file``); ``download=True`` raises (no egress).
"""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from ...io.dataset import Dataset

__all__ = ["Cifar10", "Cifar100"]


class Cifar10(Dataset):
    NAME = "cifar-10"
    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        if download and data_file is None:
            raise NotImplementedError(
                f"{self.NAME}: no network egress — pass a local data_file "
                "(the cifar python .tar.gz archive)")
        if data_file is None:
            base = os.environ.get("PADDLE_TPU_DATA_HOME",
                                  os.path.expanduser("~/.cache/paddle_tpu"))
            data_file = os.path.join(base, f"{self.NAME}-python.tar.gz")
        self.mode = mode
        self.transform = transform
        members = self._train_members if mode == "train" else self._test_members
        imgs, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                if os.path.basename(m.name) in members:
                    batch = pickle.load(tf.extractfile(m), encoding="bytes")
                    imgs.append(batch[b"data"])
                    labels.extend(batch[self._label_key])
        self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(np.transpose(img, (1, 2, 0)))
        else:
            img = img.astype(np.float32)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NAME = "cifar-100"
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"
