"""ShuffleNetV2 family (reference: python/paddle/vision/models/shufflenetv2.py)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.container import Sequential
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.activation import ReLU, Swish
from ...nn.layer.pooling import MaxPool2D, AdaptiveAvgPool2D
from ...nn.layer.common import Linear
from ...ops.api import concat, reshape, transpose, split

__all__ = ["ShuffleNetV2", "channel_shuffle",
           "shufflenet_v2_x0_25", "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
           "shufflenet_v2_swish"]


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


def _conv_bn(cin, cout, kernel, stride=1, padding=0, groups=1, act=ReLU):
    layers = [Conv2D(cin, cout, kernel, stride=stride, padding=padding,
                     groups=groups, bias_attr=False), BatchNorm2D(cout)]
    if act is not None:
        layers.append(act())
    return Sequential(*layers)


class InvertedResidual(Layer):
    """Stride-1 unit: split, transform right half, concat + shuffle."""

    def __init__(self, channels, act=ReLU):
        super().__init__()
        half = channels // 2
        self.branch = Sequential(
            _conv_bn(half, half, 1, act=act),
            _conv_bn(half, half, 3, stride=1, padding=1, groups=half, act=None),
            _conv_bn(half, half, 1, act=act))

    def forward(self, x):
        x1, x2 = split(x, 2, axis=1)
        out = concat([x1, self.branch(x2)], axis=1)
        return channel_shuffle(out, 2)


class InvertedResidualDS(Layer):
    """Stride-2 (downsample) unit: both branches transform full input."""

    def __init__(self, cin, cout, act=ReLU):
        super().__init__()
        half = cout // 2
        self.branch1 = Sequential(
            _conv_bn(cin, cin, 3, stride=2, padding=1, groups=cin, act=None),
            _conv_bn(cin, half, 1, act=act))
        self.branch2 = Sequential(
            _conv_bn(cin, half, 1, act=act),
            _conv_bn(half, half, 3, stride=2, padding=1, groups=half, act=None),
            _conv_bn(half, half, 1, act=act))

    def forward(self, x):
        out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_stage_repeats = [4, 8, 4]
_stage_out = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        act_layer = Swish if act == "swish" else ReLU
        out_c = _stage_out[scale]
        self.conv1 = _conv_bn(3, out_c[0], 3, stride=2, padding=1,
                              act=act_layer)
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        stages = []
        cin = out_c[0]
        for i, reps in enumerate(_stage_repeats):
            cout = out_c[i + 1]
            stages.append(InvertedResidualDS(cin, cout, act=act_layer))
            for _ in range(reps - 1):
                stages.append(InvertedResidual(cout, act=act_layer))
            cin = cout
        self.stages = Sequential(*stages)
        self.conv_last = _conv_bn(cin, out_c[-1], 1, act=act_layer)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(out_c[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv_last(self.stages(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
