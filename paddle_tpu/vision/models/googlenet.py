"""GoogLeNet / Inception-v1 (reference: python/paddle/vision/models/googlenet.py).

Returns (main, aux1, aux2) logits like the reference.
"""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.container import Sequential
from ...nn.layer.conv import Conv2D
from ...nn.layer.activation import ReLU
from ...nn.layer.pooling import MaxPool2D, AvgPool2D, AdaptiveAvgPool2D
from ...nn.layer.common import Linear, Dropout
from ...ops.api import concat

__all__ = ["GoogLeNet", "googlenet"]


class ConvLayer(Layer):
    def __init__(self, cin, cout, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, kernel, stride=stride, padding=padding)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.conv(x))


class Inception(Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.branch1 = ConvLayer(cin, c1, 1)
        self.branch2 = Sequential(ConvLayer(cin, c3r, 1),
                                  ConvLayer(c3r, c3, 3, padding=1))
        self.branch3 = Sequential(ConvLayer(cin, c5r, 1),
                                  ConvLayer(c5r, c5, 5, padding=2))
        self.branch4 = Sequential(MaxPool2D(kernel_size=3, stride=1, padding=1),
                                  ConvLayer(cin, proj, 1))

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x),
                       self.branch4(x)], axis=1)


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            ConvLayer(3, 64, 7, stride=2, padding=3),
            MaxPool2D(kernel_size=3, stride=2, padding=1),
            ConvLayer(64, 64, 1),
            ConvLayer(64, 192, 3, padding=1),
            MaxPool2D(kernel_size=3, stride=2, padding=1))
        self.inc3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.inc4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.inc5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.4)
            self.fc = Linear(1024, num_classes)
            # aux classifiers (train-time deep supervision)
            self.aux_pool = AvgPool2D(5, stride=3)
            self.aux1_conv = ConvLayer(512, 128, 1)
            self.aux1_fc1 = Linear(128 * 4 * 4, 1024)
            self.aux1_fc2 = Linear(1024, num_classes)
            self.aux2_conv = ConvLayer(528, 128, 1)
            self.aux2_fc1 = Linear(128 * 4 * 4, 1024)
            self.aux2_fc2 = Linear(1024, num_classes)
            self.aux_relu = ReLU()
            self.aux_dropout = Dropout(0.7)

    def _aux(self, x, conv, fc1, fc2):
        x = conv(self.aux_pool(x))
        x = x.flatten(1)
        x = self.aux_relu(fc1(x))
        return fc2(self.aux_dropout(x))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        aux1_in = x
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2_in = x
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            out = self.fc(self.dropout(x.flatten(1)))
            aux1 = self._aux(aux1_in, self.aux1_conv, self.aux1_fc1,
                             self.aux1_fc2)
            aux2 = self._aux(aux2_in, self.aux2_conv, self.aux2_fc1,
                             self.aux2_fc2)
            return out, aux1, aux2
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return GoogLeNet(**kwargs)
