"""DenseNet family (reference: python/paddle/vision/models/densenet.py)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.container import Sequential
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.activation import ReLU
from ...nn.layer.pooling import MaxPool2D, AvgPool2D, AdaptiveAvgPool2D
from ...nn.layer.common import Dropout, Linear
from ...ops.api import concat

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_cfgs = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class DenseLayer(Layer):
    def __init__(self, cin, growth_rate, bn_size, dropout=0.0):
        super().__init__()
        self.norm1 = BatchNorm2D(cin)
        self.relu = ReLU()
        self.conv1 = Conv2D(cin, bn_size * growth_rate, 1, bias_attr=False)
        self.norm2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                            bias_attr=False)
        self.dropout = Dropout(dropout) if dropout > 0 else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class DenseBlock(Layer):
    def __init__(self, num_layers, cin, growth_rate, bn_size, dropout=0.0):
        super().__init__()
        self.block = Sequential(*[
            DenseLayer(cin + i * growth_rate, growth_rate, bn_size, dropout)
            for i in range(num_layers)])

    def forward(self, x):
        return self.block(x)


class TransitionLayer(Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm = BatchNorm2D(cin)
        self.relu = ReLU()
        self.conv = Conv2D(cin, cout, 1, bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        num_init_features, growth_rate, block_cfg = _cfgs[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            Conv2D(3, num_init_features, 7, stride=2, padding=3,
                   bias_attr=False),
            BatchNorm2D(num_init_features), ReLU(),
            MaxPool2D(kernel_size=3, stride=2, padding=1))
        blocks = []
        nf = num_init_features
        for i, n in enumerate(block_cfg):
            blocks.append(DenseBlock(n, nf, growth_rate, bn_size, dropout))
            nf += n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(TransitionLayer(nf, nf // 2))
                nf //= 2
        self.blocks = Sequential(*blocks)
        self.final_norm = BatchNorm2D(nf)
        self.final_relu = ReLU()
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Linear(nf, num_classes)

    def forward(self, x):
        x = self.final_relu(self.final_norm(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
