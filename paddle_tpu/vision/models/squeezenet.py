"""SqueezeNet v1.0 / v1.1 (reference: python/paddle/vision/models/squeezenet.py)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.container import Sequential
from ...nn.layer.conv import Conv2D
from ...nn.layer.activation import ReLU
from ...nn.layer.pooling import MaxPool2D, AdaptiveAvgPool2D
from ...nn.layer.common import Dropout
from ...ops.api import concat

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class MakeFire(Layer):
    def __init__(self, cin, squeeze, expand1x1, expand3x3):
        super().__init__()
        self.squeeze = Conv2D(cin, squeeze, 1)
        self.relu = ReLU()
        self.expand1x1 = Conv2D(squeeze, expand1x1, 1)
        self.expand3x3 = Conv2D(squeeze, expand3x3, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        a = self.relu(self.expand1x1(x))
        b = self.relu(self.expand3x3(x))
        return concat([a, b], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(),
                MaxPool2D(kernel_size=3, stride=2),
                MakeFire(96, 16, 64, 64),
                MakeFire(128, 16, 64, 64),
                MakeFire(128, 32, 128, 128),
                MaxPool2D(kernel_size=3, stride=2),
                MakeFire(256, 32, 128, 128),
                MakeFire(256, 48, 192, 192),
                MakeFire(384, 48, 192, 192),
                MakeFire(384, 64, 256, 256),
                MaxPool2D(kernel_size=3, stride=2),
                MakeFire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2, padding=1), ReLU(),
                MaxPool2D(kernel_size=3, stride=2),
                MakeFire(64, 16, 64, 64),
                MakeFire(128, 16, 64, 64),
                MaxPool2D(kernel_size=3, stride=2),
                MakeFire(128, 32, 128, 128),
                MakeFire(256, 32, 128, 128),
                MaxPool2D(kernel_size=3, stride=2),
                MakeFire(256, 48, 192, 192),
                MakeFire(384, 48, 192, 192),
                MakeFire(384, 64, 256, 256),
                MakeFire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5),
                Conv2D(512, num_classes, 1), ReLU())
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.avgpool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return SqueezeNet("1.1", **kwargs)
