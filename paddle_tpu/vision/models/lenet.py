"""LeNet (reference: python/paddle/vision/models/lenet.py) — canonical home
is paddle_tpu.models.lenet; re-exported here for vision-zoo parity."""

from ...models.lenet import LeNet  # noqa: F401

__all__ = ["LeNet"]
