"""Model utilities (reference: python/paddle/vision/models/_utils.py)."""

from __future__ import annotations

from collections import OrderedDict

from ...nn.layer.layers import Layer


class IntermediateLayerGetter(Layer):
    """Wrap a model to return an OrderedDict of named intermediate outputs.

    ``return_layers`` maps child-layer name -> output key.  Only works for
    models whose children are used sequentially in forward order (same
    contract as the reference).
    """

    def __init__(self, model: Layer, return_layers: dict):
        if not set(return_layers).issubset(
                name for name, _ in model.named_children()):
            raise ValueError("return_layers are not present in model")
        super().__init__()
        remaining = dict(return_layers)
        self.return_layers = dict(return_layers)
        self._layer_names = []
        for name, module in model.named_children():
            self.add_sublayer(name, module)
            self._layer_names.append(name)
            if name in remaining:
                del remaining[name]
            if not remaining:
                break

    def forward(self, x):
        out = OrderedDict()
        for name in self._layer_names:
            x = getattr(self, name)(x)
            if name in self.return_layers:
                out[self.return_layers[name]] = x
        return out
