"""MobileNetV3 Large/Small (reference: python/paddle/vision/models/mobilenetv3.py)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.container import Sequential
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.activation import ReLU, Hardswish, Hardsigmoid
from ...nn.layer.pooling import AdaptiveAvgPool2D
from ...nn.layer.common import Linear, Dropout, Identity
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Large", "MobileNetV3Small",
           "mobilenet_v3_large", "mobilenet_v3_small"]


class SqueezeExcitation(Layer):
    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(input_channels, squeeze_channels, 1)
        self.fc2 = Conv2D(squeeze_channels, input_channels, 1)
        self.relu = ReLU()
        self.hsig = Hardsigmoid()

    def forward(self, x):
        scale = self.avgpool(x)
        scale = self.relu(self.fc1(scale))
        scale = self.hsig(self.fc2(scale))
        return x * scale


class ConvNormActivation(Sequential):
    def __init__(self, cin, cout, kernel=3, stride=1, groups=1,
                 activation=ReLU):
        padding = (kernel - 1) // 2
        layers = [Conv2D(cin, cout, kernel, stride=stride, padding=padding,
                         groups=groups, bias_attr=False),
                  BatchNorm2D(cout)]
        if activation is not None:
            layers.append(activation())
        super().__init__(*layers)


class InvertedResidualConfig:
    def __init__(self, cin, kernel, expanded, cout, use_se, activation,
                 stride, scale=1.0):
        self.input_channels = _make_divisible(cin * scale)
        self.kernel = kernel
        self.expanded_channels = _make_divisible(expanded * scale)
        self.output_channels = _make_divisible(cout * scale)
        self.use_se = use_se
        self.use_hs = activation == "HS"
        self.stride = stride


class InvertedResidual(Layer):
    def __init__(self, cfg: InvertedResidualConfig):
        super().__init__()
        self.use_res = cfg.stride == 1 and cfg.input_channels == cfg.output_channels
        act = Hardswish if cfg.use_hs else ReLU
        layers = []
        if cfg.expanded_channels != cfg.input_channels:
            layers.append(ConvNormActivation(
                cfg.input_channels, cfg.expanded_channels, kernel=1,
                activation=act))
        layers.append(ConvNormActivation(
            cfg.expanded_channels, cfg.expanded_channels, kernel=cfg.kernel,
            stride=cfg.stride, groups=cfg.expanded_channels, activation=act))
        if cfg.use_se:
            layers.append(SqueezeExcitation(
                cfg.expanded_channels,
                _make_divisible(cfg.expanded_channels // 4)))
        layers.append(ConvNormActivation(
            cfg.expanded_channels, cfg.output_channels, kernel=1,
            activation=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class MobileNetV3(Layer):
    def __init__(self, configs, last_channel, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        firstconv_out = configs[0].input_channels
        layers = [ConvNormActivation(3, firstconv_out, kernel=3, stride=2,
                                     activation=Hardswish)]
        layers += [InvertedResidual(c) for c in configs]
        lastconv_in = configs[-1].output_channels
        lastconv_out = 6 * lastconv_in
        layers.append(ConvNormActivation(lastconv_in, lastconv_out, kernel=1,
                                         activation=Hardswish))
        self.features = Sequential(*layers)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(lastconv_out, last_channel), Hardswish(),
                Dropout(0.2), Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        C = InvertedResidualConfig
        configs = [
            C(16, 3, 16, 16, False, "RE", 1, scale),
            C(16, 3, 64, 24, False, "RE", 2, scale),
            C(24, 3, 72, 24, False, "RE", 1, scale),
            C(24, 5, 72, 40, True, "RE", 2, scale),
            C(40, 5, 120, 40, True, "RE", 1, scale),
            C(40, 5, 120, 40, True, "RE", 1, scale),
            C(40, 3, 240, 80, False, "HS", 2, scale),
            C(80, 3, 200, 80, False, "HS", 1, scale),
            C(80, 3, 184, 80, False, "HS", 1, scale),
            C(80, 3, 184, 80, False, "HS", 1, scale),
            C(80, 3, 480, 112, True, "HS", 1, scale),
            C(112, 3, 672, 112, True, "HS", 1, scale),
            C(112, 5, 672, 160, True, "HS", 2, scale),
            C(160, 5, 960, 160, True, "HS", 1, scale),
            C(160, 5, 960, 160, True, "HS", 1, scale)]
        last_channel = _make_divisible(1280 * scale)
        super().__init__(configs, last_channel, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        C = InvertedResidualConfig
        configs = [
            C(16, 3, 16, 16, True, "RE", 2, scale),
            C(16, 3, 72, 24, False, "RE", 2, scale),
            C(24, 3, 88, 24, False, "RE", 1, scale),
            C(24, 5, 96, 40, True, "HS", 2, scale),
            C(40, 5, 240, 40, True, "HS", 1, scale),
            C(40, 5, 240, 40, True, "HS", 1, scale),
            C(40, 5, 120, 48, True, "HS", 1, scale),
            C(48, 5, 144, 48, True, "HS", 1, scale),
            C(48, 5, 288, 96, True, "HS", 2, scale),
            C(96, 5, 576, 96, True, "HS", 1, scale),
            C(96, 5, 576, 96, True, "HS", 1, scale)]
        last_channel = _make_divisible(1024 * scale)
        super().__init__(configs, last_channel, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return MobileNetV3Small(scale=scale, **kwargs)
