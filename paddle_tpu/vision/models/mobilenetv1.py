"""MobileNetV1 (reference: python/paddle/vision/models/mobilenetv1.py).

Depthwise convs map to XLA ``feature_group_count``; on TPU these lower to
efficient fused windows, no special kernel needed.
"""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.container import Sequential
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.activation import ReLU
from ...nn.layer.pooling import AdaptiveAvgPool2D
from ...nn.layer.common import Linear

__all__ = ["MobileNetV1", "mobilenet_v1"]


class ConvBNLayer(Layer):
    def __init__(self, cin, cout, kernel, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, kernel, stride=stride, padding=padding,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class DepthwiseSeparable(Layer):
    def __init__(self, cin, cout1, cout2, stride, scale):
        super().__init__()
        self.dw = ConvBNLayer(int(cin * scale), int(cout1 * scale), 3,
                              stride=stride, padding=1,
                              groups=int(cin * scale))
        self.pw = ConvBNLayer(int(cout1 * scale), int(cout2 * scale), 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [  # cin, c1, c2, stride
            (32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
            (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
            (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
            (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
            (1024, 1024, 1024, 1)]
        self.blocks = Sequential(*[
            DepthwiseSeparable(cin, c1, c2, s, scale) for cin, c1, c2, s in cfg])
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return MobileNetV1(scale=scale, **kwargs)
