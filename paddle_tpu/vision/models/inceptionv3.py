"""Inception-v3 (reference: python/paddle/vision/models/inceptionv3.py)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.container import Sequential
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.activation import ReLU
from ...nn.layer.pooling import MaxPool2D, AvgPool2D, AdaptiveAvgPool2D
from ...nn.layer.common import Linear, Dropout
from ...ops.api import concat

__all__ = ["InceptionV3", "inception_v3"]


class ConvBN(Layer):
    def __init__(self, cin, cout, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, kernel, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class InceptionStem(Layer):
    def __init__(self):
        super().__init__()
        self.conv1 = ConvBN(3, 32, 3, stride=2)
        self.conv2 = ConvBN(32, 32, 3)
        self.conv3 = ConvBN(32, 64, 3, padding=1)
        self.pool1 = MaxPool2D(kernel_size=3, stride=2)
        self.conv4 = ConvBN(64, 80, 1)
        self.conv5 = ConvBN(80, 192, 3)
        self.pool2 = MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        x = self.pool1(self.conv3(self.conv2(self.conv1(x))))
        return self.pool2(self.conv5(self.conv4(x)))


class InceptionA(Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = ConvBN(cin, 64, 1)
        self.b5 = Sequential(ConvBN(cin, 48, 1), ConvBN(48, 64, 5, padding=2))
        self.b3 = Sequential(ConvBN(cin, 64, 1), ConvBN(64, 96, 3, padding=1),
                             ConvBN(96, 96, 3, padding=1))
        self.pool = AvgPool2D(3, stride=1, padding=1)
        self.bp = ConvBN(cin, pool_features, 1)

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x),
                       self.bp(self.pool(x))], axis=1)


class InceptionB(Layer):
    """Grid reduction 35x35 -> 17x17."""

    def __init__(self, cin):
        super().__init__()
        self.b3 = ConvBN(cin, 384, 3, stride=2)
        self.b3dbl = Sequential(ConvBN(cin, 64, 1), ConvBN(64, 96, 3, padding=1),
                                ConvBN(96, 96, 3, stride=2))
        self.pool = MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3dbl(x), self.pool(x)], axis=1)


class InceptionC(Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = ConvBN(cin, 192, 1)
        self.b7 = Sequential(
            ConvBN(cin, c7, 1),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7dbl = Sequential(
            ConvBN(cin, c7, 1),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.pool = AvgPool2D(3, stride=1, padding=1)
        self.bp = ConvBN(cin, 192, 1)

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7dbl(x),
                       self.bp(self.pool(x))], axis=1)


class InceptionD(Layer):
    """Grid reduction 17x17 -> 8x8."""

    def __init__(self, cin):
        super().__init__()
        self.b3 = Sequential(ConvBN(cin, 192, 1), ConvBN(192, 320, 3, stride=2))
        self.b7x3 = Sequential(
            ConvBN(cin, 192, 1),
            ConvBN(192, 192, (1, 7), padding=(0, 3)),
            ConvBN(192, 192, (7, 1), padding=(3, 0)),
            ConvBN(192, 192, 3, stride=2))
        self.pool = MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7x3(x), self.pool(x)], axis=1)


class InceptionE(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = ConvBN(cin, 320, 1)
        self.b3_stem = ConvBN(cin, 384, 1)
        self.b3_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3dbl_stem = Sequential(ConvBN(cin, 448, 1),
                                     ConvBN(448, 384, 3, padding=1))
        self.b3dbl_a = ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3dbl_b = ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.pool = AvgPool2D(3, stride=1, padding=1)
        self.bp = ConvBN(cin, 192, 1)

    def forward(self, x):
        b3 = self.b3_stem(x)
        b3 = concat([self.b3_a(b3), self.b3_b(b3)], axis=1)
        b3dbl = self.b3dbl_stem(x)
        b3dbl = concat([self.b3dbl_a(b3dbl), self.b3dbl_b(b3dbl)], axis=1)
        return concat([self.b1(x), b3, b3dbl, self.bp(self.pool(x))], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = InceptionStem()
        self.blocks = Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.dropout(x.flatten(1))
            x = self.fc(x)
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return InceptionV3(**kwargs)
