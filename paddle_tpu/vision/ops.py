"""paddle.vision.ops parity (reference python/paddle/vision/ops.py):
detection ops + their Layer wrappers, deformable conv, FPN utilities,
image-file ops.

Most functional ops live in the registry (ops/impl/detection.py);
this module adds deform_conv2d (bilinear tap sampling — the TPU
formulation of the deformable-conv gather), distribute_fpn_proposals,
matrix_nms, read_file/decode_jpeg, and the Layer classes.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import run_op
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops.api import (box_coder, generate_proposals, nms, prior_box,
                       psroi_pool, roi_align, roi_pool, yolo_box,
                       yolo_loss)

__all__ = ["yolo_loss", "yolo_box", "prior_box", "box_coder",
           "deform_conv2d", "DeformConv2D", "distribute_fpn_proposals",
           "generate_proposals", "matrix_nms", "read_file", "decode_jpeg",
           "roi_pool", "RoIPool", "psroi_pool", "PSRoIPool", "roi_align",
           "RoIAlign", "nms"]


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference vision/ops.py deform_conv2d →
    deformable_conv kernel; Dai et al. 2017 / Zhu et al. 2019).

    TPU formulation: for every output location and kernel tap, bilinearly
    sample the input at (base + learned offset) — one fused gather —
    then contract taps×channels with the weight on the MXU.
    x [N,Cin,H,W]; offset [N, 2*G_d*kh*kw, Ho, Wo];
    mask [N, G_d*kh*kw, Ho, Wo] (v2) or None (v1)."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation

    def impl(xv, off, w, b, m):
        n, cin, h, wd = xv.shape
        cout, cin_g, kh, kw = w.shape
        ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        wo = (wd + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        gd = deformable_groups
        # base sampling positions per output loc per tap
        ys = jnp.arange(ho) * sh - ph
        xs = jnp.arange(wo) * sw - pw
        ky = jnp.arange(kh) * dh
        kx = jnp.arange(kw) * dw
        base_y = ys[:, None, None, None] + ky[None, None, :, None]
        base_x = xs[None, :, None, None] + kx[None, None, None, :]
        base_y = jnp.broadcast_to(base_y, (ho, wo, kh, kw))
        base_x = jnp.broadcast_to(base_x, (ho, wo, kh, kw))
        off = off.reshape(n, gd, kh * kw, 2, ho, wo)
        off_y = off[:, :, :, 0].transpose(0, 1, 3, 4, 2).reshape(
            n, gd, ho, wo, kh, kw)
        off_x = off[:, :, :, 1].transpose(0, 1, 3, 4, 2).reshape(
            n, gd, ho, wo, kh, kw)
        py = base_y[None, None] + off_y          # [N,gd,Ho,Wo,kh,kw]
        px = base_x[None, None] + off_x

        def bilinear(img, yy, xx):
            """img [C,H,W]; yy/xx [...]: zero outside."""
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy = yy - y0
            wx = xx - x0
            out = 0.0
            for ddy, ddx in ((0, 0), (0, 1), (1, 0), (1, 1)):
                iy = (y0 + ddy).astype(jnp.int32)
                ix = (x0 + ddx).astype(jnp.int32)
                valid = ((iy >= 0) & (iy < h) & (ix >= 0) & (ix < wd))
                iyc = jnp.clip(iy, 0, h - 1)
                ixc = jnp.clip(ix, 0, wd - 1)
                v = img[:, iyc, ixc]             # [C, ...]
                wgt = ((wy if ddy else 1 - wy) *
                       (wx if ddx else 1 - wx)) * valid
                out = out + v * wgt[None]
            return out

        cpg = cin // gd                           # channels per deform group

        def one_sample(img, yy, xx):
            # img [Cin,H,W]; yy/xx [gd,Ho,Wo,kh,kw]
            groups_out = []
            for g in range(gd):
                sub = img[g * cpg:(g + 1) * cpg]
                groups_out.append(bilinear(sub, yy[g], xx[g]))
            return jnp.concatenate(groups_out, 0)  # [Cin,Ho,Wo,kh,kw]

        sampled = jax.vmap(one_sample)(xv, py, px)  # [N,Cin,Ho,Wo,kh,kw]
        if m is not None:
            mm = m.reshape(n, gd, kh, kw, ho, wo).transpose(
                0, 1, 4, 5, 2, 3)
            mm = jnp.repeat(mm, cpg, axis=1)
            sampled = sampled * mm
        # contract (Cin_g, kh, kw) per output channel group
        sampled = sampled.reshape(n, groups, cin // groups, ho, wo, kh, kw)
        wg = w.reshape(groups, cout // groups, cin_g, kh, kw)
        out = jnp.einsum("ngchwyx,gocyx->ngohw", sampled, wg)
        out = out.reshape(n, cout, ho, wo)
        if b is not None:
            out = out + b[None, :, None, None]
        return out.astype(xv.dtype)

    return run_op("deform_conv2d", impl, (x, offset, weight, bias, mask),
                  {})


class DeformConv2D(Layer):
    """Layer wrapper (reference vision/ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size,) * 2 if isinstance(kernel_size, int) else \
            tuple(kernel_size)
        from .. import create_parameter
        self.weight = create_parameter(
            [out_channels, in_channels // groups, *ks], "float32")
        self.bias = None if bias_attr is False else create_parameter(
            [out_channels], "float32", is_bias=True)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference vision/ops.py
    distribute_fpn_proposals; FPN paper eq.1).  Eager (data-dependent
    output sizes), like the reference's CPU path."""
    rois = np.asarray(getattr(fpn_rois, "_value", fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 0.0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, idxs = [], []
    for level in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == level)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
    order = np.concatenate(idxs) if idxs else np.zeros((0,), np.int64)
    restore = np.argsort(order)
    rois_num_per = None
    if rois_num is not None:
        rois_num_per = [Tensor(jnp.asarray(np.asarray([len(i)])))
                        for i in idxs]
    return outs, Tensor(jnp.asarray(restore.reshape(-1, 1))), rois_num_per


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference vision/ops.py matrix_nms; SOLOv2 paper):
    decay scores by pairwise IoU instead of hard suppression."""
    bb = np.asarray(getattr(bboxes, "_value", bboxes))
    sc = np.asarray(getattr(scores, "_value", scores))
    out_boxes, out_idx, out_num = [], [], []
    B, C, M = sc.shape
    for b in range(B):
        cand = []
        for c in range(C):
            if c == background_label:
                continue
            keep = np.nonzero(sc[b, c] > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[b, c, keep])][:nms_top_k]
            boxes = bb[b, order]
            s = sc[b, c, order].copy()
            # pairwise IoU (upper triangle)
            x1 = np.maximum(boxes[:, None, 0], boxes[None, :, 0])
            y1 = np.maximum(boxes[:, None, 1], boxes[None, :, 1])
            x2 = np.minimum(boxes[:, None, 2], boxes[None, :, 2])
            y2 = np.minimum(boxes[:, None, 3], boxes[None, :, 3])
            add = 0.0 if normalized else 1.0
            inter = np.clip(x2 - x1 + add, 0, None) * \
                np.clip(y2 - y1 + add, 0, None)
            area = (boxes[:, 2] - boxes[:, 0] + add) * \
                (boxes[:, 3] - boxes[:, 1] + add)
            iou = inter / np.maximum(area[:, None] + area[None, :] - inter,
                                     1e-10)
            iou = np.triu(iou, 1)
            comp = iou.max(axis=0)              # max IoU with higher-scored
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - comp[None, :] ** 2)
                               / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) / np.maximum(1 - comp[None, :],
                                                1e-10)).min(axis=0)
            s = s * decay
            ok = s > post_threshold
            for i in np.nonzero(ok)[0]:
                cand.append((float(s[i]), c, boxes[i], order[i]))
        cand.sort(key=lambda t: -t[0])
        cand = cand[:keep_top_k]
        for scv, c, box, oi in cand:
            out_boxes.append([c, scv, *box.tolist()])
            out_idx.append(b * M + oi)
        out_num.append(len(cand))
    boxes_t = Tensor(jnp.asarray(np.asarray(out_boxes, np.float32)
                                 .reshape(-1, 6)))
    rets = [boxes_t]
    if return_rois_num:
        rets.append(Tensor(jnp.asarray(np.asarray(out_num, np.int32))))
    if return_index:
        rets.append(Tensor(jnp.asarray(np.asarray(out_idx, np.int64)
                                       .reshape(-1, 1))))
    return tuple(rets) if len(rets) > 1 else boxes_t


def read_file(filename, name=None):
    """Read file bytes as a uint8 tensor (reference vision/ops.py
    read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor (reference decode_jpeg → nvjpeg; PIL
    here)."""
    import io
    from PIL import Image
    data = bytes(np.asarray(getattr(x, "_value", x)).astype(np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._args[0], self._args[1])


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num, sampling_ratio=-1, aligned=True):
        return roi_align(x, boxes, boxes_num, self._args[0], self._args[1],
                         sampling_ratio, aligned)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._args = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._args[0],
                          self._args[1])
