"""Vision toolkit: model zoo, transforms, datasets.

Reference: python/paddle/vision (models/, transforms/, datasets/).
"""

from . import ops  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend: str) -> None:
    """Select the image-decoding backend for datasets (reference:
    vision/image.py set_image_backend; 'pil' or 'cv2')."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"backend must be 'pil'/'cv2'/'tensor', got {backend!r}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (reference vision/image.py image_load)."""
    backend = backend or _image_backend
    if backend == "cv2":
        raise ImportError("cv2 backend not available in this build; use "
                          "'pil'")
    from PIL import Image
    img = Image.open(path)
    if backend == "tensor":
        import numpy as np
        from ..core.tensor import Tensor
        return Tensor(np.asarray(img))
    return img
