"""Automatic SParsity (reference python/paddle/incubate/asp/*): 2:4
structured sparsity — mask computation, model pruning, and mask
re-application after optimizer steps.

TPU note: 2:4 sparse tensor cores are a GPU feature; on TPU the masks
still deliver model compression + the training-time regularization
semantics, computed with the same best-2-of-4 magnitude rule."""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "check_sparsity", "create_mask"]

_EXCLUDED: set = set()
_MASKS: Dict[str, jnp.ndarray] = {}


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def calculate_density(x) -> float:
    arr = np.asarray(getattr(x, "_value", x))
    return float((arr != 0).sum() / arr.size)


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """Best-n-of-m magnitude mask along the last dim (reference
    asp/utils.py create_mask mask_1d)."""
    arr = np.asarray(getattr(tensor, "_value", tensor))
    flat = arr.reshape(-1, m) if arr.size % m == 0 else None
    if flat is None:
        return np.ones_like(arr)
    idx = np.argsort(-np.abs(flat), axis=1)[:, :n]
    mask = np.zeros_like(flat)
    np.put_along_axis(mask, idx, 1.0, axis=1)
    return mask.reshape(arr.shape)


def check_sparsity(tensor, func_name="check_1d", n=2, m=4) -> bool:
    arr = np.asarray(getattr(tensor, "_value", tensor))
    if arr.size % m:
        return False
    flat = (arr.reshape(-1, m) != 0).sum(axis=1)
    return bool((flat <= n).all())


def _prunable(layer):
    from ..nn import Conv2D, Linear
    return isinstance(layer, (Linear, Conv2D))


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every prunable layer's weight (reference
    asp/asp.py prune_model)."""
    masks = {}
    for name, sub in model.named_sublayers(include_self=True):
        if not _prunable(sub):
            continue
        w = getattr(sub, "weight", None)
        if w is None or w.name in _EXCLUDED:
            continue
        mask = create_mask(w, mask_algo, n, m)
        w._value = jnp.asarray(np.asarray(w._value) * mask)
        masks[w.name] = jnp.asarray(mask)
    if with_mask:
        _MASKS.update(masks)
    return masks


def decorate(optimizer):
    """Wrap the optimizer so masks re-apply after each step (reference
    asp/asp.py decorate → OptimizerWithSparsityGuarantee)."""

    class _ASPOptimizer:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def step(self):
            self._inner.step()
            for p in self._inner._parameters or []:
                mask = _MASKS.get(p.name)
                if mask is not None:
                    p._value = p._value * mask

        def minimize(self, loss, **kw):
            loss.backward()
            self.step()
            return None, []

    return _ASPOptimizer(optimizer)
