"""paddle.incubate surface tail (reference python/paddle/incubate/
__init__.py __all__): graph ops (aliases of paddle.geometric), segment
reductions, fused softmax-mask, LookAhead/ModelAverage optimizers,
identity_loss."""

from __future__ import annotations

import jax.numpy as jnp

from ..geometric import (reindex_graph, sample_neighbors, segment_max,
                         segment_mean, segment_min, segment_sum,
                         send_u_recv)
from ..optimizer.optimizer import Optimizer

__all__ = ["graph_send_recv", "graph_reindex", "graph_sample_neighbors",
           "graph_khop_sampler", "segment_sum", "segment_mean",
           "segment_max", "segment_min", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "identity_loss",
           "LookAhead", "ModelAverage"]


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Deprecated incubate name for paddle.geometric.send_u_recv."""
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop sampling (reference incubate/operators/graph_khop_sampler):
    chained sample_neighbors + reindex over each hop."""
    import numpy as np

    from ..core.tensor import Tensor
    nodes = input_nodes
    all_edges_src, all_edges_dst = [], []
    frontier = nodes
    for k in sample_sizes:
        neigh, counts = sample_neighbors(row, colptr, frontier,
                                         sample_size=k)
        nv = np.asarray(neigh._value)
        cv = np.asarray(counts._value)
        fv = np.asarray(frontier._value)
        dst = np.repeat(fv, cv)
        all_edges_src.append(nv)
        all_edges_dst.append(dst)
        frontier = Tensor(jnp.asarray(
            np.unique(np.concatenate([fv, nv]))))
    src = np.concatenate(all_edges_src) if all_edges_src else \
        np.zeros(0, np.int64)
    dst = np.concatenate(all_edges_dst) if all_edges_dst else \
        np.zeros(0, np.int64)
    uniq, inv = np.unique(np.concatenate([np.asarray(
        input_nodes._value), src, dst]), return_inverse=True)
    n_in = len(np.asarray(input_nodes._value))
    src_r = inv[n_in:n_in + len(src)]
    dst_r = inv[n_in + len(src):]
    out = (Tensor(jnp.asarray(uniq)), Tensor(jnp.asarray(src_r)),
           Tensor(jnp.asarray(dst_r)))
    if return_eids:
        return out + (Tensor(jnp.zeros(len(src_r), jnp.int64)),)
    return out


def softmax_mask_fuse(x, mask, name=None):
    """Fused softmax(x + mask) (reference incubate/operators/
    softmax_mask_fuse → Pallas fused_softmax_mask)."""
    from ..ops.pallas.fused import fused_softmax_mask
    from ..core.dispatch import run_op

    def impl(xv, mv):
        return fused_softmax_mask(xv, mv)

    return run_op("softmax_mask_fuse", impl, (x, mask), {})


def softmax_mask_fuse_upper_triangle(x):
    """Fused causal-masked softmax (reference softmax_mask_fuse_upper_
    triangle: adds -inf above the diagonal — the GPT attention mask)."""
    from ..core.dispatch import run_op

    def impl(xv):
        import jax
        s_q, s_k = xv.shape[-2], xv.shape[-1]
        tri = jnp.tril(jnp.ones((s_q, s_k), bool))
        logits = jnp.where(tri, xv.astype(jnp.float32), -1e30)
        return jax.nn.softmax(logits, axis=-1).astype(xv.dtype)

    return run_op("softmax_mask_fuse_upper_triangle", impl, (x,), {})


def identity_loss(x, reduction="none"):
    """Reference incubate identity_loss (IPU host-loss marker): reduce
    and mark as the loss value."""
    from ..ops import api
    if reduction in ("none", 2):
        return api.assign(x)
    if reduction in ("mean", 1):
        return api.mean(x)
    if reduction in ("sum", 0):
        return api.sum(x)
    raise ValueError(f"bad reduction {reduction!r}")


class LookAhead(Optimizer):
    """Lookahead optimizer wrapper (reference incubate/optimizer/
    lookahead.py; Zhang et al. 2019): every k steps pull fast weights
    toward slow weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self._alpha = float(alpha)
        self._k = int(k)
        self._slow = {}
        self._steps = 0

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)

    def step(self):
        self.inner_optimizer.step()
        self._steps += 1
        params = self.inner_optimizer._parameters or []
        if self._steps % self._k == 0:
            for p in params:
                slow = self._slow.get(p.name)
                if slow is None:
                    slow = p._value
                slow = slow + self._alpha * (p._value - slow)
                self._slow[p.name] = slow
                p._value = slow

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, []

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)


class ModelAverage(Optimizer):
    """Model averaging (reference incubate/optimizer/modelaverage.py):
    running average of parameters; apply()/restore() swap it in for
    evaluation."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(0.0, parameters, None, None, False)
        self._sum = {}
        self._cnt = 0
        self._backup = {}

    def step(self):
        self._cnt += 1
        for p in self._parameters or []:
            cur = self._sum.get(p.name)
            self._sum[p.name] = p._value if cur is None else cur + p._value

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = {p.name: p._value
                            for p in self._parameters or []}
            for p in self._parameters or []:
                if p.name in self._sum and self._cnt:
                    p._value = self._sum[p.name] / self._cnt
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        for p in self._parameters or []:
            if p.name in self._backup:
                p._value = self._backup[p.name]
        self._backup = {}
