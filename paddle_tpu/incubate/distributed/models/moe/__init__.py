"""Mixture-of-experts (expert parallelism) — reference surface
python/paddle/incubate/distributed/models/moe."""

from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .gating import compute_capacity, gshard_aux_loss, topk_capacity_gating  # noqa: F401
from .moe_layer import MoELayer, expert_alltoall  # noqa: F401
from .utils import (  # noqa: F401
    limit_by_capacity, number_count, prune_gate_by_capacity, random_routing,
)
