"""Functional MoE gating — pure jnp, jit/shard_map friendly.

TPU-native replacement for the reference's gate implementations
(python/paddle/incubate/distributed/models/moe/gate/{gshard,switch,naive}_gate.py)
and their CUDA aux ops.  Instead of the reference's dynamic
global_scatter/global_gather (variable token counts per expert —
fluid/operators/collective/global_scatter_op.cu), gating here produces dense
fixed-capacity dispatch/combine tensors so the whole MoE layer is static-
shaped einsums that XLA can tile onto the MXU and auto-all_to_all when the
expert dim is mesh-sharded.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["topk_capacity_gating", "gshard_aux_loss", "compute_capacity"]


def compute_capacity(num_tokens: int, num_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    """Per-expert token slots: ceil(T * k * factor / E) (GShard recipe).
    Note the reference's gates use the looser ceil(cap_rate * T) instead —
    see NaiveGate.expert_capacity."""
    import math
    return max(math.ceil(num_tokens * top_k * capacity_factor / num_experts),
               top_k)


def gshard_aux_loss(probs: jax.Array, top1: jax.Array) -> jax.Array:
    """GShard load-balance loss: E * Σ_e mean(prob_e) * frac_tokens_e."""
    E = probs.shape[-1]
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=probs.dtype), axis=0)
    return E * jnp.sum(me * ce)


def topk_capacity_gating(
        logits: jax.Array, top_k: int, capacity: int,
        normalize: bool = True,
        second_expert_key: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k gating with per-expert capacity.

    Args:
      logits: [T, E] router logits (any float dtype; softmax in fp32).
      top_k: experts per token (1 = Switch, 2 = GShard).
      capacity: token slots per expert; overflow tokens are dropped
        (the jnp equivalent of the reference's limit_by_capacity /
        prune_gate_by_capacity kernels).
      normalize: renormalize the k gate weights to sum to 1 (GShard);
        Switch keeps the raw top-1 probability.
      second_expert_key: optional PRNG key — apply GShard's random routing:
        the 2nd expert is kept with probability 2*w2 (else dropped).

    Returns:
      combine:  [T, E, C] float — combine weights (0 where not dispatched).
      dispatch: [T, E, C] bool — dispatch mask (combine > 0).
      aux_loss: scalar load-balance loss.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    aux_loss = gshard_aux_loss(probs, jnp.argmax(probs, axis=-1))

    counts = jnp.zeros((E,), jnp.float32)        # kept tokens per expert
    remaining = probs
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    total_w = jnp.zeros((T,), jnp.float32)

    for j in range(top_k):
        idx_j = jnp.argmax(remaining, axis=-1)               # [T]
        oh = jax.nn.one_hot(idx_j, E, dtype=jnp.float32)     # [T, E]
        w_j = jnp.sum(probs * oh, axis=-1)                   # [T]
        if j == 1 and second_expert_key is not None:
            # random routing (reference utils.random_routing): keep the
            # second expert only with probability 2*w2
            keep2 = jax.random.uniform(second_expert_key, (T,)) < 2.0 * w_j
            oh = oh * keep2[:, None]
            w_j = w_j * keep2
        # position of each token within its expert's buffer, counting only
        # previously-kept tokens
        pos_j = jnp.sum((jnp.cumsum(oh, axis=0) - oh) * oh, axis=-1) \
            + jnp.sum(counts[None] * oh, axis=-1)            # [T]
        keep = (pos_j < capacity) & (jnp.sum(oh, -1) > 0)
        w_kept = w_j * keep
        loc = jax.nn.one_hot(
            jnp.clip(pos_j, 0, capacity - 1).astype(jnp.int32), capacity,
            dtype=jnp.float32)                               # [T, C]
        combine = combine + (w_kept[:, None, None] * oh[:, :, None]
                             * loc[:, None, :])
        counts = counts + jnp.sum(oh * keep[:, None], axis=0)
        total_w = total_w + w_kept
        remaining = remaining * (1.0 - oh)

    if normalize and top_k > 1:
        combine = combine / jnp.maximum(total_w, 1e-9)[:, None, None]
    dispatch = combine > 0.0
    return combine, dispatch, aux_loss
