"""Gate layers: Naive / GShard (top-2) / Switch (top-1).

Reference surface: python/paddle/incubate/distributed/models/moe/gate/
{base_gate,naive_gate,gshard_gate,switch_gate}.py.  Semantics mirrored:
per-expert capacity = ceil(cap_rate * num_tokens) with a (train, eval)
cap_rate pair (gshard_gate.py:67-68, switch_gate.py:60-61), Switch adds
uniform routing noise in [1-eps, 1+eps] to the scores during training
(switch_gate.py:52-55), GShard keeps the 2nd expert with probability 2*w2
(random routing).  Each gate owns the router linear and produces dense
(combine, dispatch, aux_loss) via :mod:`.gating` instead of index lists +
CUDA scatter kernels.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..... import nn
from .....core.rng import next_rng_key
from .gating import topk_capacity_gating

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


class BaseGate(nn.Layer):
    def __init__(self, d_model: int, num_experts: int):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self._loss = None

    def get_loss(self):
        """Aux load-balance loss of the last forward."""
        return self._loss


class NaiveGate(BaseGate):
    """Plain top-k softmax routing (naive_gate.py).

    With ``capacity=None`` the per-expert capacity defaults to
    ``ceil(2 * top_k * T / num_experts)`` — a balanced-load bound with 2x
    headroom — so combine/dispatch tensors stay O(T * E * cap) instead of
    the O(T^2 * E) a literal no-drop (cap = T) would allocate.  Pass
    ``capacity=(1.0, 1.0)`` for the reference's strict no-drop behavior.
    """

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2,
                 capacity: Optional[Tuple[float, float]] = None,
                 normalize: bool = True, random_routing: bool = False,
                 switch_eps: float = 0.0):
        super().__init__(d_model, num_experts)
        self.top_k = top_k
        self.capacity = capacity          # (train, eval) cap_rate or None
        self.normalize = normalize
        self.random_routing = random_routing
        self.switch_eps = switch_eps
        self.weight = self.create_parameter((d_model, num_experts))

    @property
    def needs_rng(self) -> bool:
        return self.random_routing or self.switch_eps > 0.0

    def expert_capacity(self, num_tokens: int) -> int:
        if self.capacity is None:
            return max(math.ceil(2.0 * self.top_k * num_tokens
                                 / self.num_experts), self.top_k)
        cap_rate = self.capacity[0 if self.training else 1]
        return max(math.ceil(cap_rate * num_tokens), self.top_k)

    def gate_impl(self, x, weight, rng_key=None):
        """Pure function: tokens [T, H] -> (combine, dispatch, aux)."""
        T = x.shape[0]
        logits = x.astype(jnp.float32) @ weight.astype(jnp.float32)
        route_key = None
        if rng_key is not None and self.training:
            noise_key, route_key = jax.random.split(rng_key)
            if self.switch_eps > 0.0:
                # switch_gate.py:52-55 — additive uniform noise in
                # [1-eps, 1+eps]
                noise = jax.random.uniform(noise_key, logits.shape) \
                    * 2.0 * self.switch_eps + 1.0 - self.switch_eps
                logits = logits + noise
            if not self.random_routing:
                route_key = None
        return topk_capacity_gating(
            logits, self.top_k, self.expert_capacity(T),
            normalize=self.normalize, second_expert_key=route_key)

    def forward(self, x):
        key = next_rng_key() if (self.needs_rng and self.training) else None
        combine, dispatch, aux = self.gate_impl(
            jnp.asarray(getattr(x, "_value", x)).reshape(-1, self.d_model),
            self.weight._value, key)
        self._loss = aux
        return combine, dispatch, aux


class GShardGate(NaiveGate):
    """Top-2 with capacity + random routing (gshard_gate.py)."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2,
                 capacity: Tuple[float, float] = (1.2, 2.4),
                 random_routing: bool = True):
        assert top_k == 2, "topk should be 2 in gshard"
        super().__init__(d_model, num_experts, top_k=top_k,
                         capacity=capacity, normalize=True,
                         random_routing=random_routing)


class SwitchGate(NaiveGate):
    """Top-1 Switch-Transformer gate with training noise (switch_gate.py)."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 1,
                 capacity: Tuple[float, float] = (1.2, 2.4),
                 switch_eps: float = 0.1):
        assert top_k == 1, "topk should be 1 in switch"
        super().__init__(d_model, num_experts, top_k=top_k,
                         capacity=capacity, normalize=False,
                         switch_eps=switch_eps)
