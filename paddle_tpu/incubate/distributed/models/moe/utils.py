"""MoE aux-op equivalents — jnp ports of the reference CUDA kernels
(number_count, limit_by_capacity, prune_gate_by_capacity, random_routing;
python/paddle/distributed/models/moe/utils.py + fluid/operators ``number_count``
etc.).  These operate on index-form routing (pre-dense-dispatch) for API
parity; the dense gating in gating.py subsumes them on the hot path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["number_count", "limit_by_capacity", "prune_gate_by_capacity",
           "random_routing"]


def _v(x):
    return jnp.asarray(getattr(x, "_value", x))


def number_count(gate_idx, upper_range: int):
    """Tokens per expert: histogram of gate_idx over [0, upper_range)."""
    g = _v(gate_idx).astype(jnp.int32)
    return jnp.sum(jax.nn.one_hot(g.reshape(-1), upper_range,
                                  dtype=jnp.int32), axis=0)


def limit_by_capacity(expert_count, capacity, n_worker: int = 1):
    """Clip per-expert counts to capacity shared across workers.

    ``expert_count`` is [n_worker * n_expert] ordered worker-major (the
    reference kernel's layout); each expert's capacity is consumed by its
    workers in order, so the total kept per expert never exceeds capacity.
    """
    ec = _v(expert_count)
    cap = _v(capacity)
    if n_worker == 1:
        return jnp.minimum(ec, cap if cap.ndim else cap[None])
    n_expert = cap.shape[0]
    per_worker = ec.reshape(n_worker, n_expert)
    used_before = jnp.cumsum(per_worker, axis=0) - per_worker
    remaining = jnp.maximum(cap[None, :] - used_before, 0)
    return jnp.minimum(per_worker, remaining).reshape(ec.shape)


def prune_gate_by_capacity(gate_idx, expert_count, n_expert: int,
                           n_worker: int = 1):
    """Set gate index to -1 for tokens beyond their expert's capacity."""
    g = _v(gate_idx).astype(jnp.int32).reshape(-1)
    cap = _v(expert_count).astype(jnp.int32)
    oh = jax.nn.one_hot(g, n_expert, dtype=jnp.int32)
    pos = jnp.sum((jnp.cumsum(oh, axis=0) - oh) * oh, axis=-1)
    keep = pos < jnp.take(cap, g)
    return jnp.where(keep, g, -1)


def random_routing(topk_idx, topk_value, prob, topk: int = 2):
    """GShard random routing: keep the 2nd expert with prob 2*w2, else -1."""
    if topk != 2:
        raise ValueError("random_routing supports topk == 2 only")
    idx = _v(topk_idx)
    val = _v(topk_value)
    p = _v(prob)
    keep = p < 2.0 * val[..., 1]
    second = jnp.where(keep, idx[..., 1], -1)
    return jnp.stack([idx[..., 0], second], axis=-1)
