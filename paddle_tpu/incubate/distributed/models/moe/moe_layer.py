"""MoELayer — mixture-of-experts FFN with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263.
The reference routes tokens with CUDA global_scatter/global_gather collectives
(variable counts per expert).  TPU-native design: gating emits dense
fixed-capacity combine/dispatch tensors (gating.py), the expert FFN is one
batched einsum over [E, C, ...], and expert parallelism is a sharding
annotation on the E dim — under jit XLA lowers the dispatch/combine einsums
to all_to_all over the mesh axis.  An explicit shard_map helper
(:func:`expert_alltoall`) covers the manual path (parity with
global_scatter/global_gather, distributed/utils/moe_utils.py).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..... import nn
from .....core.dispatch import run_op
from .gate import BaseGate, NaiveGate, SwitchGate, GShardGate  # noqa: F401

__all__ = ["MoELayer", "expert_alltoall"]

_GATES = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}


def expert_alltoall(expert_in: jax.Array, axis_name: str) -> jax.Array:
    """Manual EP dispatch inside shard_map: [E_local*ep_chunk ...] rearrange.

    Input  [E, C, H] with tokens for ALL experts (locally gathered),
    sharded call: each rank holds its local tokens' slots for every expert;
    all_to_all swaps so each rank holds ALL ranks' slots for its LOCAL
    experts: [E/ep, C*ep, H].  The inverse is the same call with split/concat
    swapped — global_scatter/global_gather parity
    (python/paddle/distributed/utils/moe_utils.py).
    """
    return lax.all_to_all(expert_in, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)


class MoELayer(nn.Layer):
    """Mixture of experts over a gated FFN bank.

    Args:
      d_model: hidden size.
      d_hidden: expert FFN inner size.
      num_experts: global expert count.
      gate: "gshard" | "switch" | "naive" or a BaseGate instance.
      top_k: experts per token (overrides the gate default).
      activation: expert nonlinearity (default gelu).
      ep_axis: optional mesh axis name — expert dim sharded over it via
        with_sharding_constraint (GSPMD inserts the all_to_alls).
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate="gshard", top_k: Optional[int] = None,
                 activation: Callable = jax.nn.gelu,
                 ep_axis: Optional[str] = None,
                 aux_coef: float = 0.0, router: str = "topk",
                 dropless: bool = False,
                 capacity_factor: float = 1.25):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        self.ep_axis = ep_axis
        # aux_coef > 0: the GShard balance loss reaches gradients via
        # inject_aux_grad (loss += aux_coef * aux per call) — in addition
        # to being surfaced on gate._loss for reference-style collection
        self.aux_coef = aux_coef
        # router/dropless (VERDICT r4 item 7 — eager parity with the
        # compiled hybrid step): "expert_choice" and dropless token-choice
        # delegate moe_impl to parallel.moe.moe_ffn_ep — the SAME pure
        # routine the compiled step runs, so eager and compiled logits
        # agree by construction.  The gate-zoo path (gshard/switch/naive
        # capacity dispatch) stays for router="topk" without dropless.
        if router not in ("topk", "expert_choice"):
            raise ValueError(f"unknown router {router!r}")
        if dropless and router != "topk":
            raise ValueError("dropless applies to token-choice routing "
                             "only (expert_choice is inherently dropless)")
        self.router = router
        self.dropless = dropless
        self.capacity_factor = capacity_factor
        if isinstance(gate, str):
            gate = _GATES[gate](d_model, num_experts,
                                **({"top_k": top_k} if top_k else {}))
        assert isinstance(gate, BaseGate)
        self.gate = gate
        self.top_k = top_k or getattr(gate, "top_k", 2)
        E, H, F = num_experts, d_model, d_hidden
        self.w1 = self.create_parameter((E, H, F))
        self.b1 = self.create_parameter((E, F), is_bias=True)
        self.w2 = self.create_parameter((E, F, H))
        self.b2 = self.create_parameter((E, H), is_bias=True)

    def _constrain(self, x):
        if self.ep_axis is None:
            return x
        from .....parallel.topology import get_topology
        try:
            mesh = get_topology().mesh
        except Exception:
            return x
        if self.ep_axis not in mesh.axis_names:
            return x
        spec = [None] * x.ndim
        spec[0] = self.ep_axis
        return lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(*spec)))

    def expert_ffn(self, expert_in, w1, b1, w2, b2):
        """[E, C, H] -> [E, C, H], batched over experts (one big MXU op)."""
        h = jnp.einsum("ech,ehf->ecf", expert_in, w1) + b1[:, None, :]
        h = self.activation(h)
        return jnp.einsum("ecf,efh->ech", h, w2) + b2[:, None, :]

    def moe_impl(self, x, gate_w, w1, b1, w2, b2, rng_key=None):
        """Pure function: x [..., H] -> (out [..., H], aux_loss)."""
        if self.router == "expert_choice" or self.dropless:
            from .....parallel.moe import moe_ffn_ep
            # local expert banks only (ep_axis's lax collectives need a
            # shard_map axis context the eager layer does not provide)
            out = moe_ffn_ep(
                x, gate_w, w1, b1, w2, b2, top_k=self.top_k,
                capacity_factor=self.capacity_factor,
                aux_coef=self.aux_coef,
                activation=self.activation, router=self.router,
                dropless=self.dropless)
            # keep the gate.get_loss() surface alive (reference
            # collection style `loss += gate.get_loss()`): dropless
            # token-choice has the same GShard balance loss as capacity
            # dispatch; expert choice is balanced by construction -> 0
            if self.dropless:
                from .gating import gshard_aux_loss
                probs = jax.nn.softmax(
                    x.reshape(-1, self.d_model).astype(jnp.float32)
                    @ gate_w.astype(jnp.float32), axis=-1)
                aux = gshard_aux_loss(probs, jnp.argmax(probs, -1))
            else:
                aux = jnp.zeros((), jnp.float32)
            return out, aux
        shape = x.shape
        tokens = x.reshape(-1, self.d_model)
        combine, dispatch, aux = self.gate.gate_impl(tokens, gate_w, rng_key)
        dtype = x.dtype
        expert_in = jnp.einsum("tec,th->ech",
                               dispatch.astype(jnp.float32),
                               tokens.astype(jnp.float32)).astype(dtype)
        expert_in = self._constrain(expert_in)
        expert_out = self.expert_ffn(expert_in, w1, b1, w2, b2)
        expert_out = self._constrain(expert_out)
        out = jnp.einsum("tec,ech->th", combine.astype(jnp.float32),
                         expert_out.astype(jnp.float32))
        out = out.reshape(shape).astype(dtype)
        if self.aux_coef:
            from .....parallel.moe import inject_aux_grad
            out = inject_aux_grad(out, aux, self.aux_coef)
        return out, aux

    def forward(self, x):
        from .....core.rng import next_rng_key
        key = (next_rng_key()
               if getattr(self.gate, "needs_rng", False) and self.training
               else None)

        def impl(x_, gw, w1, b1, w2, b2, k):
            return self.moe_impl(x_, gw, w1, b1, w2, b2, k)

        out, aux = run_op("moe_layer", impl,
                          (x, self.gate.weight, self.w1, self.b1, self.w2,
                           self.b2, key), {})
        # surface the aux loss like the reference's gate.get_loss()
        self.gate._loss = aux
        return out
