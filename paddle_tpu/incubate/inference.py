"""paddle.incubate.inference parity — the experimental predictor sugar
routes to the stable paddle_tpu.inference facade."""

from ..inference import (Config, LLMPredictor, Predictor,  # noqa: F401
                         create_llm_predictor, create_predictor)

__all__ = ["Config", "Predictor", "create_predictor", "LLMPredictor",
           "create_llm_predictor"]
