"""Fused transformer layer classes (reference python/paddle/incubate/nn/
layer/fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward,
FusedTransformerEncoderLayer, FusedMultiTransformer).

TPU-first: "fused" means one taped op whose body XLA/Pallas fuses — the
functional impls live in incubate.nn.functional (flash attention,
fused_bias_dropout_residual_layer_norm, swiglu)."""

from __future__ import annotations

import math
from typing import Optional

from ...nn import functional as F
from ...nn.attr import ParamAttr
from ...nn.layer.layers import Layer

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN attention block with fused residual+dropout+layernorm
    epilogue (reference fused_attention op semantics)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        from ...nn import initializer as I
        one = ParamAttr(initializer=I.Constant(1.0))
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr or one)
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr or one)
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ... import ops
        from ..nn import functional as IF
        x = query
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self.epsilon)
        b, s = x.shape[0], x.shape[1]
        qkv = ops.api.matmul(x, self.qkv_weight) + self.qkv_bias
        qkv = ops.api.reshape(qkv, [b, s, self.num_heads,
                                    3 * self.head_dim])
        q, k, v = ops.api.split(qkv, 3, axis=-1)
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, training=self.training)
        attn = ops.api.reshape(attn, [b, s, self.embed_dim])
        out = ops.api.matmul(attn, self.linear_weight)
        # fused epilogue: bias + dropout + residual + layernorm
        if self.normalize_before:
            out = IF.fused_dropout_add(out + self.linear_bias, residual,
                                       p=self.dropout_rate,
                                       training=self.training)
        else:
            out = IF.fused_bias_dropout_residual_layer_norm(
                out, residual, self.linear_bias, self.ln_scale,
                self.ln_bias, dropout_rate=self.dropout_rate,
                ln_epsilon=self.epsilon, training=self.training)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        from ...nn import initializer as I
        one = ParamAttr(initializer=I.Constant(1.0))
        self.ln1_scale = self.create_parameter([d_model],
                                               attr=ln1_scale_attr or one)
        self.ln1_bias = self.create_parameter([d_model],
                                              attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter([d_model],
                                               attr=ln2_scale_attr or one)
        self.ln2_bias = self.create_parameter([d_model],
                                              attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src, cache=None):
        from ... import ops
        from ..nn import functional as IF
        residual = src
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, [self.d_model], self.ln1_scale,
                             self.ln1_bias, self.epsilon)
        h = ops.api.matmul(x, self.linear1_weight)
        h = IF.fused_bias_act(h, self.linear1_bias,
                              act_method=self.activation)
        h = F.dropout(h, self.act_dropout_rate, training=self.training)
        out = ops.api.matmul(h, self.linear2_weight)
        if self.normalize_before:
            return IF.fused_dropout_add(out + self.linear2_bias, residual,
                                        p=self.dropout_rate,
                                        training=self.training)
        return IF.fused_bias_dropout_residual_layer_norm(
            out, residual, self.linear2_bias, self.ln2_scale,
            self.ln2_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward,
                 dropout_rate=0.1, activation="relu",
                 attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
