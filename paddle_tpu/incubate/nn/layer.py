"""Fused transformer layer classes (reference python/paddle/incubate/nn/
layer/fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward,
FusedTransformerEncoderLayer, FusedMultiTransformer).

TPU-first: "fused" means one taped op whose body XLA/Pallas fuses — the
functional impls live in incubate.nn.functional (flash attention,
fused_bias_dropout_residual_layer_norm, swiglu)."""

from __future__ import annotations

import math
from typing import Optional

from ...nn import functional as F
from ...nn.attr import ParamAttr
from ...nn.layer.layers import Layer

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN attention block with fused residual+dropout+layernorm
    epilogue (reference fused_attention op semantics)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True)
        from ...nn import initializer as I
        one = ParamAttr(initializer=I.Constant(1.0))
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr or one)
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr or one)
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ... import ops
        from ..nn import functional as IF
        x = query
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self.epsilon)
        b, s = x.shape[0], x.shape[1]
        qkv = ops.api.matmul(x, self.qkv_weight) + self.qkv_bias
        qkv = ops.api.reshape(qkv, [b, s, self.num_heads,
                                    3 * self.head_dim])
        q, k, v = ops.api.split(qkv, 3, axis=-1)
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, training=self.training)
        attn = ops.api.reshape(attn, [b, s, self.embed_dim])
        out = ops.api.matmul(attn, self.linear_weight)
        # fused epilogue: bias + dropout + residual + layernorm
        if self.normalize_before:
            out = IF.fused_dropout_add(out + self.linear_bias, residual,
                                       p=self.dropout_rate,
                                       training=self.training)
        else:
            out = IF.fused_bias_dropout_residual_layer_norm(
                out, residual, self.linear_bias, self.ln_scale,
                self.ln_bias, dropout_rate=self.dropout_rate,
                ln_epsilon=self.epsilon, training=self.training)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True)
        from ...nn import initializer as I
        one = ParamAttr(initializer=I.Constant(1.0))
        self.ln1_scale = self.create_parameter([d_model],
                                               attr=ln1_scale_attr or one)
        self.ln1_bias = self.create_parameter([d_model],
                                              attr=ln1_bias_attr,
                                              is_bias=True)
        self.ln2_scale = self.create_parameter([d_model],
                                               attr=ln2_scale_attr or one)
        self.ln2_bias = self.create_parameter([d_model],
                                              attr=ln2_bias_attr,
                                              is_bias=True)

    def forward(self, src, cache=None):
        from ... import ops
        from ..nn import functional as IF
        residual = src
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, [self.d_model], self.ln1_scale,
                             self.ln1_bias, self.epsilon)
        h = ops.api.matmul(x, self.linear1_weight)
        h = IF.fused_bias_act(h, self.linear1_bias,
                              act_method=self.activation)
        h = F.dropout(h, self.act_dropout_rate, training=self.training)
        out = ops.api.matmul(h, self.linear2_weight)
        if self.normalize_before:
            return IF.fused_dropout_add(out + self.linear2_bias, residual,
                                        p=self.dropout_rate,
                                        training=self.training)
        return IF.fused_bias_dropout_residual_layer_norm(
            out, residual, self.linear2_bias, self.ln2_scale,
            self.ln2_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward,
                 dropout_rate=0.1, activation="relu",
                 attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedLinear(Layer):
    """Linear whose matmul+bias is one fused op (reference
    incubate FusedLinear -> fused_gemm_epilogue)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = (None if bias_attr is False else self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True))

    def forward(self, x):
        from . import functional as IF
        return IF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self.transpose_weight)


class FusedDropout(Layer):
    """Dropout as a single taped op (reference incubate FusedDropout)."""

    def __init__(self, p=0.5, axis=None, mode="upscale_in_train",
                 name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis,
                         training=self.training, mode=self.mode)


class FusedDropoutAdd(Layer):
    """dropout(x) + y in one op (reference FusedDropoutAdd)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from . import functional as IF
        return IF.fused_dropout_add(x, y, self.p, training=self.training,
                                    mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """out = LN(residual + dropout(x + bias)) in one op (reference
    FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.p = dropout_rate
        self.epsilon = epsilon
        # bias_attr=False disables the linear bias like the reference
        self.linear_bias = (None if bias_attr is False else
                            self.create_parameter((embed_dim,),
                                                  attr=bias_attr,
                                                  is_bias=True))
        from ...nn import initializer as I
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter((embed_dim,), is_bias=True)

    def forward(self, x, residual):
        from . import functional as IF
        return IF.fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.p, ln_epsilon=self.epsilon,
            training=self.training)


class FusedEcMoe(Layer):
    """Expert-choice style fused MoE FFN (reference FusedEcMoe ->
    fused_ec_moe op; compute path = the fused ``moe`` op: dense expert
    batch gemms + gather)."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.act_type = act_type
        self.bmm0 = self.create_parameter(
            (num_experts, hidden_size, inter_size), attr=weight_attr)
        self.bias0 = self.create_parameter((num_experts, 1, inter_size),
                                           attr=bias_attr, is_bias=True)
        self.bmm1 = self.create_parameter(
            (num_experts, inter_size, hidden_size), attr=weight_attr)
        self.bias1 = self.create_parameter((num_experts, 1, hidden_size),
                                           attr=bias_attr, is_bias=True)

    def forward(self, x, gate):
        from ...ops import api
        return api.moe(x, gate, self.bmm0, self.bias0, self.bmm1,
                       self.bias1, act_type=self.act_type)


class FusedMultiTransformer(Layer):
    """Whole-stack serving transformer (reference FusedMultiTransformer):
    holds per-layer weights and drives the fused_multi_transformer op for
    prefill + cached decode."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        if num_layers < 0:
            num_layers = (len(qkv_weight_attrs)
                          if isinstance(qkv_weight_attrs, (list, tuple))
                          else 1)
        self.num_layers = num_layers
        self.dropout_rate = dropout_rate
        self.normalize_before = normalize_before
        self.activation = activation
        self.epsilon = epsilon
        self.trans_qkvw = trans_qkvw
        head_dim = embed_dim // num_heads
        from ...nn import initializer as I
        _ones = I.Constant(1.0)

        def _at(attrs, i, default=None):
            if attrs is None:
                return default
            if isinstance(attrs, (list, tuple)):
                return attrs[i]
            return attrs

        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        for i in range(num_layers):
            mk = self.create_parameter
            self.ln_scales.append(mk((embed_dim,),
                                     attr=_at(ln_scale_attrs, i),
                                     default_initializer=_ones))
            self.ln_biases.append(mk((embed_dim,),
                                     attr=_at(ln_bias_attrs, i),
                                     is_bias=True))
            self.qkv_weights.append(
                mk((3, num_heads, head_dim, embed_dim) if trans_qkvw
                   else (embed_dim, 3, num_heads, head_dim),
                   attr=_at(qkv_weight_attrs, i)))
            self.qkv_biases.append(mk((3, num_heads, head_dim),
                                      attr=_at(qkv_bias_attrs, i),
                                      is_bias=True))
            self.linear_weights.append(
                mk((embed_dim, embed_dim),
                   attr=_at(linear_weight_attrs, i)))
            self.linear_biases.append(mk((embed_dim,),
                                         attr=_at(linear_bias_attrs, i),
                                         is_bias=True))
            self.ffn_ln_scales.append(
                mk((embed_dim,), attr=_at(ffn_ln_scale_attrs, i),
                   default_initializer=_ones))
            self.ffn_ln_biases.append(mk((embed_dim,),
                                         attr=_at(ffn_ln_bias_attrs, i),
                                         is_bias=True))
            self.ffn1_weights.append(
                mk((embed_dim, dim_feedforward),
                   attr=_at(ffn1_weight_attrs, i)))
            self.ffn1_biases.append(mk((dim_feedforward,),
                                       attr=_at(ffn1_bias_attrs, i),
                                       is_bias=True))
            self.ffn2_weights.append(
                mk((dim_feedforward, embed_dim),
                   attr=_at(ffn2_weight_attrs, i)))
            self.ffn2_biases.append(mk((embed_dim,),
                                       attr=_at(ffn2_bias_attrs, i),
                                       is_bias=True))
            for name_, lst in [("ln_s", self.ln_scales),
                               ("ln_b", self.ln_biases),
                               ("qkvw", self.qkv_weights),
                               ("qkvb", self.qkv_biases),
                               ("lw", self.linear_weights),
                               ("lb", self.linear_biases),
                               ("flns", self.ffn_ln_scales),
                               ("flnb", self.ffn_ln_biases),
                               ("f1w", self.ffn1_weights),
                               ("f1b", self.ffn1_biases),
                               ("f2w", self.ffn2_weights),
                               ("f2b", self.ffn2_biases)]:
                self.add_parameter(f"{name_}_{i}", lst[i])

    def forward(self, x, attn_mask=None, caches=None, time_step=None,
                rotary_embs=None):
        from . import functional as IF
        return IF.fused_multi_transformer(
            x, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self.epsilon,
            cache_kvs=caches, time_step=time_step, attn_mask=attn_mask,
            rotary_embs=rotary_embs, activation=self.activation,
            dropout_rate=self.dropout_rate, training=self.training,
            trans_qkvw=self.trans_qkvw)


class FusedTransformer(Layer):
    """Encoder stack of FusedTransformerEncoderLayer (reference
    FusedTransformer)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation="gelu",
                 name=None):
        super().__init__()
        from ...nn.layer.container import LayerList
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout,
                activation=activation)
            for _ in range(num_encoder_layers)])

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask)
        return out


__all__ += ["FusedLinear", "FusedDropout", "FusedDropoutAdd",
            "FusedBiasDropoutResidualLayerNorm", "FusedEcMoe",
            "FusedMultiTransformer", "FusedTransformer"]
