"""Fused functional APIs (reference: python/paddle/incubate/nn/functional —
16 fused entry points).  Each dispatches to the Pallas kernel inventory."""

from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

from ....core.dispatch import run_op
from ....core.tensor import Tensor
from ....ops import pallas as _pk

__all__ = [
    "fused_rms_norm", "fused_layer_norm",
    "fused_bias_dropout_residual_layer_norm", "fused_rotary_position_embedding",
    "fused_bias_act", "fused_dropout_add", "swiglu", "fused_linear",
    "fused_linear_activation", "fused_multi_head_attention",
    "masked_multihead_attention", "fused_multi_transformer",
    "fused_conv_bn_act", "fused_adam", "fused_matmul_bias",
    "fused_feedforward", "blha_get_max_len", "block_multihead_attention",
    "variable_length_memory_efficient_attention", "fused_moe",
    "fused_ec_moe",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None):
    def impl(xv, w, nb, b, res):
        if b is not None:
            xv = xv + b
        if res is not None:
            xv = xv + res
        out = _pk.rms_norm(xv, w, epsilon)
        if nb is not None:
            out = out + nb
        return (out, xv) if res is not None else out
    return run_op("fused_rms_norm", impl,
                  (x, norm_weight, norm_bias, bias, residual), {})


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None):
    def impl(xv, w, b, bias_v, res):
        if bias_v is not None:
            xv = xv + bias_v
        if res is not None:
            xv = xv + res
        out = _pk.layer_norm(xv, w, b, epsilon)
        return (out, xv) if res is not None else out
    return run_op("fused_layer_norm", impl,
                  (x, norm_weight, norm_bias, bias, residual), {})


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=False):
    def impl(xv, res, b, w, lb):
        if b is None:
            b = jnp.zeros(xv.shape[-1], xv.dtype)
        if w is None:
            w = jnp.ones(xv.shape[-1], jnp.float32)
        if lb is None:
            lb = jnp.zeros(xv.shape[-1], jnp.float32)
        out, _ = _pk.fused_bias_dropout_residual_layer_norm(
            xv, res, b, w, lb, dropout_rate, ln_epsilon, training)
        return out
    return run_op("fused_bias_dropout_residual_ln", impl,
                  (x, residual, bias, ln_scale, ln_bias), {})


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    def impl(qv, kv, vv, sv, cv, pid):
        return _pk.fused_rope(qv, kv, vv, sv, cv, pid,
                              use_neox_rotary_style)
    return run_op("fused_rope", impl, (q, k, v, sin, cos, position_ids), {})


def fused_bias_act(x, bias, act_method="gelu"):
    return run_op("fused_bias_act",
                  lambda xv, b: _pk.fused_bias_act(xv, b, act_method),
                  (x, bias), {})


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....core.rng import next_rng_key
    import jax as _jax
    seed = _jax.random.randint(next_rng_key(), (), 0, 2 ** 31 - 1)         if training and p > 0.0 else None
    return run_op("fused_dropout_add",
                  lambda xv, yv, sd: _pk.fused_dropout_add(
                      xv, yv, p, training, seed=sd),
                  (x, y, seed), {})


def swiglu(x, y=None):
    def impl(xv, yv):
        if yv is None:
            h = xv.shape[-1] // 2
            xv, yv = xv[..., :h], xv[..., h:]
        return _pk.swiglu(xv, yv)
    return run_op("fused_swiglu", impl, (x, y), {})


def fused_linear(x, weight, bias=None, transpose_weight=False):
    def impl(xv, w, b):
        if transpose_weight:
            w = jnp.swapaxes(w, -2, -1)
        out = jnp.matmul(xv, w)
        if b is not None:
            out = out + b
        return out
    return run_op("fused_linear", impl, (x, weight, bias), {})


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    def impl(xv, w, b):
        if trans_x:
            xv = jnp.swapaxes(xv, -2, -1)
        if trans_y:
            w = jnp.swapaxes(w, -2, -1)
        return _pk.fused_bias_act(jnp.matmul(xv, w), b, activation)
    return run_op("fused_linear_activation", impl, (x, y, bias), {})


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Monolithic fused MHA block (reference
    incubate/nn/functional/fused_transformer.py / fused_attention op):
    [pre-LN →] fused QKV proj → attention → out proj → dropout →
    [+residual →] [post-LN].  qkv_weight: [3, H, D, E] (paddle layout), or
    [E, 3*E] with ``transpose_qkv_wb``.  Attention dispatches to the flash
    kernel via F.scaled_dot_product_attention."""
    from ....core.rng import next_rng_key
    from ....nn import functional as F

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention: decode with cache_kv goes through "
            "masked_multihead_attention / models.generation")
    if ring_id not in (-1, None):
        raise NotImplementedError(
            "fused_multi_head_attention: tensor-parallel ring_id is not "
            "wired; use the manual-SPMD block path (parallel/manual.py)")
    if mode != "upscale_in_train":
        raise NotImplementedError(
            f"fused_multi_head_attention: dropout mode {mode!r}")

    # rng keys are operands, not trace-time constants: run_op caches the
    # traced executable per shape, so a key drawn inside impl would bake
    # one dropout mask forever (same convention as fused_dropout_add)
    drop_key = (next_rng_key() if dropout_rate > 0.0 and training else None)

    def impl(xv, qkvw, lw, plns, plnb, lns, lnb, qkvb, lb, mask, dkey):
        B, S, E = xv.shape
        if transpose_qkv_wb:
            nh = num_heads
            qkvw_ = qkvw.reshape(E, 3, nh, E // nh)
            qkvw_ = jnp.transpose(qkvw_, (1, 2, 3, 0))
            if qkvb is not None:
                qkvb = qkvb.reshape(3, nh, E // nh)
        else:
            qkvw_ = qkvw
            nh = qkvw_.shape[1]
        hd = qkvw_.shape[2]
        y = xv
        if pre_layer_norm:
            mu = jnp.mean(y, -1, keepdims=True)
            var = jnp.var(y, -1, keepdims=True)
            y = (y - mu) * jax.lax.rsqrt(var + pre_ln_epsilon)
            if plns is not None:
                y = y * plns
            if plnb is not None:
                y = y + plnb
        qkv = jnp.einsum("bse,thde->bsthd", y, qkvw_)
        if qkvb is not None:
            qkv = qkv + qkvb[None, None]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,S,H,D]
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask,
            dropout_p=attn_dropout_rate if training else 0.0,
            is_causal=False, training=training)
        attn = jnp.asarray(attn._value if hasattr(attn, "_value") else attn)
        out = attn.reshape(B, S, nh * hd) @ lw
        if lb is not None:
            out = out + lb
        if dkey is not None:
            keep = jax.random.bernoulli(dkey, 1.0 - dropout_rate, out.shape)
            out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
        if add_residual:
            out = xv + out
        if not pre_layer_norm:
            mu = jnp.mean(out, -1, keepdims=True)
            var = jnp.var(out, -1, keepdims=True)
            out = (out - mu) * jax.lax.rsqrt(var + ln_epsilon)
            if lns is not None:
                out = out * lns
            if lnb is not None:
                out = out + lnb
        return out

    return run_op("fused_multi_head_attention", impl,
                  (x, qkv_weight, linear_weight, pre_ln_scale, pre_ln_bias,
                   ln_scale, ln_bias, qkv_bias, linear_bias, attn_mask,
                   drop_key), {})


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, qkv_out_scale=None,
                               out_shift=None, out_smooth=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Decode-step MMHA (reference
    incubate/nn/functional/masked_multihead_attention.py →
    masked_multihead_attention_kernel.cu): one token's fused QKV attends to
    a preallocated cache.  x: [B, 3*H*D]; cache_kv: [2, B, H, T_max, D].
    Returns (out [B, H*D], updated cache_kv).  Dispatches to the Pallas
    decode kernel on TPU (ops/pallas/decode_attention.py)."""
    from ....ops.pallas.decode_attention import decode_attention

    if rotary_tensor is not None and not rotary_emb_dims:
        rotary_emb_dims = 1
    if rotary_emb_dims and rotary_tensor is None:
        raise ValueError("masked_multihead_attention: rotary_emb_dims set "
                         "but rotary_tensor is None")
    if rotary_emb_dims not in (0, 1, 2):
        raise ValueError(f"rotary_emb_dims must be 0/1/2, got "
                         f"{rotary_emb_dims}")
    if beam_cache_offset is not None and cache_kv is None:
        raise ValueError("masked_multihead_attention: beam_cache_offset "
                         "requires cache_kv")
    if (out_shift is None) != (out_smooth is None):
        raise ValueError("masked_multihead_attention: out_shift and "
                         "out_smooth must be provided together (the "
                         "reference store applies (out+shift)*smooth)")
    quant_out = out_scale is not None and out_scale > 0
    if beam_cache_offset is not None:
        _bo = getattr(beam_cache_offset, "_value", beam_cache_offset)
        _ck = getattr(cache_kv, "_value", cache_kv)
        if _bo.ndim != 3 or _bo.shape[0] * _bo.shape[1] != _ck.shape[1]:
            raise ValueError(
                "beam_cache_offset must be [batch, beam_size, "
                "max_seq_len + max_dec_len] with batch*beam_size == "
                f"cache rows; got {tuple(_bo.shape)} vs cache "
                f"{tuple(_ck.shape)}")
        if _bo.shape[-1] != _ck.shape[3]:
            # the kernel reads offsets at every past position, so the
            # offset table must cover exactly the cache capacity — a
            # short table would silently zero-pad (reading beam 0's
            # cache) and a long one silently truncate
            raise ValueError(
                "beam_cache_offset last dim must equal the cache "
                f"capacity (cache_kv.shape[3] == {_ck.shape[3]}); got "
                f"{_bo.shape[-1]}")
    # capacity check must run on the CONCRETE lengths out here — inside
    # impl they are tracers under the default eager-op jit cache, and a
    # full cache would silently drop the scatter (JAX OOB semantics)
    if sequence_lengths is not None and cache_kv is not None:
        import numpy as _np
        _sl = sequence_lengths
        _sl = _sl._value if isinstance(_sl, Tensor) else _sl
        cap = (cache_kv._value if isinstance(cache_kv, Tensor)
               else cache_kv).shape[3]
        if not isinstance(_sl, jax.core.Tracer):
            mx = int(_np.max(_np.asarray(_sl)))
            if mx >= cap:
                raise ValueError(
                    f"masked_multihead_attention: cache full (length {mx} "
                    f">= capacity {cap})")

    def _apply_mmha_rope(q, k, rot, lens):
        """Reference mmha kernel rotary (masked_multihead_attention_
        kernel.cu:247-): ``rot`` packs a cos plane then a sin plane
        ([2, B, rotary_seq_len, 1, dim_head], the kernel comment's
        layout).  rotary_seq_len == 1 means the caller pre-gathered the
        row at the current position; a full table (rotary_seq_len > 1)
        is gathered here at each row's current length.  non-neox:
        interleaved per-element transform (q2i, q2i+1 rotated with
        cos/sin at those same elements); neox: half-rotation within each
        of ``rotary_emb_dims`` sections."""
        B, H, D = q.shape
        rot = rot.astype(jnp.float32)
        if rot.shape[0] != 2 or rot.size % (2 * B * D):
            raise ValueError("rotary_tensor must pack [2 (cos,sin), B, "
                             f"rotary_seq_len, 1, {D}]; got shape "
                             f"{rot.shape}")
        table = rot.reshape(2, B, -1, D)            # [2, B, S_rot, D]
        if table.shape[2] == 1:
            table = table[:, :, 0]                  # pre-gathered row
        else:                                       # gather at position
            pos = jnp.clip(lens, 0, table.shape[2] - 1)
            table = table[:, jnp.arange(B), pos]    # [2, B, D]
        cos = table[0][:, None]                     # [B, 1, D]
        sin = table[1][:, None]

        def tr(t):
            tf = t.astype(jnp.float32)
            if not use_neox_rotary_style:
                x = tf[..., 0::2]
                y = tf[..., 1::2]
                x2 = x * cos[..., 0::2] - y * sin[..., 0::2]
                y2 = y * cos[..., 1::2] + x * sin[..., 1::2]
                out = jnp.stack([x2, y2], axis=-1).reshape(B, H, D)
            else:
                last = D // rotary_emb_dims
                half = last // 2
                sec = tf.reshape(B, H, rotary_emb_dims, last)
                cs = cos.reshape(B, 1, rotary_emb_dims, last)
                sn = sin.reshape(B, 1, rotary_emb_dims, last)
                x = sec[..., :half]
                y = sec[..., half:]
                x2 = x * cs[..., :half] - y * sn[..., :half]
                y2 = y * cs[..., half:] + x * sn[..., half:]
                out = jnp.concatenate([x2, y2], -1).reshape(B, H, D)
            return out.astype(t.dtype)

        return tr(q), tr(k)

    def impl(xv, cache, b, seqlens, rot, smask, beam_off, qkv_scale,
             oshift, osmooth):
        B = xv.shape[0]
        H, T, D = cache.shape[2], cache.shape[3], cache.shape[4]
        if qkv_scale is not None:
            # int32 fused-QKV-matmul output dequantized per channel
            # (reference MMHALoad<T, int32_t>: x * dequant_scales[c],
            # scale layout [3, H, D] == the flat 3HD channel axis)
            xv = xv.astype(jnp.float32) * \
                qkv_scale.astype(jnp.float32).reshape(-1)[None, :]
        if b is not None:
            xv = xv + b
        q, k, v = (a[:, 0] for a in jnp.split(
            xv.reshape(B, 3, H, D), 3, axis=1))
        if seqlens is None:
            raise ValueError("masked_multihead_attention needs "
                             "sequence_lengths (cache fill per row)")
        lens = seqlens.reshape(B).astype(jnp.int32)
        if rot is not None:
            q, k = _apply_mmha_rope(q, k, rot, lens)
        # scatter this step's k/v at each row's current length (capacity
        # validated on the concrete lengths in the outer function)
        tpos = lens  # [B]
        bidx = jnp.arange(B)
        kc = cache[0].at[bidx, :, tpos].set(k.astype(cache.dtype))
        vc = cache[1].at[bidx, :, tpos].set(v.astype(cache.dtype))
        if smask is not None or beam_off is not None:
            # dense masked path, one fused XLA step (reference mmha_naive:
            # product + src_mask before softmax).  Beam search also lands
            # here: per past position t, row (bbi, beami) reads the cache
            # row of beam beam_off[bbi, beami, t] within its real batch
            # (kernel.cu:417-441 k_cache_batch + beam_offset indexing),
            # so KV is no longer a per-row [H, T, D] block.
            if beam_off is not None:
                bw = beam_off.shape[1]
                offT = beam_off.reshape(B, -1)[:, :T].astype(jnp.int32)
                if offT.shape[1] < T:      # offsets shorter than capacity:
                    offT = jnp.pad(offT, ((0, 0), (0, T - offT.shape[1])))
                src = (jnp.arange(B)[:, None] // bw) * bw + offT   # [B, T]
                # beam offsets cover PAST positions only (kernel.cu:423:
                # ti < tlength); the current step's K/V — scattered above
                # at each row's own length — always reads the own row
                src = src.at[jnp.arange(B), lens].set(jnp.arange(B))
                # k_eff[b, t] = kc[src[b, t], :, t]
                k_eff = kc[src, :, jnp.arange(T)[None, :]]   # [B, T, H, D]
                v_eff = vc[src, :, jnp.arange(T)[None, :]]
                kd = jnp.swapaxes(k_eff, 1, 2)               # [B, H, T, D]
                vd = jnp.swapaxes(v_eff, 1, 2)
            else:
                kd, vd = kc, vc
            scores = jnp.einsum("bhd,bhtd->bht", q.astype(jnp.float32),
                                kd.astype(jnp.float32)) * (D ** -0.5)
            if smask is not None:
                m = smask.astype(jnp.float32).reshape(B, 1, -1)
                if m.shape[-1] < T:
                    m = jnp.pad(m, ((0, 0), (0, 0), (0, T - m.shape[-1])))
                scores = scores + m[..., :T]
            valid = jnp.arange(T)[None, None, :] <= lens[:, None, None]
            scores = jnp.where(valid, scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bht,bhtd->bhd", probs,
                             vd.astype(jnp.float32))
        else:
            out = decode_attention(q, jnp.swapaxes(kc, 1, 2),
                                   jnp.swapaxes(vc, 1, 2), lens + 1)
        out = out.reshape(B, H * D)
        if oshift is not None:
            # reference MMHAStore<T, T, true>: (out + shift) * smooth,
            # per output channel
            out = (out.astype(jnp.float32)
                   + oshift.astype(jnp.float32).reshape(-1)[None, :]) \
                * osmooth.astype(jnp.float32).reshape(-1)[None, :]
        if quant_out:
            # reference QuantHelperFunc: clip(round(max_bound * scale *
            # v)) -> int8; round_type 0 = ties-to-even, 1 = half-away
            qv = quant_max_bound * out_scale * out.astype(jnp.float32)
            qv = jnp.rint(qv) if quant_round_type == 0 else \
                jnp.sign(qv) * jnp.floor(jnp.abs(qv) + 0.5)
            out = jnp.clip(qv, quant_min_bound, quant_max_bound).astype(
                jnp.int8)
        else:
            out = out.astype(cache.dtype)
        return out, jnp.stack([kc, vc])

    res = run_op("masked_multihead_attention", impl,
                 (x, cache_kv, bias, sequence_lengths, rotary_tensor,
                  src_mask, beam_cache_offset, qkv_out_scale, out_shift,
                  out_smooth), {}, differentiable=False)
    if beam_cache_offset is not None:
        # reference returns beam_cache_offset_out (inplace passthrough)
        return res[0], res[1], beam_cache_offset
    return res


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, pre_caches=None,
                            rotary_embs=None, time_step=None, attn_mask=None,
                            dropout_rate=0.0, rotary_emb_dims=0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """Whole-stack fused transformer (reference
    incubate/nn/functional/fused_transformer.py fused_multi_transformer →
    fused_multi_transformer_op.cu).  N pre/post-LN blocks in one op:
    [LN →] fused-QKV → attention (flash for context, MMHA decode-step when
    ``time_step`` is set) → out-proj → +residual → [LN →] ffn1 → act →
    ffn2 → +residual.

    The reference hand-fuses this chain into one CUDA kernel per block;
    under XLA one traced op body compiles to the same fusion, and the layer
    loop is a static Python loop so each block inlines.  Decode mode
    scatters into the caller's preallocated ``cache_kvs``
    ([2, B, H, T_max, D] per layer) and returns (out, updated_caches).

    qkv_weight layout: [3, H, D, E] when ``trans_qkvw`` (reference default)
    else [E, 3, H, D].
    """
    from ....nn import functional as F
    from ....ops.pallas.decode_attention import decode_attention

    # pre_caches (prefix-tuning prompts, [2, B, H, P, D] per layer):
    # context phase — queries attend to prefix + causal-current, and the
    # prefix KV is written into cache_kvs ahead of the context KV
    # (reference fused_multi_transformer_op.cu:199-277 cache_offset).
    # Decode phase — RE-PASS pre_caches every step (the reference API
    # shape): ``time_step`` counts context + generated tokens EXCLUDING
    # the prefix, and the write slot is time_step + P.  Omitting
    # pre_caches on decode after a prefixed context call would scatter
    # into the middle of the filled cache, so P is rederived from the
    # argument each call rather than guessed.
    pres = list(pre_caches) if pre_caches is not None else None
    if dropout_rate and training:
        raise NotImplementedError(
            "fused_multi_transformer: training-mode dropout not "
            "implemented (the op is a serving path; reference defaults "
            "dropout_rate=0)")
    decode = time_step is not None
    t_step = int(getattr(time_step, "_value", time_step)) if decode else None
    n_layers = len(qkv_weights)
    caches = list(cache_kvs) if cache_kvs is not None else None
    rot = None
    if rotary_embs is not None:
        rot = jnp.asarray(getattr(rotary_embs, "_value", rotary_embs))

    def _ln(y, s, b):
        mu = jnp.mean(y, -1, keepdims=True)
        var = jnp.var(y, -1, keepdims=True)
        y = (y - mu) * jax.lax.rsqrt(var + epsilon)
        if s is not None:
            y = y * s
        if b is not None:
            y = y + b
        return y

    def impl(xv, mask, rot, *flat):
        it = iter(flat)

        def nxt():
            return next(it)

        lns, lnb = [nxt() for _ in range(n_layers)], \
            [nxt() for _ in range(n_layers)]
        qkvw = [nxt() for _ in range(n_layers)]
        qkvb = [nxt() for _ in range(n_layers)]
        lw = [nxt() for _ in range(n_layers)]
        lb = [nxt() for _ in range(n_layers)]
        flns = [nxt() for _ in range(n_layers)]
        flnb = [nxt() for _ in range(n_layers)]
        f1w = [nxt() for _ in range(n_layers)]
        f1b = [nxt() for _ in range(n_layers)]
        f2w = [nxt() for _ in range(n_layers)]
        f2b = [nxt() for _ in range(n_layers)]
        kv = [nxt() for _ in range(n_layers)] if caches is not None else \
            [None] * n_layers
        pc = [nxt() for _ in range(n_layers)] if pres is not None else \
            [None] * n_layers

        B, S, E = xv.shape
        new_caches = []
        y = xv
        for i in range(n_layers):
            w = qkvw[i]
            if trans_qkvw:
                H, D = w.shape[1], w.shape[2]
            else:
                H, D = w.shape[2], w.shape[3]
                w = jnp.transpose(w, (1, 2, 3, 0))
            resid = y
            h = _ln(y, lns[i], lnb[i]) if pre_layer_norm else y
            qkv = jnp.einsum("bse,thde->bsthd", h, w)
            if qkvb[i] is not None:
                qkv = qkv + qkvb[i][None, None]
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,S,H,D]
            if rot is not None:
                # rotary_embs: [2, B, 1, S_max, D] (cos, sin) — reference
                # fused_multi_transformer neox-half rotation on q/k
                pos0 = t_step if decode else 0
                cos = jax.lax.dynamic_slice_in_dim(rot[0], pos0, S,
                                                   axis=2)[:, 0][:, :, None]
                sin = jax.lax.dynamic_slice_in_dim(rot[1], pos0, S,
                                                   axis=2)[:, 0][:, :, None]

                def _rot_half(t):
                    t1, t2 = jnp.split(t, 2, axis=-1)
                    return jnp.concatenate([-t2, t1], axis=-1)

                q = q * cos + _rot_half(q) * sin
                k = k * cos + _rot_half(k) * sin
            if decode:
                # cache slot = prefix length + t_step (prefix KV occupies
                # cache[:P] from the context phase); RoPE position above
                # stays t_step — prefix prompts carry no positions
                # (reference fused_multi_transformer_op.cu: out_seq_len =
                # seq + cache_offset while rotary indexes the timestep)
                P_dec = pc[i].shape[3] if pc[i] is not None else 0
                slot = t_step + P_dec
                lens = jnp.full((B,), slot, jnp.int32)
                bidx = jnp.arange(B)
                kc = kv[i][0].at[bidx, :, slot].set(k[:, 0])
                vc = kv[i][1].at[bidx, :, slot].set(v[:, 0])
                new_caches.append(jnp.stack([kc, vc]))
                attn = decode_attention(q[:, 0], jnp.swapaxes(kc, 1, 2),
                                        jnp.swapaxes(vc, 1, 2), lens + 1)
                attn = attn[:, None]                       # [B, 1, H, D]
            else:
                k_full, v_full, amask = k, v, mask
                if pc[i] is not None:
                    pk = jnp.swapaxes(pc[i][0], 1, 2)   # [B, P, H, D]
                    pv = jnp.swapaxes(pc[i][1], 1, 2)
                    P = pk.shape[1]
                    k_full = jnp.concatenate([pk.astype(k.dtype), k], 1)
                    v_full = jnp.concatenate([pv.astype(v.dtype), v], 1)
                    if amask is None:
                        # prefix always visible; causal over current
                        amask = jnp.tril(
                            jnp.ones((S, P + S), bool), P)[None, None]
                    elif amask.shape[-1] == S:
                        # caller mask sized for the current tokens only:
                        # extend with an always-visible prefix band
                        if amask.dtype == jnp.bool_:
                            band = jnp.ones(
                                (*amask.shape[:-1], P), jnp.bool_)
                        else:
                            band = jnp.zeros(
                                (*amask.shape[:-1], P), amask.dtype)
                        amask = jnp.concatenate([band, amask], -1)
                if kv[i] is not None:
                    Tfill = k_full.shape[1]
                    kc = kv[i][0].at[:, :, :Tfill].set(
                        jnp.swapaxes(k_full, 1, 2))
                    vc = kv[i][1].at[:, :, :Tfill].set(
                        jnp.swapaxes(v_full, 1, 2))
                    new_caches.append(jnp.stack([kc, vc]))
                att = F.scaled_dot_product_attention(
                    q, k_full, v_full, attn_mask=amask,
                    is_causal=amask is None, training=False)
                attn = jnp.asarray(getattr(att, "_value", att))
            out = attn.reshape(B, S, H * D) @ lw[i]
            if lb[i] is not None:
                out = out + lb[i]
            y = resid + out
            if not pre_layer_norm:
                y = _ln(y, lns[i], lnb[i])
            resid = y
            h = _ln(y, flns[i], flnb[i]) if pre_layer_norm else y
            h = h @ f1w[i]
            if f1b[i] is not None:
                h = h + f1b[i]
            h = getattr(jax.nn, activation)(h)
            h = h @ f2w[i]
            if f2b[i] is not None:
                h = h + f2b[i]
            y = resid + h
            if not pre_layer_norm:
                y = _ln(y, flns[i], flnb[i])
        return (y, *new_caches) if new_caches else y

    flat_args = (list(ln_scales) + list(ln_biases) + list(qkv_weights)
                 + list(qkv_biases) + list(linear_weights)
                 + list(linear_biases) + list(ffn_ln_scales)
                 + list(ffn_ln_biases) + list(ffn1_weights)
                 + list(ffn1_biases) + list(ffn2_weights)
                 + list(ffn2_biases))
    if caches is not None:
        flat_args += caches
    if pres is not None:
        flat_args += pres
    out = run_op("fused_multi_transformer", impl,
                 (x, attn_mask, rot, *flat_args), {}, differentiable=False)
    if caches is not None:
        return out[0], list(out[1:])
    return out


def fused_conv_bn_act(x, conv_weight, bn_scale, bn_bias, bn_mean, bn_var,
                      stride=1, padding=0, epsilon=1e-5,
                      act: str = "relu", data_format="NCHW"):
    """Fused conv + batch-norm (inference stats) + activation (reference:
    phi/kernels/fusion/gpu/fused_scale_bias_relu_conv_bn_kernel.cu).

    TPU-native: BN folds INTO the conv weights algebraically —
    w' = w * scale/sqrt(var+eps) per out-channel, b' = bias - mean*scale/
    sqrt(var+eps) — so the whole op is ONE conv plus a bias-activation
    epilogue XLA fuses; no separate normalization pass ever runs."""
    from ....nn import functional as F

    def impl(w, sc, bb, mu, var):
        inv = sc * jax.lax.rsqrt(var + epsilon)
        w_f = w * inv[:, None, None, None]            # fold into OIHW
        b_f = bb - mu * inv
        return w_f, b_f

    # x is NOT an input of the fold — keeping it out of the op keys the
    # jit cache on the (tiny) weight shapes only, not the batch shape
    w_f, b_f = run_op("conv_bn_fold", impl,
                      (conv_weight, bn_scale, bn_bias, bn_mean, bn_var),
                      {})
    out = F.conv2d(x, w_f, bias=b_f, stride=stride, padding=padding,
                   data_format=data_format)
    if act == "relu":
        from ....ops import api as _api
        out = _api.relu(out)
    elif act not in (None, "identity", "none"):
        raise ValueError(f"unsupported act {act!r}")
    return out


def fused_adam(params, grads, lrs, moments1, moments2, beta1_pows,
               beta2_pows, master_weights=None, skip_update=None,
               beta1=0.9, beta2=0.999, epsilon=1e-8,
               multi_precision=False, use_adamw=False, weight_decay=0.01):
    """Multi-tensor Adam (reference phi/kernels/fused_adam_kernel.h): one
    fused update over a list of params, following the reference contract:
    ``beta1_pows``/``beta2_pows`` hold beta^t (bias correction divides by
    ``1 - pow``) and are RETURNED advanced by one factor; with
    ``master_weights`` the update runs on the fp32 master and the param
    gets the cast-down copy.

    Returns (params, moments1, moments2, beta1_pows, beta2_pows,
    master_weights)."""
    n = len(params)

    def pick(seq, i):
        return seq[i] if isinstance(seq, (list, tuple)) else seq

    outs = ([], [], [], [], [], [])
    for i in range(n):
        if skip_update is not None and bool(
                np.asarray(getattr(skip_update[i], "_value",
                                   skip_update[i]))):
            outs[0].append(params[i])
            outs[1].append(moments1[i])
            outs[2].append(moments2[i])
            outs[3].append(pick(beta1_pows, i))
            outs[4].append(pick(beta2_pows, i))
            outs[5].append(None if master_weights is None
                           else master_weights[i])
            continue

        def impl(pv, gv, m1v, m2v, b1p, b2p, lr, mw):
            g32 = gv.astype(jnp.float32)
            work = mw if mw is not None else pv.astype(jnp.float32)
            if use_adamw:
                work = work * (1.0 - lr * weight_decay)
            nm1 = beta1 * m1v + (1 - beta1) * g32
            nm2 = beta2 * m2v + (1 - beta2) * g32 * g32
            mhat = nm1 / (1 - b1p)            # pows hold beta^t already
            vhat = nm2 / (1 - b2p)
            new_work = work - lr * mhat / (jnp.sqrt(vhat) + epsilon)
            return (new_work.astype(pv.dtype), nm1, nm2,
                    b1p * beta1, b2p * beta2,
                    new_work if mw is not None else None)

        mw = None if master_weights is None else master_weights[i]
        res = run_op("fused_adam", impl,
                     (params[i], grads[i], moments1[i], moments2[i],
                      pick(beta1_pows, i), pick(beta2_pows, i),
                      pick(lrs, i), mw), {})
        for acc, v in zip(outs, res):
            acc.append(v)
    return outs


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """matmul + bias epilogue (reference fused_matmul_bias →
    fused_gemm_epilogue cublasLt kernel; XLA fuses the epilogue natively)."""
    def impl(xv, yv, b):
        a = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        w = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = a @ w
        return out if b is None else out + b
    return run_op("fused_matmul_bias", impl, (x, y, bias), {})


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """Transformer FFN block as one fused region (reference
    incubate/nn/functional/fused_transformer.py:36 →
    fused_feedforward kernel): [pre-]LN → linear1 → act → dropout →
    linear2 → dropout → residual [→ post-LN]."""
    from ....core.rng import next_rng_key
    keys = (next_rng_key(), next_rng_key()) if (
        training and (dropout1_rate or dropout2_rate)) else (None, None)

    def ln(v, scale, b, eps):
        mu = v.mean(-1, keepdims=True)
        var = ((v - mu) ** 2).mean(-1, keepdims=True)
        out = (v - mu) * jax.lax.rsqrt(var + eps)
        if scale is not None:
            out = out * scale
        if b is not None:
            out = out + b
        return out

    def drop(v, rate, key):
        if rate == 0.0:
            return v
        if not training or key is None:
            # downscale_in_infer applies the (1-p) factor at INFERENCE
            # (reference nn/functional/common.py dropout mode semantics)
            return v * (1.0 - rate) if mode == "downscale_in_infer" else v
        keep = jax.random.bernoulli(key, 1.0 - rate, v.shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - rate), 0.0)
        return jnp.where(keep, v, 0.0)

    acts = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}
    if activation not in acts:
        raise ValueError(f"unsupported activation {activation!r}")

    def impl(xv, w1, w2, b1, b2, s1, lb1, s2, lb2, k1, k2):
        h = ln(xv, s1, lb1, ln1_epsilon) if pre_layer_norm else xv
        h = h @ w1
        if b1 is not None:
            h = h + b1
        h = acts[activation](h)
        h = drop(h, dropout1_rate, k1)
        h = h @ w2
        if b2 is not None:
            h = h + b2
        h = drop(h, dropout2_rate, k2)
        out = xv + h if add_residual else h
        if not pre_layer_norm:
            out = ln(out, s2, lb2, ln2_epsilon)
        return out.astype(xv.dtype)

    return run_op("fused_feedforward", impl,
                  (x, linear1_weight, linear2_weight, linear1_bias,
                   linear2_bias, ln1_scale, ln1_bias, ln2_scale, ln2_bias,
                   keys[0], keys[1]), {})


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size=None,
                     name=None):
    """Max enc/dec lengths for block attention scheduling (reference
    fusion/gpu blha_get_max_len kernel)."""
    def impl(enc, dec):
        return jnp.max(enc), jnp.max(dec)
    return run_op("blha_get_max_len", impl,
                  (seq_lens_encoder, seq_lens_decoder), {})


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, *, max_seq_len=None,
                              block_size=None, use_neox_style=False,
                              name=None, **kw):
    """Paged-KV block attention, decode phase (reference
    fusion/gpu/block_multi_head_attention_kernel.cu).

    TPU scope: the decode step over a paged pool — qkv [B, 3, H, D] (one
    new token per sequence), caches are page pools [NB, BS, H, D],
    block_tables [B, MB].  Appends the new K/V to the pages, then runs
    the paged gather + masked attention (ops/paged_kv.py).  Returns
    (out [B, H, D], key_cache, value_cache)."""
    from ....ops.paged_kv import paged_append, paged_decode_attention
    bs = block_size or key_cache.shape[1] if hasattr(
        key_cache, "shape") else block_size

    def impl(p, kc, vc, dec_lens, bt):
        q, k_new, v_new = p[:, 0], p[:, 1], p[:, 2]
        kc, vc = paged_append(kc, vc, k_new, v_new, bt, dec_lens,
                              int(bs))
        out = paged_decode_attention(q, kc, vc, bt, dec_lens + 1)
        return out, kc, vc

    return run_op("block_multihead_attention", impl,
                  (qkv, key_cache, value_cache, seq_lens_decoder,
                   block_tables), {})


def variable_length_memory_efficient_attention(query, key, value,
                                               seq_lens, kv_seq_lens,
                                               mask=None, scale=None,
                                               causal=False,
                                               pre_cache_length=0):
    """Varlen memory-efficient attention (reference fusion/gpu
    variable_length_memory_efficient_attention + cutlass): per-sequence
    lengths mask a padded batch; the flash kernel path gives O(T)
    memory, the dense fallback masks explicitly.  q/k/v: [B, H, S, D];
    seq_lens/kv_seq_lens: [B]."""
    import math as _math

    def impl(q, k, v, ql, kl, m):
        B, H, S, D = q.shape
        s = scale if scale is not None else 1.0 / _math.sqrt(D)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * s
        kmask = jnp.arange(k.shape[2])[None, None, None, :] \
            < kl[:, None, None, None]
        qmask = jnp.arange(S)[None, None, :, None] < ql[:, None, None, None]
        mask_all = kmask & qmask
        if causal:
            # query i may see the full pre-cache prefix plus keys up to
            # its own (cache-offset) position
            rows = jnp.arange(S)[:, None] + int(pre_cache_length)
            tri = rows >= jnp.arange(k.shape[2])[None, :]
            mask_all = mask_all & tri[None, None]
        logits = jnp.where(mask_all, logits, jnp.finfo(jnp.float32).min)
        if m is not None:
            logits = logits + m.astype(jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        p = jnp.where(mask_all, p, 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    return run_op("var_len_mem_efficient_attention", impl,
                  (query, key, value, seq_lens, kv_seq_lens, mask), {})


def fused_moe(x, gate_weight, expert_weights1, expert_biases1,
              expert_weights2, expert_biases2, *, moe_topk=2,
              norm_topk_prob=True, name=None, **kw):
    """Fused MoE FFN (reference incubate fused_moe → fused_moe kernel):
    softmax gate → top-k dispatch → per-expert FFN → weighted combine.
    Dense einsum formulation — every token visits every expert and the
    top-k mask zeroes the rest, which on TPU trades FLOPs for zero
    all-to-all and perfect load balance at small expert counts."""
    def impl(xv, gw, w1, b1, w2, b2):
        B = xv.shape[:-1]
        d = xv.shape[-1]
        t = xv.reshape(-1, d)                      # [T, d]
        gate = jax.nn.softmax(t @ gw, axis=-1)     # [T, E]
        E = gate.shape[-1]
        topv, topi = jax.lax.top_k(gate, moe_topk)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, -1, keepdims=True)
        w_dense = jnp.zeros((t.shape[0], E), gate.dtype)
        w_dense = w_dense.at[jnp.arange(t.shape[0])[:, None],
                             topi].set(topv)
        h = jnp.einsum("td,edf->tef", t, w1)
        if b1 is not None:
            h = h + b1[None]
        h = jax.nn.gelu(h)
        h = jnp.einsum("tef,efd->ted", h, w2)
        if b2 is not None:
            h = h + b2[None]
        out = jnp.einsum("ted,te->td", h, w_dense)
        return out.reshape(*B, d).astype(xv.dtype)

    return run_op("fused_moe", impl,
                  (x, gate_weight, expert_weights1, expert_biases1,
                   expert_weights2, expert_biases2), {})


def fused_ec_moe(x, gate, expert_weights1, expert_biases1, expert_weights2,
                 expert_biases2, act_type="gelu", name=None):
    """Expert-choice MoE (reference fused_ec_moe kernel): same fused
    dense formulation with a precomputed gate tensor."""
    def impl(xv, g, w1, b1, w2, b2):
        B = xv.shape[:-1]
        d = xv.shape[-1]
        t = xv.reshape(-1, d)
        gate_p = jax.nn.softmax(g.reshape(t.shape[0], -1), axis=-1)
        h = jnp.einsum("td,edf->tef", t, w1) + (
            b1[None] if b1 is not None else 0.0)
        h = jax.nn.gelu(h) if act_type == "gelu" else jax.nn.relu(h)
        h = jnp.einsum("tef,efd->ted", h, w2) + (
            b2[None] if b2 is not None else 0.0)
        out = jnp.einsum("ted,te->td", h, gate_p)
        return out.reshape(*B, d).astype(xv.dtype)

    return run_op("fused_ec_moe", impl,
                  (x, gate, expert_weights1, expert_biases1,
                   expert_weights2, expert_biases2), {})
