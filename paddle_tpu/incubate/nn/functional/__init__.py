"""Fused functional APIs (reference: python/paddle/incubate/nn/functional —
16 fused entry points).  Each dispatches to the Pallas kernel inventory."""

from __future__ import annotations

import jax.numpy as jnp

from ....core.dispatch import run_op
from ....core.tensor import Tensor
from ....ops import pallas as _pk

__all__ = [
    "fused_rms_norm", "fused_layer_norm",
    "fused_bias_dropout_residual_layer_norm", "fused_rotary_position_embedding",
    "fused_bias_act", "fused_dropout_add", "swiglu", "fused_linear",
    "fused_linear_activation", "fused_multi_head_attention",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None):
    def impl(xv, w, nb, b, res):
        if b is not None:
            xv = xv + b
        if res is not None:
            xv = xv + res
        out = _pk.rms_norm(xv, w, epsilon)
        if nb is not None:
            out = out + nb
        return (out, xv) if res is not None else out
    return run_op("fused_rms_norm", impl,
                  (x, norm_weight, norm_bias, bias, residual), {})


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None):
    def impl(xv, w, b, bias_v, res):
        if bias_v is not None:
            xv = xv + bias_v
        if res is not None:
            xv = xv + res
        out = _pk.layer_norm(xv, w, b, epsilon)
        return (out, xv) if res is not None else out
    return run_op("fused_layer_norm", impl,
                  (x, norm_weight, norm_bias, bias, residual), {})


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=False):
    def impl(xv, res, b, w, lb):
        if b is None:
            b = jnp.zeros(xv.shape[-1], xv.dtype)
        if w is None:
            w = jnp.ones(xv.shape[-1], jnp.float32)
        if lb is None:
            lb = jnp.zeros(xv.shape[-1], jnp.float32)
        out, _ = _pk.fused_bias_dropout_residual_layer_norm(
            xv, res, b, w, lb, dropout_rate, ln_epsilon, training)
        return out
    return run_op("fused_bias_dropout_residual_ln", impl,
                  (x, residual, bias, ln_scale, ln_bias), {})


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    def impl(qv, kv, vv, sv, cv, pid):
        return _pk.fused_rope(qv, kv, vv, sv, cv, pid,
                              use_neox_rotary_style)
    return run_op("fused_rope", impl, (q, k, v, sin, cos, position_ids), {})


def fused_bias_act(x, bias, act_method="gelu"):
    return run_op("fused_bias_act",
                  lambda xv, b: _pk.fused_bias_act(xv, b, act_method),
                  (x, bias), {})


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....core.rng import next_rng_key
    import jax as _jax
    seed = _jax.random.randint(next_rng_key(), (), 0, 2 ** 31 - 1)         if training and p > 0.0 else None
    return run_op("fused_dropout_add",
                  lambda xv, yv, sd: _pk.fused_dropout_add(
                      xv, yv, p, training, seed=sd),
                  (x, y, seed), {})


def swiglu(x, y=None):
    def impl(xv, yv):
        if yv is None:
            h = xv.shape[-1] // 2
            xv, yv = xv[..., :h], xv[..., h:]
        return _pk.swiglu(xv, yv)
    return run_op("fused_swiglu", impl, (x, y), {})


def fused_linear(x, weight, bias=None, transpose_weight=False):
    def impl(xv, w, b):
        if transpose_weight:
            w = jnp.swapaxes(w, -2, -1)
        out = jnp.matmul(xv, w)
        if b is not None:
            out = out + b
        return out
    return run_op("fused_linear", impl, (x, weight, bias), {})


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu"):
    def impl(xv, w, b):
        if trans_x:
            xv = jnp.swapaxes(xv, -2, -1)
        if trans_y:
            w = jnp.swapaxes(w, -2, -1)
        return _pk.fused_bias_act(jnp.matmul(xv, w), b, activation)
    return run_op("fused_linear_activation", impl, (x, y, bias), {})


def fused_multi_head_attention(x, qkv_weight, linear_weight, *args, **kwargs):
    raise NotImplementedError(
        "compose MultiHeadAttention (flash-attention backed) instead; "
        "monolithic fused MHA arrives with the decode/inference module")
