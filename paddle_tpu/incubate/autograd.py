"""paddle.incubate.autograd parity (reference
python/paddle/incubate/autograd/ — jac/hessian/jvp/vjp + forward_grad).

Higher-order AD is native in JAX; these wrappers keep the reference's
Tensor-level signatures.  primitive-mode prim flags (enable_prim) are
no-ops: XLA is always the compiler."""

from ..autograd.functional import hessian, jacobian, jvp, vjp  # noqa: F401

__all__ = ["jacobian", "hessian", "jvp", "vjp", "forward_grad",
           "enable_prim", "disable_prim", "prim_enabled"]


def forward_grad(func, xs, v=None):
    """Forward-mode gradient (alias of jvp's tangent output)."""
    _, tangents = jvp(func, xs, v)
    return tangents


def enable_prim():  # pragma: no cover - API parity no-op
    return None


def disable_prim():  # pragma: no cover - API parity no-op
    return None


def prim_enabled() -> bool:
    return True  # XLA composite lowering is always on
