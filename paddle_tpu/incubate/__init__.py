from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import asp  # noqa: F401
from .extras import (  # noqa: F401
    LookAhead, ModelAverage, graph_khop_sampler, graph_reindex,
    graph_sample_neighbors, graph_send_recv, identity_loss,
    segment_max, segment_mean, segment_min, segment_sum,
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)
from . import inference  # noqa: F401
