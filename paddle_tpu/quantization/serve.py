"""Serving-side quantization: the PTQ export path the engine consumes.

The layer-graph PTQ in this package (observers -> QuantedLayer ->
QuantizedLinear) serves the Layer/Predictor world; the continuous-
batching engine serves raw param PYTREES.  This module is the bridge:

* :class:`ServeQuantConfig` — the engine's ``quant_config`` ctor knob
  (weight dtype + group size + KV-pool dtype), hashed into the AOT
  ``engine_config`` so a warm start can never half-load a mismatched
  quantization.
* :func:`quantize_params_for_serving` — PTQ-export a zoo param tree to
  the ``<name>__q`` / ``<name>__s`` leaf convention that
  ``models.generation.build_llama_decoder(quant=...)`` and the quantized
  ``ops/decode_block`` tiers consume.  Scales are per-output-channel (or
  per (input-group, channel)) fp32 absmax — optionally the OBSERVER-
  calibrated per-channel absmax (:func:`calibrate_weight_thresholds`,
  the same ``PerChannelAbsMaxObserver`` statistic the layer-graph deploy
  path bakes), so calibration-time outlier clipping survives into the
  served tree.

Weight-only means exactly that: activations, norms, biases and the
embedding/head stay at the model dtype; only block matmul weights are
stored as int8 codes (or halves-packed int4 nibbles) + fp32 scales.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

__all__ = ["ServeQuantConfig", "quantize_params_for_serving",
           "calibrate_weight_thresholds", "dequantize_block_weight",
           "quantized_leaf_names"]

_WEIGHT_DTYPES = (None, "int8", "int4")
_KV_DTYPES = (None, "int8")
_GROUP_SIZES = (-1, 64, 128)


@dataclasses.dataclass(frozen=True)
class ServeQuantConfig:
    """The engine's quantization knob.

    ``weight_dtype``: None (full width) / "int8" / "int4" — storage of
    block matmul weights (``__q`` codes + ``__s`` fp32 scales).
    ``group_size``: -1 = one scale per output channel; 64/128 = one
    scale per (input-row group, channel).
    ``kv_dtype``: None / "int8" — paged-KV pool storage; int8 pools
    carry per-(token, head) fp32 scales (``ops.paged_kv.
    QuantizedKVPool``), chosen over per-page absmax so a rejected
    spec-decode draft can never retroactively requantize committed
    tokens.
    """
    weight_dtype: Optional[str] = None
    group_size: int = -1
    kv_dtype: Optional[str] = None

    def __post_init__(self):
        if self.weight_dtype not in _WEIGHT_DTYPES:
            raise ValueError(f"weight_dtype must be one of "
                             f"{_WEIGHT_DTYPES}, got {self.weight_dtype!r}")
        if self.kv_dtype not in _KV_DTYPES:
            raise ValueError(f"kv_dtype must be one of {_KV_DTYPES}, "
                             f"got {self.kv_dtype!r}")
        if self.group_size not in _GROUP_SIZES:
            raise ValueError(f"group_size must be one of {_GROUP_SIZES},"
                             f" got {self.group_size}")
        if self.weight_dtype is None and self.group_size != -1:
            raise ValueError("group_size without weight_dtype is "
                             "meaningless — set weight_dtype")

    @property
    def quantized_weights(self) -> bool:
        return self.weight_dtype is not None

    @property
    def quantized_kv(self) -> bool:
        return self.kv_dtype is not None

    @property
    def algo(self) -> Optional[str]:
        """The ``nn.quant.weight_quantize`` algo string."""
        if self.weight_dtype is None:
            return None
        return f"weight_only_{self.weight_dtype}"

    def describe(self) -> Dict[str, object]:
        """Stable dict for the AOT ``engine_config`` hash."""
        return {"weight_dtype": self.weight_dtype,
                "group_size": self.group_size,
                "kv_dtype": self.kv_dtype}


def quantized_leaf_names(name: str):
    """(codes, scales) leaf names for a quantized matmul weight."""
    return name + "__q", name + "__s"


def _is_block_matmul(name: str, v) -> bool:
    """A quantizable block leaf: a stacked matmul weight, not a norm
    gain / bias / already-quantized leaf (mirrors the predicate of
    ``models.generation.quantize_llama_params``)."""
    return (name.endswith("_w") and v.ndim >= 3
            and not name.startswith("ln") and "__" not in name)


def calibrate_weight_thresholds(params) -> Dict[str, np.ndarray]:
    """Observer-calibrated per-channel thresholds for every quantizable
    block weight: runs a ``PerChannelAbsMaxObserver`` over each layer's
    weight matrix (weight-only PTQ calibrates on the weights themselves)
    and returns ``{leaf name: [L, N] absmax}`` — the reference the
    round-trip test compares dequantized exports against."""
    from .observers import PerChannelAbsMaxObserver
    out: Dict[str, np.ndarray] = {}
    for name, v in params["blocks"].items():
        if not _is_block_matmul(name, v):
            continue
        flat = np.asarray(v).reshape((-1,) + v.shape[-2:])   # [L, K, N]
        rows = []
        for i in range(flat.shape[0]):
            obs = PerChannelAbsMaxObserver(axis=-1)
            obs.forward(jnp.asarray(flat[i]))
            rows.append(np.asarray(obs.cal_thresholds()).reshape(-1))
        out[name] = np.stack(rows)                           # [L, N]
    return out


def _quantize_matrix(w, config: ServeQuantConfig, thresholds=None):
    """One [K, N] matrix -> (codes, scales) under ``config``.

    Pure NUMPY, bit-for-bit the ``nn.quant.weight_quantize`` layout
    (absmax scales, halves-packed int4 nibbles, grouped [G, N] scales —
    pinned by the PTQ round-trip test through ``weight_dequantize``).
    Host-side on purpose: PTQ export runs at ENGINE CONSTRUCTION, and a
    warm-started quantized engine must stay at zero backend compiles
    (the ``serve_quant_warm`` budget row) — a traced quantize would
    recompile per construction.

    ``thresholds``: calibrated per-channel absmax [N]; int8 per-channel
    only (grouped / int4 scales re-derive absmax per group — the
    calibrated statistic IS the per-channel absmax, so raw and
    calibrated coincide unless an observer clipped)."""
    wf = np.asarray(w, np.float32)
    K = wf.shape[0]
    gs = config.group_size
    if (thresholds is not None and config.weight_dtype == "int8"
            and gs == -1):
        absmax = np.asarray(thresholds, np.float32).reshape(-1)
    elif gs != -1:
        G = -(-K // gs)
        wp = np.pad(wf, ((0, G * gs - K), (0, 0)))
        absmax = np.max(np.abs(wp.reshape(G, gs, -1)), axis=1)
    else:
        absmax = np.max(np.abs(wf), axis=0)
    qmax = 7.0 if config.weight_dtype == "int4" else 127.0
    scale = np.maximum(absmax, 1e-8) / qmax
    srow = np.repeat(scale, gs, axis=0)[:K] if gs != -1 else scale
    q = np.clip(np.round(wf / srow), -qmax - 1, qmax).astype(np.int8)
    if config.weight_dtype == "int4":
        if q.shape[0] % 2:
            q = np.pad(q, ((0, 1), (0, 0)))
        half = q.shape[0] // 2
        # HALVES packing: rows [0, K/2) low nibble, [K/2, K) high —
        # the nn.quant layout the kernels unpack
        q = ((q[:half] & 0x0F) | (q[half:] << 4)).astype(np.int8)
    return q, scale.astype(np.float32)


def quantize_params_for_serving(params, config: ServeQuantConfig,
                                thresholds: Optional[Dict] = None):
    """PTQ export: a zoo param tree -> the engine's quantized tree.

    Every stacked block matmul weight ``<name>`` (shape
    ``[*stages, L, K, N]``) is replaced by ``<name>__q`` (int8 codes;
    int4 halves-packed ``[..., ceil(K/2), N]``) and ``<name>__s`` (fp32
    scales ``[..., N]`` or grouped ``[..., G, N]``); everything else —
    norms, embedding, head, non-block leaves — passes through untouched.
    ``thresholds`` (from :func:`calibrate_weight_thresholds`) overrides
    raw absmax for per-channel int8.  Identity when the config has no
    weight quantization.
    """
    if not config.quantized_weights:
        return params
    blocks = params["blocks"]
    out = {k: v for k, v in params.items() if k != "blocks"}
    qblocks = {}
    for name, v in blocks.items():
        if not _is_block_matmul(name, v):
            qblocks[name] = v
            continue
        lead = v.shape[:-2]
        flat = np.asarray(v).reshape((-1,) + v.shape[-2:])   # [L, K, N]
        th = (thresholds or {}).get(name)
        qs, ss = [], []
        for i in range(flat.shape[0]):
            q, s = _quantize_matrix(flat[i], config,
                                    None if th is None else th[i])
            qs.append(q)
            ss.append(s)
        qn, sn = quantized_leaf_names(name)
        qblocks[qn] = jnp.asarray(
            np.stack(qs).reshape(lead + qs[0].shape))
        qblocks[sn] = jnp.asarray(
            np.stack(ss).reshape(lead + ss[0].shape))
    out["blocks"] = qblocks
    return out


def dequantize_block_weight(q, s, config: ServeQuantConfig, k: int):
    """Dequantize one layer's exported weight (``[K', N]`` codes +
    scales) back to fp32 ``[K, N]`` — the round-trip test's probe and
    the documentation of the storage layout in one place."""
    from ..nn.quant import weight_dequantize
    out = weight_dequantize(jnp.asarray(q), jnp.asarray(s),
                            algo=config.algo, k=k,
                            group_size=config.group_size)
    return out._value if hasattr(out, "_value") else jnp.asarray(out)
