"""paddle.quantization parity (reference python/paddle/quantization/ —
QuantConfig, QAT/PTQ entry points, quanters; python/paddle/nn/quant fake
quant ops).

TPU-first: fake-quant is a pure jnp straight-through-estimator op (XLA
fuses it into the surrounding graph); QAT wraps layers with quanters, PTQ
runs observers that collect absmax/histogram stats during calibration.
"""

from .config import QuantConfig  # noqa: F401
from .quanters import (  # noqa: F401
    AbsMaxObserver, BaseObserver, BaseQuanter, FakeQuanterWithAbsMax,
    quanter, get_quanter, register_quanter,
    FakeQuanterWithAbsMaxObserver, quant_dequant,
)
from .observers import (  # noqa: F401
    EMAAbsMaxObserver, GroupWiseWeightObserver, HistPercentileObserver,
    PerChannelAbsMaxObserver,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .export import (  # noqa: F401
    QuantizedLinear, convert_to_deploy, export_quantized,
)
from .serve import (  # noqa: F401
    ServeQuantConfig, quantize_params_for_serving,
    calibrate_weight_thresholds, dequantize_block_weight,
)

__all__ = ["QuantConfig", "QAT", "PTQ", "BaseQuanter",
           "BaseObserver", "quanter", "get_quanter", "register_quanter",
           "FakeQuanterWithAbsMax", "FakeQuanterWithAbsMaxObserver",
           "AbsMaxObserver", "EMAAbsMaxObserver",
           "PerChannelAbsMaxObserver", "HistPercentileObserver",
           "GroupWiseWeightObserver", "quant_dequant",
           "QuantizedLinear", "convert_to_deploy", "export_quantized",
           "ServeQuantConfig", "quantize_params_for_serving",
           "calibrate_weight_thresholds", "dequantize_block_weight"]
