"""paddle.quantization parity (reference python/paddle/quantization/ —
QuantConfig, QAT/PTQ entry points, quanters; python/paddle/nn/quant fake
quant ops).

TPU-first: fake-quant is a pure jnp straight-through-estimator op (XLA
fuses it into the surrounding graph); QAT wraps layers with quanters, PTQ
runs observers that collect absmax/histogram stats during calibration.
"""

from .config import QuantConfig  # noqa: F401
from .quanters import (  # noqa: F401
    AbsMaxObserver, BaseObserver, BaseQuanter, FakeQuanterWithAbsMax,
    quanter,
    FakeQuanterWithAbsMaxObserver, quant_dequant,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401

__all__ = ["QuantConfig", "QAT", "PTQ", "BaseQuanter",
           "BaseObserver", "quanter",
           "FakeQuanterWithAbsMax", "FakeQuanterWithAbsMaxObserver",
           "AbsMaxObserver", "quant_dequant"]
