"""Observer library (reference python/paddle/quantization/observers/ —
abs_max.py, groupwise.py — plus the imperative PTQ observers: moving
average, histogram/percentile).

Observers COLLECT statistics during calibration forwards and expose
``scales()`` / ``cal_thresholds()``; they never alter the tensor.  All
stat updates happen host-side on concrete values (calibration is an
eager loop by construction), so none of this enters the compiled graph.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .quanters import BaseObserver, register_quanter

__all__ = ["EMAAbsMaxObserver", "PerChannelAbsMaxObserver",
           "HistPercentileObserver", "GroupWiseWeightObserver"]


def _val(x):
    return np.asarray(getattr(x, "_value", x))


@register_quanter("ema_abs_max")
class EMAAbsMaxObserver(BaseObserver):
    """Exponential-moving-average absmax (imperative moving-average
    observer): smoother than global max under outlier batches."""

    def __init__(self, moving_rate: float = 0.9, quant_bits: int = 8):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self._ema = None

    def forward(self, x):
        cur = float(np.abs(_val(x)).max())
        self._ema = cur if self._ema is None else \
            self.moving_rate * self._ema + (1 - self.moving_rate) * cur
        return x

    def cal_thresholds(self):
        return self._ema or 0.0

    def scales(self):
        return Tensor(jnp.asarray([max(self._ema or 0.0, 1e-9)],
                                  jnp.float32))


@register_quanter("per_channel_abs_max")
class PerChannelAbsMaxObserver(BaseObserver):
    """Per-output-channel absmax over ``axis`` (reference per-channel
    weight observers): one scale per channel."""

    def __init__(self, axis: int = -1, quant_bits: int = 8):
        super().__init__()
        self.axis = axis
        self.quant_bits = quant_bits
        self._max = None

    def forward(self, x):
        v = np.abs(_val(x))
        ax = tuple(i for i in range(v.ndim) if i != self.axis % v.ndim)
        cur = v.max(axis=ax) if ax else v
        self._max = cur if self._max is None else np.maximum(self._max,
                                                             cur)
        return x

    def cal_thresholds(self):
        return self._max

    def scales(self):
        if self._max is None:          # never calibrated: no claim
            return None
        return Tensor(jnp.asarray(np.maximum(self._max, 1e-9),
                                  jnp.float32))


@register_quanter("hist_percentile")
class HistPercentileObserver(BaseObserver):
    """Histogram + percentile threshold (imperative HistObserver /
    PercentileObserver): clips the absmax tail at ``percentile`` of the
    observed magnitude mass — robust to activation outliers."""

    def __init__(self, percentile: float = 0.999, bins: int = 2048,
                 quant_bits: int = 8):
        super().__init__()
        self.percentile = percentile
        self.bins = bins
        self.quant_bits = quant_bits
        self._hist = None
        self._edges = None

    def forward(self, x):
        v = np.abs(_val(x)).reshape(-1)
        hi = float(v.max()) if v.size else 0.0
        if self._hist is None:
            self._edges = np.linspace(0.0, max(hi, 1e-9), self.bins + 1)
            self._hist = np.histogram(v, bins=self._edges)[0].astype(
                np.float64)
        else:
            if hi > self._edges[-1]:
                # grow the range: re-bin the old histogram into new edges
                new_edges = np.linspace(0.0, hi, self.bins + 1)
                centers = (self._edges[:-1] + self._edges[1:]) / 2
                re_binned = np.histogram(
                    centers, bins=new_edges, weights=self._hist)[0]
                self._hist, self._edges = re_binned, new_edges
            self._hist += np.histogram(v, bins=self._edges)[0]
        return x

    def cal_thresholds(self):
        if self._hist is None or self._hist.sum() == 0:
            return 0.0
        cdf = np.cumsum(self._hist) / self._hist.sum()
        idx = int(np.searchsorted(cdf, self.percentile))
        return float(self._edges[min(idx + 1, self.bins)])

    def scales(self):
        return Tensor(jnp.asarray([max(self.cal_thresholds(), 1e-9)],
                                  jnp.float32))


@register_quanter("groupwise_weight")
class GroupWiseWeightObserver(BaseObserver):
    """Group-wise weight absmax (reference observers/groupwise.py): the
    K dim is chunked into ``group_size`` groups, one scale each — the
    stat layer for grouped weight-only kernels."""

    def __init__(self, group_size: int = 128, quant_bits: int = 4):
        super().__init__()
        self.group_size = group_size
        self.quant_bits = quant_bits
        self._max = None

    def forward(self, x):
        v = np.abs(_val(x))            # [K, N]
        if v.ndim != 2:
            raise ValueError(
                "GroupWiseWeightObserver requires 2-D [K, N] weights "
                f"(got shape {v.shape}); use PerChannelAbsMaxObserver "
                "for conv weights or activations")
        k, n = v.shape
        g = self.group_size
        pad = (-k) % g
        if pad:
            v = np.concatenate([v, np.zeros((pad, n), v.dtype)], axis=0)
        cur = v.reshape(-1, g, n).max(axis=1)      # [K/g, N]
        self._max = cur if self._max is None else np.maximum(self._max,
                                                             cur)
        return x

    def cal_thresholds(self):
        return self._max

    def scales(self):
        if self._max is None:          # never calibrated: no claim
            return None
        return Tensor(jnp.asarray(np.maximum(self._max, 1e-9),
                                  jnp.float32))
