"""Quanters & observers (reference python/paddle/quantization/quanters/
abs_max.py FakeQuanterWithAbsMaxObserver, observers/abs_max.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["quant_dequant", "BaseQuanter", "FakeQuanterWithAbsMax",
           "FakeQuanterWithAbsMaxObserver", "AbsMaxObserver"]


def _qdq_raw(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


@jax.custom_vjp
def _qdq_ste(x, scale, qmax):
    return _qdq_raw(x, scale, qmax)


def _qdq_fwd(x, scale, qmax):
    return _qdq_raw(x, scale, qmax), (x, scale, qmax)


def _qdq_bwd(res, g):
    x, scale, qmax = res
    # straight-through: pass grad inside the clip range, zero outside
    inside = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale), None


_qdq_ste.defvjp(_qdq_fwd, _qdq_bwd)


@primitive("fake_quant_dequant")
def _qdq_op(x, scale, *, bit_length):
    qmax = float(2 ** (bit_length - 1) - 1)
    return _qdq_ste(x, scale, qmax)


def quant_dequant(x, scale, bit_length: int = 8):
    """Fake-quantize x to bit_length ints and back (STE gradient)."""
    return _qdq_op(x, scale, bit_length=bit_length)


class BaseQuanter(Layer):
    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class FakeQuanterWithAbsMax(BaseQuanter):
    """Static absmax fake quanter (scale from current tensor)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        from ..ops import api as _api
        scale = _api.abs(x).max()
        return quant_dequant(x, scale, self.quant_bits)

    def scales(self):
        return None


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-average absmax quanter for QAT (reference
    quanters/abs_max.py: FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype="float32", name=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self._scale = self.create_parameter([1], is_bias=True)
        self._scale.stop_gradient = True
        self._initialized = False

    def forward(self, x):
        import jax.numpy as jnp
        if self.training:
            cur = float(jnp.max(jnp.abs(x._value)))
            if not self._initialized:
                new = cur
                self._initialized = True
            else:
                prev = float(self._scale._value[0])
                r = self.moving_rate
                new = r * prev + (1 - r) * cur
            self._scale.set_value(jnp.asarray([new], jnp.float32))
        scale = Tensor(jnp.maximum(self._scale._value[0], 1e-9))
        return quant_dequant(x, scale, self.bit_length)

    def scales(self):
        return Tensor(self._scale._value)


class AbsMaxObserver(BaseQuanter):
    """PTQ calibration observer: tracks global absmax, then quantizes
    (reference observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits
        self._max = 0.0

    def forward(self, x):
        import jax.numpy as jnp
        self._max = max(self._max, float(jnp.max(jnp.abs(x._value))))
        return x  # observation only during calibration

    def cal_thresholds(self):
        return self._max

    def scales(self):
        import jax.numpy as jnp
        return Tensor(jnp.asarray([max(self._max, 1e-9)], jnp.float32))


class BaseObserver(BaseQuanter):
    """Observer base (reference paddle/quantization/base_observer.py):
    a quanter that only COLLECTS statistics during calibration; PTQ
    observers (AbsMaxObserver etc.) subclass this."""

    def cal_thresholds(self):
        raise NotImplementedError


_QUANTER_REGISTRY = {}


def quanter(name: str):
    """Class decorator registering a custom quanter under ``name``
    (reference quantization/factory.py quanter): the QuantConfig factory
    can then instantiate it by name."""
    def deco(cls):
        _QUANTER_REGISTRY[name] = cls
        cls.quanter_name = name
        return cls
    return deco


register_quanter = quanter          # observer-side alias


def get_quanter(name: str, **kwargs):
    """Instantiate a registered quanter/observer by name (the factory
    entry point — reference factory.QuanterFactory._instance)."""
    if name not in _QUANTER_REGISTRY:
        raise KeyError(
            f"unknown quanter {name!r}; registered: "
            f"{sorted(_QUANTER_REGISTRY)}")
    return _QUANTER_REGISTRY[name](**kwargs)


# built-ins are addressable by name too
quanter("abs_max")(FakeQuanterWithAbsMax)
quanter("abs_max_observer")(AbsMaxObserver)
quanter("moving_abs_max")(FakeQuanterWithAbsMaxObserver)
