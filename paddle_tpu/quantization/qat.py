"""QAT (reference python/paddle/quantization/qat.py) — wrap configured
layers with fake-quant on activations and weights."""

from __future__ import annotations

from ..nn.layer.layers import Layer
from .config import QuantConfig

__all__ = ["QAT", "QuantedLayer"]


class QuantedLayer(Layer):
    """Wrapper applying activation/weight fake-quant around one layer."""

    def __init__(self, layer: Layer, activation_quanter, weight_quanter):
        super().__init__()
        self.inner = layer
        self.activation_quanter = self._resolve(activation_quanter)
        self.weight_quanter = self._resolve(weight_quanter)

    @staticmethod
    def _resolve(q):
        """Accept an instance, a factory/class, or a REGISTERED NAME
        (quanters.get_quanter — the factory.py name path)."""
        if isinstance(q, str):
            from .quanters import get_quanter
            return get_quanter(q)
        if callable(q) and not isinstance(q, Layer):
            return q()
        return q

    def forward(self, x, *args, **kwargs):
        from ..nn import functional as F
        from ..nn.layer.common import Linear
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self.inner, "weight"):
            qw = self.weight_quanter(self.inner.weight)
            if isinstance(self.inner, Linear):
                # taped functional path: STE gradient flows through the
                # fake-quant back to the real weight
                return F.linear(x, qw, getattr(self.inner, "bias", None))
            # generic layers: value-level substitution (observer/PTQ use)
            from ..nn.layer.layers import functional_call
            return functional_call(self.inner, {"weight": qw._value}, x,
                                   *args, **kwargs)
        return self.inner(x, *args, **kwargs)


class QAT:
    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        """Replace configured sublayers with QuantedLayer wrappers."""
        for name, child in list(model.named_children()):
            cfg = self.config.config_for(child, name)
            if cfg is not None:
                act, w = cfg
                setattr(model, name, QuantedLayer(child, act, w))
            else:
                self.quantize(child, inplace=True)
        return model

    def convert(self, model: Layer, inplace: bool = False,
                deploy: bool = False, weight_dtype: str = "int8"
                ) -> Layer:
        """Strip wrappers back to inner layers.  ``deploy=True`` goes the
        whole way: Linear layers become :class:`QuantizedLinear` with
        real int8/int4 weights feeding weight_only_linear (export.py);
        default keeps fp weights baked at the trained scales (the
        reference convert() behavior)."""
        if deploy:
            from .export import convert_to_deploy
            return convert_to_deploy(model, weight_dtype)
        from .export import bake_fake_quant
        for name, child in list(model.named_children()):
            if isinstance(child, QuantedLayer):
                bake_fake_quant(child.inner, child.weight_quanter)
                setattr(model, name, child.inner)
            else:
                self.convert(child, inplace=True)
        return model
