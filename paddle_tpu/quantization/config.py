"""QuantConfig (reference python/paddle/quantization/config.py)."""

from __future__ import annotations

from typing import Dict, Optional, Type

from ..nn.layer.layers import Layer

__all__ = ["QuantConfig"]


class QuantConfig:
    """Maps layers/layer-types/prefixes to (activation, weight) quanters."""

    def __init__(self, activation=None, weight=None):
        self.default_activation = activation
        self.default_weight = weight
        self._type_configs: Dict[Type[Layer], tuple] = {}
        self._layer_configs: Dict[int, tuple] = {}
        self._name_configs: Dict[str, tuple] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        if not isinstance(layer_type, (list, tuple)):
            layer_type = [layer_type]
        for t in layer_type:
            self._type_configs[t] = (activation, weight)
        return self

    def add_layer_config(self, layer, activation=None, weight=None):
        if not isinstance(layer, (list, tuple)):
            layer = [layer]
        for l in layer:
            self._layer_configs[id(l)] = (activation, weight)
        return self

    def add_name_config(self, layer_name, activation=None, weight=None):
        if not isinstance(layer_name, (list, tuple)):
            layer_name = [layer_name]
        for n in layer_name:
            self._name_configs[n] = (activation, weight)
        return self

    def config_for(self, layer: Layer, name: str = ""):
        """Resolve the (activation, weight) quanter factories for a layer;
        precedence layer > name > type > default."""
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        for prefix, cfg in self._name_configs.items():
            if name.startswith(prefix):
                return cfg
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        if self.default_activation or self.default_weight:
            return (self.default_activation, self.default_weight)
        return None
