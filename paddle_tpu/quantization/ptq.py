"""PTQ (reference python/paddle/quantization/ptq.py) — insert observers,
calibrate, convert to quantized weights."""

from __future__ import annotations

from ..nn.layer.layers import Layer
from .config import QuantConfig
from .qat import QuantedLayer
from .quanters import quant_dequant

__all__ = ["PTQ"]


class PTQ:
    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        """Insert observers on configured layers; run calibration batches
        through the returned model."""
        for name, child in list(model.named_children()):
            cfg = self.config.config_for(child, name)
            if cfg is not None:
                act, w = cfg
                setattr(model, name, QuantedLayer(child, act, w))
            else:
                self.quantize(child, inplace=True)
        return model

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Apply observed scales: weights are fake-quantized in place and
        observers removed."""
        for name, child in list(model.named_children()):
            if isinstance(child, QuantedLayer):
                inner = child.inner
                q = child.weight_quanter
                if hasattr(inner, "weight") and q is not None and \
                        hasattr(q, "scales") and q.scales() is not None:
                    inner.weight.set_value(
                        quant_dequant(inner.weight,
                                      q.scales().max()).numpy())
                setattr(model, name, inner)
            else:
                self.convert(child, inplace=True)
        return model
