"""PTQ (reference python/paddle/quantization/ptq.py) — insert observers,
calibrate, convert to quantized weights."""

from __future__ import annotations

from ..nn.layer.layers import Layer
from .config import QuantConfig
from .qat import QuantedLayer

__all__ = ["PTQ"]


class PTQ:
    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        """Insert observers on configured layers; run calibration batches
        through the returned model."""
        for name, child in list(model.named_children()):
            cfg = self.config.config_for(child, name)
            if cfg is not None:
                act, w = cfg
                setattr(model, name, QuantedLayer(child, act, w))
            else:
                self.quantize(child, inplace=True)
        return model

    def convert(self, model: Layer, inplace: bool = False,
                deploy: bool = False, weight_dtype: str = "int8"
                ) -> Layer:
        """Apply observed scales.  ``deploy=True`` produces
        :class:`~paddle_tpu.quantization.QuantizedLinear` layers with
        real integer weights (weight_only_linear path); default bakes
        fake-quantized fp weights and removes observers."""
        if deploy:
            from .export import convert_to_deploy
            return convert_to_deploy(model, weight_dtype)
        from .export import bake_fake_quant
        for name, child in list(model.named_children()):
            if isinstance(child, QuantedLayer):
                bake_fake_quant(child.inner, child.weight_quanter)
                setattr(model, name, child.inner)
            else:
                self.convert(child, inplace=True)
        return model
