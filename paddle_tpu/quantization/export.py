"""Quantized deployment: convert → QuantizedLinear (int8/int4 weights +
scales feeding the weight_only_linear Pallas path) → jit.save → Predictor.

Reference: python/paddle/quantization/quantize.py convert + nn/quant
quantized_linear deploy layers + slim export.  This closes the loop the
VERDICT flagged: quantize → save → serve, with the served graph reading
int8 weights directly (half/quarter the HBM bytes of bf16 — the actual
TPU win of quantization)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.common import Linear
from ..nn.layer.layers import Layer
from .qat import QuantedLayer
from .quanters import quant_dequant

__all__ = ["QuantizedLinear", "convert_to_deploy", "export_quantized"]


class QuantizedLinear(Layer):
    """Deploy-time linear over quantized weights: holds the int8 (or
    packed-int4) weight and per-channel scales as BUFFERS; forward runs
    ``weight_only_linear`` (Pallas streaming-dequant matmul on TPU)."""

    def __init__(self, weight_q, weight_scale, bias=None,
                 weight_dtype: str = "int8"):
        super().__init__()
        self.weight_dtype = weight_dtype
        self.register_buffer("weight_q", Tensor(weight_q))
        self.register_buffer("weight_scale", Tensor(weight_scale))
        if bias is not None:
            self.bias = self.create_parameter(
                list(bias.shape), is_bias=True)
            self.bias.set_value(getattr(bias, "_value", bias))
        else:
            self.bias = None

    @classmethod
    def from_linear(cls, linear: Linear, weight_dtype: str = "int8",
                    thresholds=None) -> "QuantizedLinear":
        """``thresholds``: calibrated per-channel (or scalar) absmax from
        the weight observer/quanter — when given (int8 only), it REPLACES
        the raw-weight absmax so outlier clipping from calibration
        survives into deployment."""
        if weight_dtype not in ("int8", "int4"):
            raise ValueError(
                f"weight_dtype must be 'int8' or 'int4', got "
                f"{weight_dtype!r}")
        if thresholds is not None and weight_dtype == "int8":
            w = jnp.asarray(linear.weight._value, jnp.float32)   # [K, N]
            th = jnp.asarray(getattr(thresholds, "_value", thresholds),
                             jnp.float32).reshape(-1)
            if th.size not in (1, w.shape[-1]):
                # e.g. a group-wise [K/g, N] grid: the flat int8 deploy
                # path can't consume it — fall back to raw absmax
                return cls.from_linear(linear, weight_dtype)
            scale = jnp.maximum(jnp.broadcast_to(th, (w.shape[-1],)),
                                1e-8) / 127.0
            wq = jnp.clip(jnp.round(w / scale), -127, 127).astype(
                jnp.int8)
            return cls(wq, scale, bias=getattr(linear, "bias", None),
                       weight_dtype="int8")
        from ..nn.quant import weight_quantize
        algo = "weight_only_int8" if weight_dtype == "int8" \
            else "weight_only_int4"
        wq, scale = weight_quantize(linear.weight, algo=algo)
        return cls(getattr(wq, "_value", wq),
                   getattr(scale, "_value", scale),
                   bias=getattr(linear, "bias", None),
                   weight_dtype=weight_dtype)

    def forward(self, x):
        from ..nn.quant import weight_only_linear
        return weight_only_linear(x, self.weight_q, self.bias,
                                  self.weight_scale,
                                  weight_dtype=self.weight_dtype)


def _quanter_thresholds(q):
    """Calibrated absmax threshold(s) from a quanter/observer, or None."""
    if q is None or not hasattr(q, "scales"):
        return None
    try:
        s = q.scales()
    except NotImplementedError:
        return None
    return s


def _quanter_bits(q, default: int = 8) -> int:
    return int(getattr(q, "quant_bits", getattr(q, "bit_length",
                                                default)))


def bake_fake_quant(inner: Layer, q) -> None:
    """THE single bake path (qat/ptq non-deploy convert delegate here):
    overwrite ``inner.weight`` with its fake-quantized value at the
    quanter's calibrated scale (falling back to raw absmax)."""
    if q is None or not hasattr(inner, "weight"):
        return
    th = _quanter_thresholds(q)
    if th is not None:
        s = float(jnp.max(jnp.asarray(getattr(th, "_value", th))))
    else:
        s = float(jnp.max(jnp.abs(inner.weight._value)))
    inner.weight.set_value(
        quant_dequant(inner.weight, Tensor(jnp.float32(max(s, 1e-9))),
                      bit_length=_quanter_bits(q))._value)


def convert_to_deploy(model: Layer,
                      weight_dtype: str = "int8") -> Layer:
    """Walk the model; every :class:`QuantedLayer` wrapping a Linear
    becomes a :class:`QuantizedLinear` with real integer weights (at the
    weight quanter's CALIBRATED scales when it has them); other quanted
    layers get their fake-quant baked into fp weights (the reference
    convert() fallback).  Observers disappear."""
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be 'int8' or 'int4', got "
                         f"{weight_dtype!r}")
    for name, child in list(model.named_children()):
        if isinstance(child, QuantedLayer):
            inner = child.inner
            if isinstance(inner, Linear):
                th = _quanter_thresholds(child.weight_quanter) \
                    if weight_dtype == "int8" else None
                setattr(model, name,
                        QuantizedLinear.from_linear(inner, weight_dtype,
                                                    thresholds=th))
                continue
            bake_fake_quant(inner, child.weight_quanter)
            setattr(model, name, inner)
        else:
            convert_to_deploy(child, weight_dtype)
    return model


def export_quantized(model: Layer, path: str, input_spec,
                     weight_dtype: str = "int8") -> Layer:
    """convert → jit.save: the serialized program reads int8 weights +
    scales (Predictor/jit.load serve it without any quantization code)."""
    deploy = convert_to_deploy(model, weight_dtype)
    deploy.eval()
    from .. import jit
    jit.save(deploy, path, input_spec=input_spec)
    return deploy
