from .dataloader import (  # noqa: F401
    DataLoader, default_collate_fn, device_prefetch_iterator,
)
from .worker import WorkerInfo, get_worker_info  # noqa: F401
from .dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler,
)
