"""DataLoader (reference: python/paddle/io/reader.py:262 — multiprocess
worker pool + blocking queue + pin-memory).

TPU-native host pipeline: worker threads/processes produce numpy batches, a
background prefetcher keeps a bounded queue full and (optionally) stages
batches onto device ahead of compute — replacing the reference's C++
buffered readers.  The native (C) double-buffered batch assembler lives in
paddle_tpu/native (used automatically when built).
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, List, Optional

import numpy as np

from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler
from ..observability import REGISTRY as _METRICS

__all__ = ["DataLoader", "default_collate_fn", "device_prefetch_iterator"]


def _prefetch_sharding(explicit=None):
    """Sharding for staged batches: the explicitly-passed one, else the
    active ``parallel`` topology's data-parallel sharding (batch dim
    split over dp+sharding axes) when a multi-device topology has been
    initialized, else None (commit to the default device)."""
    if explicit is not None:
        return explicit
    from ..parallel import topology as _topo
    t = _topo._topology
    if t is not None and t.world_size > 1:
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(t.mesh, PartitionSpec(t.data_axes()))
    return None


class _DevicePrefetcher:
    """Bounded background thread that stages the next N host batches onto
    device (``jax.device_put``) so host→device transfer overlaps step
    execution.  Yields batches IN ORDER; ``close()`` (or abandoning the
    iterator mid-epoch) wakes and joins the producer thread.

    Robustness contract (ISSUE 2): transient staging failures (device
    transfer hiccups — RuntimeError/OSError and jax runtime errors) are
    retried with bounded exponential backoff before propagating; a
    producer exception surfaces on the CONSUMER thread exactly once (the
    iterator then terminates — it does not re-raise on every
    subsequent ``next``); ``close()`` is idempotent and join-safe."""

    _END = object()
    #: transient-staging retry schedule: attempt k sleeps BACKOFF_BASE*2^k
    STAGE_RETRIES = 3
    BACKOFF_BASE = 0.05

    _RETRYABLE = (RuntimeError, OSError)

    def __init__(self, produce, size: int, sharding=None,
                 convert: Optional[Callable] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(size)))
        self._closed = threading.Event()
        self._close_lock = threading.Lock()
        self._joined = False
        # _exc crosses the producer→consumer thread boundary: the
        # producer stores, the consumer swaps it out (take-once).  Both
        # must happen under _exc_lock or a concurrent close/next can
        # lose the exception and truncate the epoch silently.
        self._exc_lock = threading.Lock()
        self._exc: Optional[BaseException] = None
        self._sharding = sharding
        self._convert = convert
        self._thread = threading.Thread(
            target=self._worker, args=(produce,), daemon=True)
        self._thread.start()

    # -- producer side -------------------------------------------------
    def _stage(self, item):
        import jax

        sh = self._sharding
        if sh is None:
            # no sharding: still COMMIT to the default device (a bare
            # device_put leaves the array uncommitted and the transfer
            # can be deferred to first use — the opposite of prefetch)
            sh = jax.local_devices()[0]
            self._sharding = sh

        def put(x):
            if isinstance(x, np.ndarray):
                if x.dtype == np.float64:
                    x = x.astype(np.float32)
                return jax.device_put(x, sh)
            if hasattr(x, "_value"):        # Tensor
                x._value = jax.device_put(x._value, sh)
                return x
            if isinstance(x, jax.Array):
                return jax.device_put(x, sh)
            return x

        if isinstance(item, (tuple, list)):
            return type(item)(self._stage(b) for b in item)
        if isinstance(item, dict):
            return {k: self._stage(v) for k, v in item.items()}
        return put(item)

    def _enqueue(self, item) -> bool:
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _stage_with_retry(self, item):
        """Retry transient staging failures with bounded exponential
        backoff; give up (and propagate) after STAGE_RETRIES attempts or
        on a non-transient error type."""
        import time

        attempt = 0
        while True:
            try:
                return self._stage(item)
            except self._RETRYABLE:
                if attempt >= self.STAGE_RETRIES or self._closed.is_set():
                    if _METRICS.enabled:
                        _METRICS.counter(
                            "io.prefetch_stage_failures_total").inc()
                    raise
                if _METRICS.enabled:
                    _METRICS.counter("io.prefetch_retries_total",
                                     desc="transient staging retries"
                                     ).inc()
                time.sleep(self.BACKOFF_BASE * (2 ** attempt))
                attempt += 1

    def _worker(self, produce):
        try:
            for item in produce():
                if self._convert is not None:
                    item = self._convert(item)
                if not self._enqueue(self._stage_with_retry(item)):
                    return                   # consumer closed early
        except BaseException as e:           # propagate to consumer
            with self._exc_lock:
                self._exc = e
        finally:
            self._enqueue(self._END)

    # -- consumer side -------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed.is_set():
            raise StopIteration
        if _METRICS.enabled:
            # queue depth BEFORE the blocking get: 0 here means the
            # consumer is about to stall on the producer (prefetch is
            # not keeping up); wait_secs measures that stall directly
            import time as _time
            _METRICS.gauge("io.prefetch_queue_depth").set(self._q.qsize())
            t0 = _time.perf_counter()
            item = self._q.get()
            _METRICS.histogram("io.prefetch_wait_secs", unit="s",
                               desc="consumer wait on staged batches"
                               ).record(_time.perf_counter() - t0)
        else:
            item = self._q.get()
        if item is self._END:
            self.close()
            with self._exc_lock:
                exc, self._exc = self._exc, None
            if exc is not None:
                raise exc        # exactly once; later nexts StopIterate
            raise StopIteration
        return item

    def close(self) -> None:
        """Mid-epoch shutdown: wake the (possibly blocked) producer,
        drain the queue, and join the thread.  Idempotent (second close
        is a no-op) and join-safe (never joins the current thread, and
        never joins the same thread twice)."""
        self._closed.set()
        with self._close_lock:
            if self._joined:
                return
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            if self._thread is threading.current_thread():
                return
            self._thread.join(timeout=5.0)
            self._joined = not self._thread.is_alive()

    # Deliberate best-effort backstop for abandoned iterators: close()
    # is idempotent, never joins the current thread, and bounds the
    # join — acceptable to run from a finalizer.
    def __del__(self):  # locklint: disable=LK005
        try:
            self.close()
        # finalizer racing interpreter shutdown: anything may be torn down
        except Exception:  # tracelint: disable=TL006
            pass


def device_prefetch_iterator(iterable, size: int = 2, sharding=None):
    """Stage batches from any host iterable onto device ``size`` batches
    ahead of the consumer (used by ``DataLoader(device_prefetch=N)`` and
    the bench harness).  ``sharding`` defaults to the active parallel
    topology's data sharding when one is initialized."""
    return _DevicePrefetcher(lambda: iter(iterable), size,
                             sharding=_prefetch_sharding(sharding))


def default_collate_fn(batch: List[Any]):
    """Stack a list of samples into batched numpy arrays (reference:
    io/dataloader/collate.py).  Large contiguous samples are assembled by
    the native C++ collate (threaded memcpy, GIL-free)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        from .. import native
        return native.collate_stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if hasattr(sample, "_value"):  # Tensor
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(col)) for col in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class _PrefetchIterator:
    """Producer thread fills a bounded ring ahead of the consumer.  The
    handoff uses the native C++ TokenRing when built (blocking waits drop
    the GIL; batches ride a slot table keyed by token), with a pure-Python
    queue fallback inside TokenRing itself."""

    def __init__(self, produce, num_prefetch: int, to_tensor: Callable):
        from .. import native
        cap = max(num_prefetch, 1)
        self._ring = native.TokenRing(cap)
        self._slots: dict = {}
        self._slots_lock = threading.Lock()
        self._to_tensor = to_tensor
        self._exc: Optional[BaseException] = None

        def worker():
            token = 0
            try:
                for item in produce():
                    with self._slots_lock:
                        self._slots[token] = item
                    if not self._ring.push(token):
                        return  # consumer closed early
                    token += 1
            except BaseException as e:  # propagate to consumer
                # _slots_lock doubles as the _exc guard: the consumer
                # swaps it out under the same lock (take-once handoff)
                with self._slots_lock:
                    self._exc = e
            finally:
                self._ring.close()

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def close(self):
        """Consumer-side shutdown: wake a possibly-blocked producer, wait
        for it to exit, and only then let the native ring be destroyed
        (prevents use-after-free on early iteration abandonment).
        Idempotent and join-safe."""
        self._ring.close()
        if self._thread is threading.current_thread():
            return
        self._thread.join(timeout=2.0)
        if self._thread.is_alive():
            # producer stuck: leak the native ring rather than free it
            # under a live waiter
            self._ring.leak()

    # Deliberate best-effort backstop: close() is idempotent and its
    # join is bounded; skipping it would use-after-free the native ring
    # when an iterator is abandoned mid-epoch.
    def __del__(self):  # locklint: disable=LK005
        try:
            self.close()
        # finalizer racing interpreter shutdown: anything may be torn down
        except Exception:  # tracelint: disable=TL006
            pass

    def __iter__(self):
        return self

    def __next__(self):
        token = self._ring.pop()
        if token is None:
            self.close()
            with self._slots_lock:
                exc, self._exc = self._exc, None
            if exc is not None:
                raise exc        # exactly once; later nexts StopIterate
            raise StopIteration
        with self._slots_lock:
            item = self._slots.pop(token)
        return self._to_tensor(item)


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list: bool = True, batch_sampler: Optional[BatchSampler] = None,
                 batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, collate_fn: Optional[Callable] = None,
                 num_workers: int = 0, use_buffer_reader: bool = True,
                 prefetch_factor: int = 2, use_shared_memory: bool = True,
                 timeout: int = 0, worker_init_fn: Optional[Callable] = None,
                 persistent_workers: bool = False, device_prefetch: int = 0,
                 device_prefetch_sharding=None):
        self.dataset = dataset
        # stage the next N batches onto device in a background thread so
        # host→device transfer overlaps step compute (0 = off)
        self.device_prefetch = device_prefetch
        self.device_prefetch_sharding = device_prefetch_sharding
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self._pool = None                 # lazily-built WorkerPool
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if self._iterable_ds:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_ds:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------------
    def _new_pool(self):
        from .worker import WorkerPool
        return WorkerPool(
            self.dataset, self.collate_fn, self.num_workers,
            use_shared_memory=self.use_shared_memory,
            worker_init_fn=self.worker_init_fn, timeout=self.timeout,
            iterable=self._iterable_ds)

    def _get_pool(self):
        # one pool serves ONE live epoch: concurrent iterators must not
        # share a result queue (their batch indices would interleave), so
        # a busy persistent pool spawns a dedicated throwaway sibling
        if self._pool is None:
            self._pool = self._new_pool()
        if getattr(self._pool, "_in_epoch", False):
            return self._new_pool()
        return self._pool

    def _release_pool(self):
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None

    # Deliberate best-effort backstop: _release_pool() forwards to the
    # worker pool's idempotent shutdown (bounded joins + terminate
    # fallback) so abandoned loaders don't leak worker processes.
    def __del__(self):  # locklint: disable=LK005
        try:
            self._release_pool()
        # finalizer racing interpreter shutdown: anything may be torn down
        except Exception:  # tracelint: disable=TL006
            pass

    def _produce_batches(self):
        if self.num_workers > 0:
            # subprocess workers (reference reader.py:262 multiprocess
            # mode): index-fed, shared-memory transport, sampler order
            pool = self._get_pool()
            dedicated = pool is not self._pool
            if self._iterable_ds:
                # each worker owns a stream shard (get_worker_info-style);
                # feed per-worker batch-size tasks round-robin
                def sizes():
                    while True:
                        yield self.batch_size
                index_iter = sizes()
            else:
                index_iter = iter(self.batch_sampler)
            try:
                yield from pool.run_epoch(index_iter, self.prefetch_factor,
                                          drop_last=(self.drop_last
                                                     if self._iterable_ds
                                                     else False))
            finally:
                if dedicated:
                    pool.shutdown()
                elif not self.persistent_workers:
                    self._release_pool()
        elif self._iterable_ds:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch or (self.drop_last
                                 and len(batch) < self.batch_size):
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def _to_tensors(self, batch):
        from ..core.tensor import Tensor

        def conv(x):
            if isinstance(x, np.ndarray):
                if x.dtype == np.float64:
                    x = x.astype(np.float32)
                return Tensor(x)
            return x

        if isinstance(batch, (tuple, list)):
            return type(batch)(conv(b) for b in batch)
        if isinstance(batch, dict):
            return {k: conv(v) for k, v in batch.items()}
        return conv(batch)

    def __iter__(self):
        if self.num_workers > 0:
            # fork the worker pool from the MAIN thread (forking from the
            # prefetch thread deadlocks: the child inherits locks held by
            # sibling threads — queue feeders, jax internals).  The pool
            # prefetches across processes itself, so the extra thread
            # prefetcher adds nothing here.
            self._get_pool()
            if self.device_prefetch > 0:
                return _DevicePrefetcher(
                    self._produce_batches, self.device_prefetch,
                    sharding=_prefetch_sharding(
                        self.device_prefetch_sharding),
                    convert=self._to_tensors)
            return (self._to_tensors(b) for b in self._produce_batches())
        if self.device_prefetch > 0:
            # the device prefetcher pulls host batches ahead itself, so it
            # subsumes the host-side _PrefetchIterator
            return _DevicePrefetcher(
                self._produce_batches, self.device_prefetch,
                sharding=_prefetch_sharding(self.device_prefetch_sharding),
                convert=self._to_tensors)
        if self.use_buffer_reader:
            return _PrefetchIterator(self._produce_batches,
                                     self.prefetch_factor * max(
                                         self.num_workers, 1),
                                     self._to_tensors)
        return (self._to_tensors(b) for b in self._produce_batches())
