"""Datasets (reference: python/paddle/io/dataset.py)."""

from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ConcatDataset",
           "ChainDataset", "ComposeDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        from ..core.tensor import Tensor
        self.tensors = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                        for t in tensors]
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t.numpy()[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ConcatDataset(Dataset):
    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx = len(self) + idx
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Iterable[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Sequence[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence, generator=None):
    total = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        counts = [int(np.floor(total * f)) for f in lengths]
        for i in range(total - sum(counts)):
            counts[i % len(counts)] += 1
        lengths = counts
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    from ..core.rng import default_generator
    import jax
    key = (generator or default_generator()).split()
    perm = np.asarray(jax.random.permutation(key, total))
    out, start = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[start:start + n].tolist()))
        start += n
    return out
