"""Multiprocess DataLoader workers (reference: python/paddle/io/reader.py:262
+ python/paddle/io/dataloader/worker.py — subprocess workers, worker seeds,
shared-memory batch transport, persistent_workers).

Design: a shared index queue feeds forked worker processes; each worker maps
``indices -> collate_fn([dataset[i]])`` with NumPy only (no JAX in workers —
the device belongs to the trainer process), ships the batch back over a
result queue, large arrays riding POSIX shared memory instead of the pipe.
The parent reorders by batch index so iteration order matches the sampler.
Forked workers + SHM is the TPU-host analog of the reference's C++ shared
-memory LoDTensor transport (use_shared_memory=True default there too).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue as _queue
import sys
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

__all__ = ["get_worker_info", "WorkerInfo"]

_SHM_MIN_BYTES = 1 << 16          # arrays smaller than 64 KiB ride the pipe


@dataclass
class WorkerInfo:
    """Visible to dataset code inside a worker (reference:
    io/dataloader/worker.py WorkerInfo: id/num_workers/seed/dataset)."""
    id: int
    num_workers: int
    seed: int
    dataset: Any


_worker_info: Optional[WorkerInfo] = None


def get_worker_info() -> Optional[WorkerInfo]:
    """None in the main process; the worker's WorkerInfo inside a worker
    (reference: paddle.io.get_worker_info)."""
    return _worker_info


class _ExcInfo:
    """Picklable carrier for a worker-side exception."""

    def __init__(self, exc: BaseException):
        self.type_name = type(exc).__name__
        self.msg = str(exc)
        self.tb = traceback.format_exc()

    def reraise(self):
        raise RuntimeError(
            f"DataLoader worker raised {self.type_name}: {self.msg}\n"
            f"--- worker traceback ---\n{self.tb}")


# ---------------------------------------------------------------------------
# shared-memory batch transport
# ---------------------------------------------------------------------------

def _shm_pack(obj, segments):
    """Replace large ndarrays with shared-memory descriptors; collect the
    created segments so the worker can close its handles after send."""
    from multiprocessing import shared_memory

    if isinstance(obj, np.ndarray) and obj.nbytes >= _SHM_MIN_BYTES:
        seg = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        np.ndarray(obj.shape, obj.dtype, buffer=seg.buf)[...] = obj
        segments.append(seg)
        return ("__shm__", seg.name, obj.shape, str(obj.dtype))
    if isinstance(obj, tuple):
        return tuple(_shm_pack(v, segments) for v in obj)
    if isinstance(obj, list):
        return [_shm_pack(v, segments) for v in obj]
    if isinstance(obj, dict):
        return {k: _shm_pack(v, segments) for k, v in obj.items()}
    return obj


def _shm_unpack(obj):
    from multiprocessing import shared_memory

    if isinstance(obj, tuple):
        if len(obj) == 4 and obj[0] == "__shm__":
            _, name, shape, dtype = obj
            seg = shared_memory.SharedMemory(name=name)
            try:
                return np.ndarray(shape, np.dtype(dtype),
                                  buffer=seg.buf).copy()
            finally:
                seg.close()
                try:
                    seg.unlink()
                except FileNotFoundError:
                    pass
        return tuple(_shm_unpack(v) for v in obj)
    if isinstance(obj, list):
        return [_shm_unpack(v) for v in obj]
    if isinstance(obj, dict):
        return {k: _shm_unpack(v) for k, v in obj.items()}
    return obj


# ---------------------------------------------------------------------------
# worker loop
# ---------------------------------------------------------------------------

def _worker_loop(dataset, collate_fn, index_q, result_q, worker_id,
                 num_workers, base_seed, worker_init_fn, use_shared_memory,
                 iterable):
    global _worker_info
    seed = base_seed + worker_id
    np.random.seed(seed % (2 ** 32))
    import random
    random.seed(seed)
    _worker_info = WorkerInfo(worker_id, num_workers, seed, dataset)
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        ds_iter = None
        cur_epoch = -1
        while True:
            task = index_q.get()
            if task is None:
                break
            bidx, indices, epoch, drop_last = task
            try:
                if iterable:
                    if epoch != cur_epoch:
                        # fresh stream per epoch (persistent_workers keeps
                        # the process; the reference re-creates the
                        # iterator each epoch too)
                        ds_iter = iter(dataset)
                        cur_epoch = epoch
                    batch = []
                    for _ in range(indices):          # indices = batch size
                        try:
                            batch.append(next(ds_iter))
                        except StopIteration:
                            break
                    if not batch or (drop_last and len(batch) < indices):
                        result_q.put((bidx, "__iter_end__", worker_id))
                        continue
                    out = collate_fn(batch)
                else:
                    out = collate_fn([dataset[i] for i in indices])
                segments = []
                if use_shared_memory:
                    out = _shm_pack(out, segments)
                result_q.put((bidx, out, worker_id))
                for seg in segments:
                    seg.close()                        # parent unlinks
            except Exception as e:  # per-batch errors propagate to parent
                result_q.put((bidx, _ExcInfo(e), worker_id))
    except KeyboardInterrupt:
        pass


# ---------------------------------------------------------------------------
# parent-side pool
# ---------------------------------------------------------------------------

class WorkerPool:
    """Owns the worker processes + queues; yields batches in sampler order.

    persistent_workers=True keeps processes alive across epochs (reference
    reader.py persistent_workers); otherwise the pool is torn down when an
    epoch's iterator is exhausted or closed.
    """

    def __init__(self, dataset, collate_fn: Callable, num_workers: int,
                 use_shared_memory: bool = True,
                 worker_init_fn: Optional[Callable] = None,
                 timeout: float = 0, iterable: bool = False):
        self._ctx = mp.get_context("fork" if sys.platform != "win32"
                                   else "spawn")
        # start the parent's resource tracker BEFORE forking so every
        # worker shares it: create(+)/attach(+, set no-op)/unlink(-) then
        # balance in ONE tracker instead of leaking per-worker trackers
        try:
            from multiprocessing import resource_tracker
            resource_tracker.ensure_running()
        except (ImportError, OSError):
            pass    # no tracker: workers fall back to per-process ones
        self._num_workers = num_workers
        self._timeout = timeout or None
        self._iterable = iterable
        # one index queue PER worker (reference reader.py worker loop):
        # a shared queue lets one fast worker starve the others — fatal
        # for IterableDataset stream sharding, where each worker owns a
        # distinct shard of the data
        self._index_qs = [self._ctx.Queue() for _ in range(num_workers)]
        self._result_q = self._ctx.Queue()
        base_seed = int.from_bytes(os.urandom(4), "little")
        self._procs = []
        for wid in range(num_workers):
            p = self._ctx.Process(
                target=_worker_loop,
                args=(dataset, collate_fn, self._index_qs[wid],
                      self._result_q, wid, num_workers, base_seed,
                      worker_init_fn, use_shared_memory, iterable),
                daemon=True)
            p.start()
            self._procs.append(p)
        self._alive = True
        self._epoch = -1
        self._in_epoch = False

    # -- epoch iteration --------------------------------------------------
    def run_epoch(self, index_iter, prefetch: int, drop_last: bool = False):
        """Feed index batches, yield collated batches in order.  Guarantees
        no in-flight task survives into the next epoch (a finally-drain
        covers early exits — consumer break, iterable end — so persistent
        workers can't cross-contaminate batch indices across epochs)."""
        self._epoch += 1
        self._in_epoch = True
        epoch = self._epoch
        reorder: dict = {}
        next_out = 0
        next_in = 0
        received = 0
        exhausted = False
        ended_workers = set()

        def feed_one():
            nonlocal next_in, exhausted
            if exhausted:
                return False
            try:
                idx = next(index_iter)
            except StopIteration:
                exhausted = True
                return False
            self._index_qs[next_in % self._num_workers].put(
                (next_in, idx, epoch, drop_last))
            next_in += 1
            return True

        def get_result(user_timeout):
            """Poll the result queue in short slices so dead workers are
            detected instead of blocking forever (timeout=0 -> unbounded
            user wait but still supervised)."""
            waited = 0.0
            while True:
                try:
                    return self._result_q.get(timeout=5.0)
                except _queue.Empty:
                    self._check_workers()
                    waited += 5.0
                    if user_timeout and waited >= user_timeout:
                        raise RuntimeError(
                            f"DataLoader timed out after {waited:.0f}s "
                            "waiting for a worker batch")

        try:
            for _ in range(max(prefetch, 1) * self._num_workers):
                if not feed_one():
                    break

            while next_out < next_in:
                bidx, payload, wid = get_result(self._timeout)
                received += 1
                if isinstance(payload, _ExcInfo):
                    payload.reraise()
                if isinstance(payload, str) and payload == "__iter_end__":
                    ended_workers.add(wid)
                    reorder[bidx] = None
                else:
                    reorder[bidx] = _shm_unpack(payload)
                while next_out in reorder:
                    item = reorder.pop(next_out)
                    next_out += 1
                    feed_one()
                    if item is not None:
                        yield item
                if self._iterable and \
                        len(ended_workers) >= self._num_workers:
                    break
        finally:
            # drain every outstanding task so SHM segments are unlinked and
            # the next epoch starts from an empty result queue
            self._drain(next_in - received)
            self._in_epoch = False

    def _drain(self, outstanding: int):
        import time
        deadline = time.time() + 30
        while outstanding > 0 and time.time() < deadline:
            try:
                _, payload, _ = self._result_q.get(timeout=1.0)
            except _queue.Empty:
                if not any(p.is_alive() for p in self._procs):
                    break
                continue
            if not isinstance(payload, (_ExcInfo, str)):
                _shm_unpack(payload)       # attach+copy+unlink, then drop
            outstanding -= 1

    def _check_workers(self):
        dead = [p.pid for p in self._procs if not p.is_alive()]
        if dead:
            raise RuntimeError(
                f"DataLoader worker(s) {dead} exited unexpectedly")

    # -- shutdown ---------------------------------------------------------
    def shutdown(self):
        if not self._alive:
            return
        self._alive = False
        try:
            for q in self._index_qs:
                q.put(None)
            for p in self._procs:
                p.join(timeout=5)
            for p in self._procs:
                if p.is_alive():
                    p.terminate()
            # unlink SHM of any never-delivered batches
            while True:
                try:
                    _, payload, _ = self._result_q.get_nowait()
                except _queue.Empty:
                    break
                if not isinstance(payload, (_ExcInfo, str)):
                    try:
                        _shm_unpack(payload)
                    except (OSError, ValueError):
                        pass    # segment already unlinked by the worker
        finally:
            for q in self._index_qs:
                q.close()
            self._result_q.close()

    # Deliberate best-effort backstop: shutdown() is idempotent, its
    # joins are bounded with a terminate() fallback, and it unlinks the
    # shared-memory segments of undelivered batches — skipping it on an
    # abandoned pool would leak worker processes AND /dev/shm segments.
    def __del__(self):  # locklint: disable=LK005
        try:
            self.shutdown()
        # finalizer racing interpreter shutdown: anything may be torn down
        except Exception:  # tracelint: disable=TL006
            pass
