"""Samplers (reference: python/paddle/io/sampler.py,
batch_sampler.py; DistributedBatchSampler from
python/paddle/io/dataloader/batch_sampler.py — shards indices across dp
ranks)."""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler", "BatchSampler",
           "SubsetRandomSampler", "WeightedRandomSampler",
           "DistributedBatchSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self) -> Iterator[int]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement: bool = False,
                 num_samples: Optional[int] = None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng(self._seed())
        if self.replacement:
            yield from rng.integers(0, n, self.num_samples).tolist()
        else:
            from .. import native
            perm = native.shuffle_indices(n, int(rng.integers(2 ** 62)))
            yield from perm[:self.num_samples].tolist()

    def _seed(self):
        import jax
        from ..core.rng import default_generator
        gen = self.generator if self.generator is not None else default_generator()
        key = gen.split()
        return int(np.asarray(jax.random.key_data(key)).ravel()[-1]) & 0x7FFFFFFF

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices: Sequence[int], generator=None):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        perm = np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in perm])

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights: Sequence[float], num_samples: int,
                 replacement: bool = True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler: Optional[Sampler] = None,
                 shuffle: bool = False, batch_size: int = 1,
                 drop_last: bool = False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self) -> Iterator[List[int]]:
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the index space across data-parallel ranks (reference:
    io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None, shuffle: bool = False,
                 drop_last: bool = False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..parallel.env import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        n = len(dataset)
        self.num_samples = int(np.ceil(n / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        # pad to make evenly divisible
        indices += indices[: self.total_size - len(indices)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size
