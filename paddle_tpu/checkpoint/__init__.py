"""Fault-tolerance subsystem (ISSUE 2): atomic/async checkpointing with a
verified ``latest`` pointer, retention, auto-resume payload helpers, and
jit-compatible anomaly step-guards.

Reference semantics: ``paddle.save``/fleet checkpointing +
``GradScaler``'s check_finite/update_loss_scaling skip-step machinery,
rebuilt TPU-native: checkpoints are single atomic archives
(framework/io.py) published under a manager that rotates old ones and
only ever advances ``latest`` to a checksum-verified file; the async
variant snapshots device state to host in the caller's thread and does
disk I/O on ONE bounded background thread (the dataloader-prefetcher
idiom); the step guard skips non-finite updates inside the compiled
train step via where-select so donated buffers stay untouched.
"""

from .manager import (CheckpointManager, latest_checkpoint,
                      LATEST_POINTER, CKPT_PREFIX, CKPT_SUFFIX)
from .async_checkpointer import AsyncCheckpointer
from .step_guard import (NonFiniteError, StepGuard, guard_select,
                         nonfinite_guard)
from ..framework.io import CheckpointCorruptError


class TrainingPreempted(RuntimeError):
    """SIGTERM arrived during ``Model.fit`` with checkpointing active: a
    final checkpoint was flushed to disk before this was raised.  Restart
    the job and call ``fit(resume="auto")`` to continue."""


__all__ = [
    "CheckpointManager", "AsyncCheckpointer", "latest_checkpoint",
    "CheckpointCorruptError", "NonFiniteError", "StepGuard",
    "guard_select", "nonfinite_guard", "TrainingPreempted",
    "LATEST_POINTER", "CKPT_PREFIX", "CKPT_SUFFIX",
]
