"""Anomaly step-guards: skip non-finite updates inside the compiled
train step.

Reference semantics: ``check_finite_and_unscale`` +
``update_loss_scaling`` (python/paddle/amp/grad_scaler.py) — a step whose
loss or gradients contain NaN/Inf must not touch params or optimizer
moments, must back off the dynamic loss scale, and repeated occurrences
must abort with a diagnosis instead of silently training on garbage.

TPU-native shape: the check and the skip both live INSIDE the jitted
step.  ``nonfinite_guard`` reduces loss+grads to one boolean scalar;
``guard_select`` where-selects every output leaf between the computed
update and the carried-in state.  A select keeps the program a single
branch-free XLA executable (no retrace, donation-safe: XLA may alias the
output to either operand) — exactly the ``lax.cond``-free formulation
the fused optimizer's donated flat buffers need, since params and
moments then come back bit-identical on a skipped step.

Host side, :class:`StepGuard` counts consecutive skips and raises
:class:`NonFiniteError` past a threshold.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["NonFiniteError", "StepGuard", "nonfinite_guard",
           "guard_select"]


class NonFiniteError(FloatingPointError):
    """Training diverged: too many consecutive steps produced a
    non-finite loss or gradients and were skipped."""


def nonfinite_guard(loss, grads) -> jax.Array:
    """Scalar bool: True when ``loss`` and every gradient element are
    finite (the update may be applied).  jit-compatible; grads may be any
    pytree.  Uses all-isfinite rather than an isfinite(norm) check so a
    large-but-finite gradient whose SQUARE overflows is not a false
    positive."""
    ok = jnp.isfinite(jnp.asarray(loss)).all()
    for g in jax.tree_util.tree_leaves(grads):
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            ok = ok & jnp.isfinite(g).all()
    return ok


def guard_select(ok, new_tree, old_tree):
    """``new_tree`` where ``ok`` else ``old_tree``, leaf-wise.  Both trees
    must share structure/dtypes; with ``ok`` scalar this lowers to one
    select per leaf and is donation-safe."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree)


class StepGuard:
    """Host-side skip accounting for the in-graph guard.

    ``record(skipped)`` after each step; raises :class:`NonFiniteError`
    once ``max_consecutive`` skips occur back to back.  ``scaler`` (an
    ``amp.GradScaler``) is optional — when present, each skip counts as a
    found-inf step (backing off the dynamic loss scale) and each good
    step as a growth step."""

    def __init__(self, max_consecutive: int = 50, scaler=None):
        if max_consecutive < 1:
            raise ValueError("max_consecutive must be >= 1")
        self.max_consecutive = max_consecutive
        self.scaler = scaler
        self.consecutive = 0
        self.total_skipped = 0

    def record(self, skipped: bool, step: Optional[int] = None,
               loss: Any = None) -> None:
        if self.scaler is not None and self.scaler.is_enable():
            self.scaler._found_inf = bool(skipped)
            self.scaler.update()
        if not skipped:
            self.consecutive = 0
            return
        self.consecutive += 1
        self.total_skipped += 1
        if self.consecutive >= self.max_consecutive:
            where = f" at step {step}" if step is not None else ""
            lossmsg = f" (last loss: {loss})" if loss is not None else ""
            raise NonFiniteError(
                f"{self.consecutive} consecutive training steps{where} "
                f"produced non-finite loss or gradients and were skipped"
                f"{lossmsg}. The model state was NOT updated by any of "
                "them. Likely causes: learning rate too high, fp16 "
                "overflow with too large an initial loss scale, or bad "
                "input data. Lower the LR / loss scale, or raise "
                "max_consecutive_skips if spikes are expected.")

    def state_dict(self) -> dict:
        return {"consecutive": self.consecutive,
                "total_skipped": self.total_skipped}

    def load_state_dict(self, state: dict) -> None:
        self.consecutive = int(state.get("consecutive", 0))
        self.total_skipped = int(state.get("total_skipped", 0))
