"""Anomaly step-guards: skip non-finite updates inside the compiled
train step.

Reference semantics: ``check_finite_and_unscale`` +
``update_loss_scaling`` (python/paddle/amp/grad_scaler.py) — a step whose
loss or gradients contain NaN/Inf must not touch params or optimizer
moments, must back off the dynamic loss scale, and repeated occurrences
must abort with a diagnosis instead of silently training on garbage.

TPU-native shape: the check and the skip both live INSIDE the jitted
step.  ``nonfinite_guard`` reduces loss+grads to one boolean scalar;
``guard_select`` where-selects every output leaf between the computed
update and the carried-in state.  A select keeps the program a single
branch-free XLA executable (no retrace, donation-safe: XLA may alias the
output to either operand) — exactly the ``lax.cond``-free formulation
the fused optimizer's donated flat buffers need, since params and
moments then come back bit-identical on a skipped step.

Host side, :class:`StepGuard` counts consecutive skips and raises
:class:`NonFiniteError` past a threshold.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

__all__ = ["NonFiniteError", "StepGuard", "nonfinite_guard",
           "guard_select"]


class NonFiniteError(FloatingPointError):
    """Training diverged: too many consecutive steps produced a
    non-finite loss or gradients and were skipped."""


def nonfinite_guard(loss, grads) -> jax.Array:
    """Scalar bool: True when ``loss`` and every gradient element are
    finite (the update may be applied).  jit-compatible; grads may be any
    pytree.  Uses all-isfinite rather than an isfinite(norm) check so a
    large-but-finite gradient whose SQUARE overflows is not a false
    positive."""
    ok = jnp.isfinite(jnp.asarray(loss)).all()
    for g in jax.tree_util.tree_leaves(grads):
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            ok = ok & jnp.isfinite(g).all()
    return ok


def guard_select(ok, new_tree, old_tree):
    """``new_tree`` where ``ok`` else ``old_tree``, leaf-wise.  Both trees
    must share structure/dtypes; with ``ok`` scalar this lowers to one
    select per leaf and is donation-safe."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new_tree, old_tree)


class StepGuard:
    """Host-side skip accounting for the in-graph guard.

    ``record(skipped)`` after each step; raises :class:`NonFiniteError`
    once ``max_consecutive`` skips occur back to back.  ``scaler`` (an
    ``amp.GradScaler``) is optional — when present, each skip counts as a
    found-inf step (backing off the dynamic loss scale) and each good
    step as a growth step.

    ``metrics`` (an ``observability.MetricsRegistry``, optional) routes
    skip and loss-scale-backoff events through the telemetry layer —
    previously these only surfaced as the terminal raise after
    ``max_consecutive`` skips; with telemetry on, every skip is a
    counter increment plus an event record the flight recorder keeps."""

    def __init__(self, max_consecutive: int = 50, scaler=None,
                 metrics=None):
        if max_consecutive < 1:
            raise ValueError("max_consecutive must be >= 1")
        self.max_consecutive = max_consecutive
        self.scaler = scaler
        self.metrics = metrics
        self.consecutive = 0
        self.total_skipped = 0
        self.total_backoffs = 0

    def record(self, skipped: bool, step: Optional[int] = None,
               loss: Any = None) -> None:
        scale_before = None
        if self.scaler is not None and self.scaler.is_enable():
            scale_before = self.scaler.get_loss_scaling()
            self.scaler._found_inf = bool(skipped)
            self.scaler.update()
            if skipped and self.scaler.get_loss_scaling() < scale_before:
                self.total_backoffs += 1
                self._emit_backoff(step, scale_before)
        if not skipped:
            self.consecutive = 0
            return
        self.consecutive += 1
        self.total_skipped += 1
        self._emit_skip(step, loss)
        if self.consecutive >= self.max_consecutive:
            where = f" at step {step}" if step is not None else ""
            lossmsg = f" (last loss: {loss})" if loss is not None else ""
            raise NonFiniteError(
                f"{self.consecutive} consecutive training steps{where} "
                f"produced non-finite loss or gradients and were skipped"
                f"{lossmsg}. The model state was NOT updated by any of "
                "them. Likely causes: learning rate too high, fp16 "
                "overflow with too large an initial loss scale, or bad "
                "input data. Lower the LR / loss scale, or raise "
                "max_consecutive_skips if spikes are expected.")

    # -- telemetry (host-side; no-ops unless a registry is wired and
    # enabled, so the guarded step path costs one attribute check) ------
    def _emit_skip(self, step, loss) -> None:
        m = self.metrics
        if m is None or not m.enabled:
            return
        m.counter("train.skipped_steps_total",
                  desc="non-finite steps skipped by the guard").inc()
        m.gauge("train.consecutive_skips").set(self.consecutive)
        m.event("step_skip", step=step,
                loss=(None if loss is None else float(loss)),
                consecutive=self.consecutive,
                total_skipped=self.total_skipped)

    def _emit_backoff(self, step, scale_before) -> None:
        m = self.metrics
        if m is None or not m.enabled:
            return
        scale = self.scaler.get_loss_scaling()
        m.counter("train.scale_backoff_total",
                  desc="dynamic loss-scale reductions").inc()
        m.gauge("train.loss_scale").set(scale)
        m.event("scale_backoff", step=step, scale_before=scale_before,
                scale=scale)

    def state_dict(self) -> dict:
        return {"consecutive": self.consecutive,
                "total_skipped": self.total_skipped,
                "total_backoffs": self.total_backoffs}

    def load_state_dict(self, state: dict) -> None:
        self.consecutive = int(state.get("consecutive", 0))
        self.total_skipped = int(state.get("total_skipped", 0))
        self.total_backoffs = int(state.get("total_backoffs", 0))
