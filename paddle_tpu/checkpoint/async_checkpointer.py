"""Async checkpointing: overlap the disk write with training.

One bounded background thread (the io/dataloader prefetcher idiom — a
daemon worker behind a ``queue.Queue(maxsize=N)``) performs the
manager's atomic save+verify+publish, while the TRAINING thread only
pays for the device→host snapshot.  The snapshot must be synchronous:
the train step donates its param/moment buffers, so by the time the
writer thread runs, the live arrays have been overwritten in place —
the checkpoint serializes the host copy taken at call time.

Failures on the writer thread are sticky: the next ``save``/``wait``/
``close`` re-raises them on the caller's thread (exactly once), so a
full disk cannot silently drop every subsequent checkpoint.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

import numpy as np

from .manager import CheckpointManager
from ..observability import REGISTRY as _METRICS

__all__ = ["AsyncCheckpointer"]


def _snapshot(obj: Any):
    """Deep-copy a checkpoint payload to host memory.  Device arrays
    (and Tensors wrapping them) are fetched; host containers are
    rebuilt so later in-place mutation by the caller cannot alias."""
    from ..core.tensor import Tensor

    if isinstance(obj, Tensor):
        t = Tensor(np.asarray(obj._value))
        t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _snapshot(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_snapshot(v) for v in obj)
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    if hasattr(obj, "__array__") or hasattr(obj, "device"):
        return np.asarray(obj)
    return obj


class AsyncCheckpointer:
    """Wraps a :class:`CheckpointManager`; ``save`` returns as soon as
    the state is snapshotted to host and enqueued.  The queue is bounded:
    when ``queue_size`` saves are already pending, ``save`` blocks until
    the writer catches up (bounding host memory to queue_size+1
    snapshots)."""

    _STOP = object()

    def __init__(self, manager: CheckpointManager, queue_size: int = 1):
        self.manager = manager
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_size)))
        self._exc: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._pending = 0
        self._idle = threading.Event()
        self._idle.set()
        self._closed = False
        self.last_saved_step: Optional[int] = None
        self._thread = threading.Thread(target=self._writer, daemon=True,
                                        name="paddle-tpu-ckpt-writer")
        self._thread.start()

    # -- writer thread --------------------------------------------------
    def _writer(self) -> None:
        while True:
            item = self._q.get()
            if item is self._STOP:
                return
            state, step = item
            try:
                self.manager.save(state, step)
                self.last_saved_step = step
            except BaseException as e:
                with self._lock:
                    self._exc = e
                if _METRICS.enabled:
                    _METRICS.counter("checkpoint.async_failures_total"
                                     ).inc()
            finally:
                with self._lock:
                    self._pending -= 1
                    pending = self._pending
                    if pending == 0:
                        self._idle.set()
                # thread-safe by registry contract: the writer thread
                # updates the queue gauge as saves drain
                if _METRICS.enabled:
                    _METRICS.gauge("checkpoint.queue_depth").set(pending)

    def _raise_pending(self) -> None:
        with self._lock:
            exc, self._exc = self._exc, None
        if exc is not None:
            raise exc

    # -- caller side ----------------------------------------------------
    def save(self, state: Any, step: int) -> None:
        """Snapshot ``state`` to host and enqueue the disk write.  Blocks
        only when ``queue_size`` writes are already pending."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_pending()
        t0 = time.perf_counter()
        snap = _snapshot(state)
        with self._lock:
            self._pending += 1
            pending = self._pending
            self._idle.clear()
        if _METRICS.enabled:
            # the snapshot is the only cost the TRAINING thread pays
            _METRICS.histogram("checkpoint.snapshot_secs", unit="s",
                               desc="device→host state snapshot").record(
                                   time.perf_counter() - t0)
            _METRICS.counter("checkpoint.async_saves_total").inc()
            _METRICS.gauge("checkpoint.queue_depth").set(pending)
        self._q.put((snap, int(step)))

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every enqueued checkpoint is on disk; re-raises a
        writer failure.  Returns False on timeout."""
        done = self._idle.wait(timeout)
        self._raise_pending()
        return done

    def close(self, timeout: float = 60.0) -> None:
        """Drain pending writes and stop the writer.  Idempotent and
        join-safe (a second close, or one racing the writer's own exit,
        is a no-op)."""
        if self._closed:
            return
        self._closed = True
        self._idle.wait(timeout)
        self._q.put(self._STOP)
        if self._thread is not threading.current_thread():
            self._thread.join(timeout)
        self._raise_pending()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # Deliberate best-effort backstop: close() is idempotent, bounds
    # both the idle wait and the join, and never joins the current
    # thread — dropping it would truncate an in-flight async save when
    # a checkpointer is abandoned without close().
    def __del__(self):  # locklint: disable=LK005
        try:
            self.close(timeout=5.0)
        # finalizer racing interpreter shutdown: anything may be torn down
        except Exception:  # tracelint: disable=TL006
            pass
