"""Checkpoint directory manager: atomic publish, verified ``latest``
pointer, retention.

Layout (one directory per run)::

    <dir>/ckpt-00000042.pdckpt   # framework.io archive (atomic save)
    <dir>/latest                 # name of the newest VERIFIED checkpoint
    <dir>/.tmp-*                 # crash stragglers (cleaned opportunistically)

The pointer protocol makes recovery trivial: ``latest`` is only ever
rewritten (atomically) AFTER the new checkpoint file has been fully
written, renamed into place, and re-read/checksum-verified.  A process
killed at ANY byte of that sequence leaves ``latest`` naming the previous
good checkpoint; a reader that finds a corrupt or missing pointee falls
back to scanning for the newest checkpoint that passes verification.
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from ..framework import io as fio
from ..framework.io import CheckpointCorruptError
from ..observability import REGISTRY as _METRICS

__all__ = ["CheckpointManager", "latest_checkpoint", "LATEST_POINTER",
           "CKPT_PREFIX", "CKPT_SUFFIX"]

LATEST_POINTER = "latest"
CKPT_PREFIX = "ckpt-"
CKPT_SUFFIX = ".pdckpt"
_CKPT_RE = re.compile(re.escape(CKPT_PREFIX) + r"(\d+)" +
                      re.escape(CKPT_SUFFIX) + r"$")


def _step_of(name: str) -> Optional[int]:
    m = _CKPT_RE.match(name)
    return int(m.group(1)) if m else None


def _list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """(step, filename) for every checkpoint file, ascending by step."""
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out = [(s, n) for n in names
           if (s := _step_of(n)) is not None]
    out.sort()
    return out


def latest_checkpoint(directory: str) -> Optional[str]:
    """Absolute path of the newest VERIFIED checkpoint, or None.

    Follows the ``latest`` pointer first; if the pointer is missing,
    stale, or names a file that fails verification (crash between
    publish and pointer update, or on-disk corruption), falls back to
    scanning checkpoints newest-first and returns the first one that
    verifies."""
    candidates: List[str] = []
    ptr = os.path.join(directory, LATEST_POINTER)
    try:
        with open(ptr, "r") as f:
            name = f.read().strip()
        if name:
            candidates.append(name)
    except OSError:
        pass
    for _, name in reversed(_list_checkpoints(directory)):
        if name not in candidates:
            candidates.append(name)
    for name in candidates:
        path = os.path.join(directory, name)
        try:
            fio.verify(path)
        except (CheckpointCorruptError, FileNotFoundError, ValueError):
            continue
        return path
    return None


class CheckpointManager:
    """Publishes checkpoints atomically with retention and a verified
    ``latest`` pointer.

    ``save(state, step)`` is synchronous; :class:`AsyncCheckpointer`
    wraps a manager to overlap the disk write with training."""

    def __init__(self, directory: str, keep_last: int = 5):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.directory = os.path.abspath(directory)
        self.keep_last = keep_last
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------
    def path_for(self, step: int) -> str:
        return os.path.join(self.directory,
                            f"{CKPT_PREFIX}{int(step):08d}{CKPT_SUFFIX}")

    def save(self, state: Any, step: int) -> str:
        """Write, verify, publish ``latest``, rotate.  Returns the path.

        Order matters: the pointer only moves after verification, so an
        interrupted save (even one that corrupted its own file) never
        changes what ``latest`` resolves to."""
        path = self.path_for(step)
        t0 = time.perf_counter()
        fio.save(state, path)
        t_save = time.perf_counter()
        fio.verify(path)
        t_verify = time.perf_counter()
        fio.atomic_write_bytes(os.path.basename(path).encode(),
                               os.path.join(self.directory, LATEST_POINTER))
        self._rotate(keep_name=os.path.basename(path))
        self._sweep_stragglers()
        if _METRICS.enabled:        # host-side telemetry (ISSUE 5)
            t_publish = time.perf_counter()
            _METRICS.counter("checkpoint.saves_total").inc()
            _METRICS.histogram("checkpoint.save_secs", unit="s",
                               desc="write+fsync+rename").record(
                                   t_save - t0)
            _METRICS.histogram("checkpoint.verify_secs", unit="s").record(
                t_verify - t_save)
            _METRICS.event(
                "checkpoint", phase="save", step=int(step),
                path=os.path.basename(path),
                save_secs=round(t_save - t0, 6),
                verify_secs=round(t_verify - t_save, 6),
                publish_secs=round(t_publish - t_verify, 6),
                total_secs=round(t_publish - t0, 6),
                bytes=os.path.getsize(path))
        return path

    def restore(self, path: Optional[str] = None) -> Optional[Any]:
        """Load ``path`` (default: the latest verified checkpoint).
        Returns None when the directory holds no usable checkpoint."""
        if path is None:
            path = latest_checkpoint(self.directory)
            if path is None:
                return None
        return fio.load(path)

    def all_steps(self) -> List[int]:
        return [s for s, _ in _list_checkpoints(self.directory)]

    # ------------------------------------------------------------------
    def _rotate(self, keep_name: str) -> None:
        ckpts = _list_checkpoints(self.directory)
        excess = len(ckpts) - self.keep_last
        for _, name in ckpts:
            if excess <= 0:
                break
            if name == keep_name:   # never delete what latest names
                continue
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass
            excess -= 1

    def _sweep_stragglers(self) -> None:
        """Remove ``.tmp-*`` leftovers from crashed saves (best effort)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for n in names:
            if n.startswith(fio._TMP_PREFIX):
                try:
                    os.unlink(os.path.join(self.directory, n))
                except OSError:
                    pass
