"""Eager op dispatch.

The TPU-native analog of the reference's generated dygraph path
(/root/reference/paddle/fluid/pybind/eager_op_function.cc →
``*_ad_func`` → PHI kernel; SURVEY §3.1).  The per-op C++ machinery collapses
into one generic :func:`run_op`:

1. flatten ``(args, kwargs)`` into dynamic array leaves + static structure
   (the static part plays the role of ``KernelKey`` — it keys a jit cache,
   so each (op, static-args) pair compiles once and replays);
2. in eager mode, execute through a cached ``jax.jit`` and, when grad is
   enabled and a differentiable Tensor participates, record a
   :class:`~paddle_tpu.core.autograd.GradNode` on the tape;
3. inside a ``jax`` trace (functional/jit/`to_static` path), fall through to
   a direct call so the op fuses into the enclosing XLA program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .autograd import GradNode, is_grad_enabled
from .flags import FLAGS

__all__ = ["run_op", "primitive", "register_custom_vjp"]


def _is_dynamic(leaf: Any) -> bool:
    from .tensor import Tensor
    return isinstance(leaf, (Tensor, jax.Array, np.ndarray)) or (
        isinstance(leaf, np.generic))


def _is_tensor_leaf(x: Any) -> bool:
    from .tensor import Tensor
    return isinstance(x, Tensor)


# op name -> forward fn (impl); populated by ops.registry
_FORWARD_CACHE: Dict[Any, Callable] = {}

# bound by paddle_tpu.static on import: the symbolic Variable class; any op
# touching one records a Program node instead of executing
_static_variable_cls: Optional[type] = None


def _record_static(name: str, fn: Callable, treedef, leaves):
    """Record this op call into the owning static Program (reference:
    op append into framework.Program's global block)."""
    from ..static import Variable

    static_leaves: List[Any] = []
    dyn_idx: List[int] = []
    markers: List[Any] = []
    consts: List[Any] = []
    avals: List[Any] = []
    prog = None               # param-only ops fall back to the default
    from .tensor import Parameter
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, Variable):
            prog = prog or leaf.program
            dyn_idx.append(i)
            markers.append(leaf)
            avals.append(leaf.aval())
            static_leaves.append(None)
        elif isinstance(leaf, Parameter) and leaf.trainable:
            # live param ref (NOT a frozen const): replay reads the box's
            # current value, and the static training path (append_backward
            # /minimize) differentiates + updates through this slot
            # (reference: Parameter vars in the Program's global block)
            v = jnp.asarray(leaf._value)
            dyn_idx.append(i)
            markers.append(leaf)
            avals.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
            static_leaves.append(None)
        elif _is_dynamic(leaf):
            from .tensor import Tensor
            v = jnp.asarray(leaf._value if isinstance(leaf, Tensor)
                            else leaf)
            dyn_idx.append(i)
            markers.append(None)
            consts.append(v)
            avals.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
            static_leaves.append(None)
        else:
            static_leaves.append(leaf)
    dyn_set = tuple(dyn_idx)

    def call(dyn_vals):
        new_leaves = list(static_leaves)
        for j, i in enumerate(dyn_set):
            new_leaves[i] = dyn_vals[j]
        a, k = jax.tree.unflatten(treedef, new_leaves)
        return fn(*a, **k)

    if prog is None:
        from ..static import default_main_program
        prog = default_main_program()
    out_abs = jax.eval_shape(call, avals)
    out_flat, out_treedef = jax.tree.flatten(out_abs)
    return prog.record(name, call, markers, consts, out_flat, out_treedef,
                       statics=[s for s in static_leaves if s is not None])

# optional per-op-call hook set by amp.debugging operator-stats collection
_op_stats_hook: Optional[Callable] = None


def _exec_cached(exec_key: Tuple, call: Callable) -> Callable:
    fn = _FORWARD_CACHE.get(exec_key)
    if fn is None:
        fn = jax.jit(call) if FLAGS.eager_op_jit else call
        _FORWARD_CACHE[exec_key] = fn
    return fn


def _check_nan_inf(name: str, leaves: List[Any]) -> None:
    for i, v in enumerate(leaves):
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            n_bad = int(jnp.sum(~jnp.isfinite(v)))
            if n_bad:
                from .enforce import summarize_leaf
                msg = (f"NaN/Inf detected in output [{i}] of op {name!r}: "
                       f"{n_bad} non-finite element(s) in "
                       f"{summarize_leaf(v)}")
                if FLAGS.check_nan_inf_level == 0:
                    raise FloatingPointError(msg)
                import warnings
                warnings.warn(msg)


def run_op(name: str, fn: Callable, args: tuple, kwargs: dict,
           differentiable: bool = True, jit: bool = True):
    """Execute op ``name`` implemented by pure function ``fn``."""
    from .tensor import Tensor
    from . import amp_state

    if amp_state.enabled():
        tgt = amp_state.cast_policy(name)
        if tgt is not None:
            def _amp_cast(x):
                if isinstance(x, Tensor) and jnp.issubdtype(
                        jnp.asarray(x._value).dtype, jnp.floating) and \
                        jnp.asarray(x._value).dtype != tgt:
                    return x.astype(tgt) if hasattr(x, "astype") else x
                return x
            args = tuple(_amp_cast(a) for a in args)
            kwargs = {k: _amp_cast(v) for k, v in kwargs.items()}

    leaves, treedef = jax.tree.flatten((args, kwargs), is_leaf=_is_tensor_leaf)

    if _static_variable_cls is not None:
        from .tensor import Parameter as _Param
        # record ops touching a Variable OR a trainable Parameter: an op
        # on params alone (e.g. wpe(arange(s)) — position embedding with
        # a concrete index) must still enter the Program, else the param
        # is constant-folded and silently excluded from static training
        if any(isinstance(l, _static_variable_cls)
               or (isinstance(l, _Param) and l.trainable)
               for l in leaves):
            return _record_static(name, fn, treedef, leaves)

    dyn_idx: List[int] = []
    dyn_tensors: List[Optional[Tensor]] = []
    dyn_values: List[Any] = []
    static: List[Any] = []
    any_tracer = False
    needs_grad = False
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, Tensor):
            v = leaf._value
            dyn_idx.append(i)
            dyn_tensors.append(leaf)
            dyn_values.append(v)
            static.append(None)
            if isinstance(v, jax.core.Tracer):
                any_tracer = True
            if (not leaf.stop_gradient
                    and jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact)):
                needs_grad = True
        elif isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
            dyn_idx.append(i)
            dyn_tensors.append(None)
            dyn_values.append(leaf)
            static.append(None)
            if isinstance(leaf, jax.core.Tracer):
                any_tracer = True
        else:
            static.append(leaf)

    dyn_set = tuple(dyn_idx)

    if _op_stats_hook is not None:
        _dt = next((jnp.asarray(v).dtype for v in dyn_values
                    if hasattr(v, "dtype")
                    or isinstance(v, (np.ndarray, np.generic))), None)
        _op_stats_hook(name, _dt)

    def call(dyn_vals):
        new_leaves = list(static)
        for j, i in enumerate(dyn_set):
            new_leaves[i] = dyn_vals[j]
        a, k = jax.tree.unflatten(treedef, new_leaves)
        return fn(*a, **k)

    # ---- traced (functional) path: let it fuse into the outer XLA program
    if any_tracer:
        try:
            out = call(dyn_values)
        except BaseException as e:
            from .enforce import op_error_context
            raise op_error_context(name, dyn_values, "traced", e) from e
        return _wrap_out(out, None)

    # ---- eager path
    try:
        static_key = tuple(
            s if _hashable(s) else repr(s) for s in static)
        exec_key = (name, fn, treedef, static_key, dyn_set,
                    tuple(_aval_key(v) for v in dyn_values))
    except TypeError:
        exec_key = None

    try:
        if exec_key is not None and FLAGS.eager_op_jit and jit:
            out = _exec_cached(exec_key, call)(dyn_values)
        else:
            out = call(dyn_values)
    except BaseException as e:
        from .enforce import op_error_context
        raise op_error_context(name, dyn_values, "eager", e) from e

    node = None
    if differentiable and needs_grad and is_grad_enabled():
        out_flat, out_treedef = jax.tree.flatten(out)
        out_avals = [jax.ShapeDtypeStruct(jnp.shape(v), jnp.asarray(v).dtype)
                     for v in out_flat]
        node = GradNode(name, exec_key, call, dyn_tensors, dyn_values,
                        out_avals, out_treedef)

    if FLAGS.check_nan_inf:
        _check_nan_inf(name, jax.tree.leaves(out))

    return _wrap_out(out, node)


def _hashable(x: Any) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _aval_key(v: Any):
    a = jnp.asarray(v) if not hasattr(v, "dtype") else v
    return (tuple(getattr(a, "shape", ())), str(a.dtype))


def _wrap_out(out: Any, node: Optional[GradNode]):
    from .tensor import Tensor

    out_flat, out_treedef = jax.tree.flatten(out)
    wrapped = []
    for idx, v in enumerate(out_flat):
        t = Tensor(v, stop_gradient=(node is None))
        t._node = node
        t._out_index = idx
        wrapped.append(t)
    if len(wrapped) == 1 and out_treedef.num_leaves == 1 and not isinstance(
            out, (tuple, list, dict)):
        return wrapped[0]
    return jax.tree.unflatten(out_treedef, wrapped)


def primitive(name: str, differentiable: bool = True):
    """Decorator turning a pure jnp function into an eager-dispatch op."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return run_op(name, fn, args, kwargs, differentiable=differentiable)

        wrapper.__pt_primitive__ = name
        wrapper.raw = fn
        return wrapper

    return deco


def register_custom_vjp(fn: Callable, fwd: Callable, bwd: Callable,
                        nondiff_argnums: Tuple[int, ...] = ()) -> Callable:
    """Attach a hand-written VJP (e.g. a Pallas backward kernel) to an impl
    function; the generic tape/vjp machinery then uses it automatically."""
    wrapped = jax.custom_vjp(fn, nondiff_argnums=nondiff_argnums)
    wrapped.defvjp(fwd, bwd)
    return wrapped
