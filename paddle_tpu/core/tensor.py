"""The Tensor façade over ``jax.Array``.

Analog of the reference's ``phi::DenseTensor`` + Python ``Tensor``
(/root/reference/paddle/phi/core/dense_tensor.h:37 and the eager tensor
patched methods, python/paddle/base/dygraph/tensor_patch_methods.py).
Storage, layout, strides and allocators collapse into ``jax.Array``; what
remains is the imperative-API state the reference keeps on the C++ side:
``stop_gradient``, ``.grad``, hooks, name, and the autograd linkage.

Tensor is registered as a jax pytree node, so Tensors pass transparently
through ``jax.jit`` / ``jax.grad`` / shard_map — the bridge between the
Paddle-style imperative shell and functional JAX.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as _dt
from .autograd import backward as _backward

__all__ = ["Tensor", "to_tensor", "Parameter"]

_name_counter = itertools.count()


class Tensor:
    __slots__ = ("_value", "stop_gradient", "grad", "name", "persistable",
                 "_node", "_out_index", "_retain_grads", "_grad_hooks",
                 "trainable", "process_mesh", "placements", "param_spec",
                 "optimize_attr", "__weakref__")

    def __init__(self, value, stop_gradient: bool = True,
                 name: Optional[str] = None, persistable: bool = False):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, np.ndarray)) or isinstance(
                value, np.generic):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad: Optional["Tensor"] = None
        self.name = name or f"tensor_{next(_name_counter)}"
        self.persistable = persistable
        self.trainable = True
        self._node = None
        self._out_index = 0
        self._retain_grads = False
        self._grad_hooks: List[Callable] = []

    # ------------------------------------------------------------------
    # properties
    # ------------------------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(jnp.shape(self._value))

    @property
    def ndim(self) -> int:
        return jnp.ndim(self._value)

    @property
    def dtype(self):
        return jnp.asarray(self._value).dtype

    @property
    def size(self) -> int:
        return int(np.prod(jnp.shape(self._value), dtype=np.int64))

    @property
    def place(self):
        from .device import Place
        v = self._value
        if isinstance(v, jax.Array) and not isinstance(v, jax.core.Tracer):
            try:
                d = list(v.devices())[0]
                return Place(d.platform, d.id)
            except (IndexError, RuntimeError):
                pass    # deleted/donated array: fall to default_place
        from .device import default_place
        return default_place()

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    # ------------------------------------------------------------------
    # conversion
    # ------------------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __float__(self):
        return float(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __index__(self):
        # lets range(t)/list[t] work eagerly; under trace the jax tracer
        # raises TracerIntegerConversionError (dy2static fallback catches)
        if isinstance(self._value, jax.core.Tracer):
            return self._value.__index__()
        v = np.asarray(self._value)
        if not (np.issubdtype(v.dtype, np.integer)
                or v.dtype == np.bool_):
            raise TypeError(
                f"'{v.dtype}' tensor cannot be interpreted as an integer")
        return int(v)

    def __bool__(self):
        return bool(np.asarray(self._value))

    def __len__(self):
        s = jnp.shape(self._value)
        if not s:
            raise TypeError("len() of a 0-d tensor")
        return s[0]

    def __repr__(self):
        v = self._value
        if isinstance(v, jax.core.Tracer):
            body = repr(v)
        else:
            body = np.array2string(np.asarray(v), precision=6, threshold=64)
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False) -> None:
        _backward(self, grad_tensor, retain_graph=retain_graph)

    def retain_grads(self) -> None:
        self._retain_grads = True

    def register_hook(self, hook: Callable) -> Callable:
        """Hook ``hook(grad) -> grad|None`` applied when this tensor's grad is
        accumulated (reference: eager/hooks.h; used by DP reducers)."""
        self._grad_hooks.append(hook)

        def remove():
            self._grad_hooks.remove(hook)

        remove.remove = remove
        return remove

    def _accumulate_grad(self, g) -> None:
        if isinstance(g, Tensor) and g._node is not None:
            # create_graph path: keep the graph-linked grad Tensor so the
            # grad itself stays differentiable (double grad)
            for hook in self._grad_hooks:
                out = hook(g)
                if out is not None:
                    g = out
            self.grad = g if self.grad is None else self.grad + g
            return
        if isinstance(g, Tensor):
            g = g._value
        for hook in self._grad_hooks:
            out = hook(Tensor(g))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else out
        if self.grad is None:
            self.grad = Tensor(g)
        else:
            self.grad = Tensor(self.grad._value + g)

    def clear_grad(self) -> None:
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self) -> "Tensor":
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops import api as _api
        return _api.assign(self)

    # ------------------------------------------------------------------
    # mutation (functional under the hood; jax arrays are immutable)
    # ------------------------------------------------------------------
    def copy_(self, other) -> "Tensor":
        self._value = jnp.asarray(other._value if isinstance(other, Tensor)
                                  else other, self.dtype)
        return self

    def set_value(self, value) -> "Tensor":
        return self.copy_(value)

    def _replace_(self, value) -> "Tensor":
        """In-place value swap used by optimizers/in-place ops."""
        self._value = value if not isinstance(value, Tensor) else value._value
        return self

    def __setitem__(self, idx, value) -> None:
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(self._value).at[idx].set(value)

    def __getitem__(self, idx):
        from ..ops import api as _api
        return _api._getitem(self, _unwrap_index(idx))

    # ------------------------------------------------------------------
    # dtype / device movement
    # ------------------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from ..ops import api as _api
        return _api.cast(self, dtype)

    cast = astype

    def to(self, *args, **kwargs) -> "Tensor":
        dtype = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str) and (a in ("cpu",) or a.startswith(("tpu", "gpu", "axon"))):
                device = a
            else:
                dtype = a
        t = self
        if dtype is not None:
            t = t.astype(dtype)
        if device is not None:
            from .device import Place
            if isinstance(device, str):
                ty, _, idx = device.partition(":")
                device = Place(ty, int(idx or 0))
            v = jax.device_put(t._value, device.jax_device())
            t = Tensor(v, stop_gradient=t.stop_gradient, name=t.name)
        return t

    def cpu(self) -> "Tensor":
        return self.to("cpu")

    def pin_memory(self) -> "Tensor":
        return self

    def contiguous(self) -> "Tensor":
        return self

    def is_contiguous(self) -> bool:
        return True

    # ------------------------------------------------------------------
    # misc paddle-compat
    # ------------------------------------------------------------------
    def numel(self) -> int:
        return self.size

    def dim(self) -> int:
        return self.ndim

    def element_size(self) -> int:
        return jnp.asarray(self._value).dtype.itemsize

    def block_until_ready(self) -> "Tensor":
        if isinstance(self._value, jax.Array):
            self._value.block_until_ready()
        return self


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    return idx


# ---------------------------------------------------------------------------
# pytree registration: Tensors flow through jax transforms
# ---------------------------------------------------------------------------
def _tensor_flatten(t: Tensor):
    # aux must NOT carry identity data (e.g. the auto name): treedef
    # equality gates lax.cond/while_loop branch matching, and two Tensors
    # computed on different branches must flatten identically
    return (t._value,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    # well-behaved pytree: jax unflattens with sentinel/placeholder
    # children (error rendering, transposes) — no asarray validation here
    t = object.__new__(Tensor)
    t._value = children[0]
    t.stop_gradient = aux[0]
    t.grad = None
    t.name = f"tensor_{next(_name_counter)}"
    t.persistable = False
    t.trainable = True
    t._node = None
    t._out_index = 0
    t._retain_grads = False
    t._grad_hooks = []
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


class Parameter(Tensor):
    """Trainable tensor (``stop_gradient=False``, ``persistable=True``).
    Analog of paddle's EagerParamBase."""

    def __init__(self, value, name: Optional[str] = None, trainable: bool = True):
        super().__init__(value, stop_gradient=not trainable, name=name,
                         persistable=True)
        self.trainable = trainable


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """Paddle-compatible ``paddle.to_tensor``."""
    if isinstance(data, Tensor):
        v = data._value
    else:
        v = data
    if dtype is not None:
        v = jnp.asarray(v, _dt.canonical_dtype(dtype))
    else:
        v = jnp.asarray(v)
        if v.dtype == jnp.float64 and _dt.default_float_dtype() == jnp.float32:
            v = v.astype(jnp.float32)
    if place is not None:
        from .device import Place
        if isinstance(place, str):
            ty, _, idx = place.partition(":")
            place = Place(ty, int(idx or 0))
        v = jax.device_put(v, place.jax_device())
    return Tensor(v, stop_gradient=stop_gradient)


def inplace_rebind(x: "Tensor", out: "Tensor") -> "Tensor":
    """Make ``x`` observe in-place op result ``out`` (reference: inplace ops
    + eager/tensor_wrapper.h inplace-version semantics).

    The autograd node of ``out`` recorded ``x`` as an input box; rebinding
    ``x`` to ``out`` would alias that input to the node's own output and
    create a self-cycle in backward.  Snapshot the producer link into a
    fresh box first, then rebind."""
    node = getattr(out, "_node", None)
    if node is not None and x._node is None and not x.stop_gradient:
        # reference parity: in-place on a grad-requiring leaf is an error
        # (the leaf's gradient would silently accumulate into the hidden
        # pre-inplace snapshot and be dropped)
        raise RuntimeError(
            "a leaf Tensor with stop_gradient=False cannot be used in an "
            "in-place operation; detach() it or wrap in no_grad()")
    if node is not None and node.in_tensors is not None:
        for i, t in enumerate(node.in_tensors):
            if t is x:
                snap = Tensor(x._value, stop_gradient=x.stop_gradient,
                              name=x.name + ".preinplace")
                snap._node = x._node
                snap._out_index = x._out_index
                snap._retain_grads = False
                node.in_tensors[i] = snap
    x._value = out._value
    x._node = out._node
    x._out_index = out._out_index
    if not out.stop_gradient:
        x.stop_gradient = False
    return x
