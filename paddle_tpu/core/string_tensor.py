"""StringTensor (reference paddle/phi/core/string_tensor.h — the dtype
pstring tensor that backs the faster-tokenizer ops).

Strings never reach the device: XLA has no string dtype, and the reference
runs its string kernels on host too.  This is a shaped numpy object-array
wrapper with the Tensor-like surface the tokenizer path needs; downstream
numeric outputs (ids/offsets) become ordinary device Tensors.
"""

from __future__ import annotations

from typing import Iterable, List, Union

import numpy as np

__all__ = ["StringTensor", "to_string_tensor"]


class StringTensor:
    def __init__(self, data):
        arr = np.asarray(data, dtype=object)
        self._data = arr

    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def dtype(self) -> str:
        return "pstring"

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, np.ndarray):
            return StringTensor(out)
        return out

    def __len__(self):
        return self._data.shape[0] if self._data.ndim else 1

    def __iter__(self):
        return iter(self._data.tolist())

    def __eq__(self, other):
        o = other._data if isinstance(other, StringTensor) else other
        return np.asarray(self._data == o)

    # identity hash: __eq__ returns an elementwise array (numpy-style),
    # so value hashing is impossible — keep instances usable as keys
    __hash__ = object.__hash__

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self.tolist()!r})"

    def lower(self) -> "StringTensor":
        return StringTensor(np.vectorize(str.lower, otypes=[object])(
            self._data))

    def encode(self, encoding="utf-8"):
        return [s.encode(encoding) for s in self._data.reshape(-1)]


def to_string_tensor(data: Union[str, Iterable]) -> StringTensor:
    if isinstance(data, str):
        data = [data]
    return StringTensor(list(data))
