"""JAX version compatibility shims.

The tree targets the current public JAX API surface; older jaxlib builds
(this container ships 0.4.x) predate some promotions out of
``jax.experimental``.  Each shim installs the modern name when missing so
the rest of the tree (and the tests) can use one spelling.

* ``jax.shard_map`` — promoted from ``jax.experimental.shard_map`` (whose
  ``check_rep`` kwarg was renamed ``check_vma``).
* ``jax.lax.axis_size`` — newer helper; ``lax.psum(1, axis)`` of a python
  scalar is evaluated statically and returns the same int.
* ``jax.export`` — the submodule exists but older ``jax/__init__`` does
  not import it, so attribute access raises; importing it binds it.
"""

from __future__ import annotations

import jax
from jax import lax as _lax

try:
    import jax.export  # noqa: F401  (binds the jax.export attribute)
except ImportError:
    pass

if not hasattr(_lax, "axis_size"):
    def _axis_size(axis_name):
        return _lax.psum(1, axis_name)

    _lax.axis_size = _axis_size

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=None,
                  **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma

        def bind(fn):
            return _shard_map(fn, mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)

        return bind if f is None else bind(f)

    jax.shard_map = shard_map
