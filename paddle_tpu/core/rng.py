"""Random number generation.

Replaces the reference's per-device stateful ``Generator``
(/root/reference/paddle/phi/core/generator.cc) and the model-parallel
``RNGStatesTracker`` (fleet/layers/mpu/random.py:34) with JAX key folding:

* Eager mode: a process-global :class:`Generator` holds a key and splits it on
  every random op (stateful convenience, Paddle-style ``paddle.seed``).
* Traced mode (inside ``jit``): random ops pull keys from an explicit
  :func:`rng_scope` context, so randomness is a traced input — pure and
  reproducible.  Modules (e.g. Dropout) call :func:`next_rng_key` and work in
  both modes transparently.
* Parallel RNG: :class:`RNGStatesTracker` folds a named-axis index into the
  key so e.g. tensor-parallel dropout differs per mp rank while weights init
  identically (semantics of mpu/random.py:34 without state shipping).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "seed", "Generator", "default_generator", "next_rng_key", "rng_scope",
    "RNGStatesTracker", "get_rng_state", "set_rng_state",
]


class Generator:
    """Stateful key source.

    State is (seed, draw counter) — plain Python ints; each draw derives
    ``fold_in(key(seed), counter)``.  Keeping the state off-device means a
    draw that happens to run under a jit trace (an op impl delegating to a
    keyed kernel) can never leak a tracer into global state — the traced
    fold_in result stays local to the trace."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed) % (2 ** 63)   # key() wants a non-neg int64
        self._count = 0
        self._lock = threading.Lock()

    def manual_seed(self, seed: int) -> "Generator":
        with self._lock:
            self._seed = int(seed) % (2 ** 63)
            self._count = 0
        return self

    def split(self) -> jax.Array:
        with self._lock:
            self._count += 1
            c = self._count
        return jax.random.fold_in(jax.random.key(self._seed), c)

    def get_state(self):
        return np.asarray([self._seed, self._count], np.uint64)

    def set_state(self, state) -> None:
        s = np.asarray(state).ravel()
        with self._lock:
            self._seed = int(s[0]) % (2 ** 63)
            self._count = int(s[1])

    @property
    def initial_seed(self) -> int:
        return self._seed


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int) -> Generator:
    """Paddle-compatible global seed."""
    return _default_generator.manual_seed(s)


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state) -> None:
    _default_generator.set_state(state)


# ---------------------------------------------------------------------------
# Scoped (functional) keys for traced code
# ---------------------------------------------------------------------------
class _RngScope(threading.local):
    def __init__(self):
        self.stack: List[Dict] = []


_scope = _RngScope()


class rng_scope:
    """``with rng_scope(key): ...`` — random ops inside draw from `key` by
    fold_in counter, making them pure functions of the provided key.  Used by
    the functional/jit path to thread dropout keys through a traced step."""

    def __init__(self, key):
        if isinstance(key, int):
            key = jax.random.key(key)
        self._frame = {"key": key, "count": 0}

    def __enter__(self):
        _scope.stack.append(self._frame)
        return self

    def __exit__(self, *exc):
        _scope.stack.pop()
        return False


def next_rng_key(generator: Optional[Generator] = None) -> jax.Array:
    """The single entry point random ops use for a fresh key.

    Inside an :class:`rng_scope` (the traced path) keys derive from the scope
    key via fold_in of a call counter; otherwise the stateful global
    generator splits.
    """
    if _scope.stack:
        frame = _scope.stack[-1]
        frame["count"] += 1
        return jax.random.fold_in(frame["key"], frame["count"])
    return (generator or _default_generator).split()


class RNGStatesTracker:
    """Named RNG streams for model parallelism.

    ``add(name, seed)`` registers a stream; ``with tracker.rng_state(name):``
    makes random ops draw from that stream.  For per-mp-rank divergence fold
    the axis index into the seed (see parallel/topology).
    """

    def __init__(self):
        self._seeds: Dict[str, int] = {}
        self._gens: Dict[str, Generator] = {}

    def add(self, name: str, seed: int) -> None:
        if name in self._seeds:
            raise ValueError(f"rng state {name!r} already added")
        for n, s in self._seeds.items():
            if s == seed:
                raise ValueError(f"seed {seed} already used by stream {n!r}")
        self._seeds[name] = seed
        self._gens[name] = Generator(seed)

    def rng_state(self, name: str = "global_seed"):
        if name not in self._gens:
            raise ValueError(f"unknown rng stream {name!r}")
        return _generator_scope(self._gens[name])

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self._gens.items()}

    def set_states_tracker(self, states) -> None:
        for n, s in states.items():
            self._gens[n].set_state(s)

    def reset(self) -> None:
        self._seeds.clear()
        self._gens.clear()


class _generator_scope:
    """Route next_rng_key() through a specific Generator (eager path)."""

    def __init__(self, gen: Generator):
        self._gen = gen

    def __enter__(self):
        _scope.stack.append({"key": self._gen.split(), "count": 0})
        return self

    def __exit__(self, *exc):
        _scope.stack.pop()
        return False
