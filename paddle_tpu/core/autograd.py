"""Define-by-run eager autograd.

TPU-native replacement for the reference's C++ eager engine
(/root/reference/paddle/fluid/eager: ``GradNodeBase`` grad_node_info.h:197,
``RunBackward`` backward.cc:105, ``GradTensorHolder`` accumulation).  Instead
of generated per-op C++ grad nodes, every eager op records one
:class:`GradNode` holding the op's pure function and its dynamic inputs;
``backward()`` walks the node DAG in reverse creation order and computes
input cotangents with a cached, jit-compiled ``jax.vjp`` — so the "grad
kernel" for every op is derived automatically from the forward impl, the same
single-source property the reference gets from its YAML backward registry
(phi/ops/yaml/backward.yaml).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "GradNode", "backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "is_grad_enabled",
]


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class _GradModeCtx:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with _GradModeCtx(self._mode):
                return fn(*a, **k)

        return wrapper


def no_grad(fn=None):
    """Context manager / decorator disabling tape recording (Paddle
    ``paddle.no_grad``)."""
    ctx = _GradModeCtx(False)
    return ctx(fn) if fn is not None else ctx


def enable_grad(fn=None):
    ctx = _GradModeCtx(True)
    return ctx(fn) if fn is not None else ctx


_node_counter = [0]

# (pack, unpack) hooks for primals saved on GradNodes
# (paddle.autograd.saved_tensors_hooks — offload/compress saved
# activations); None = save values directly
_saved_tensor_hooks = None


class GradNode:
    """One recorded eager op.

    Attributes:
      exec_key: hashable key identifying the pure callable (for the vjp cache)
      call: ``call(dyn_vals) -> out_tree`` pure function of dynamic leaves
      in_tensors: the Tensor objects among the dynamic leaves (None where the
        dynamic leaf was a raw array)
      in_values: concrete values of ALL dynamic leaves (saved primals)
      out_avals: flat list of jax.ShapeDtypeStruct per output leaf
      out_treedef: structure of the forward output
    """

    __slots__ = ("name", "exec_key", "call", "in_tensors", "in_values",
                 "out_avals", "out_treedef", "id", "unpack_hook")

    def __init__(self, name, exec_key, call, in_tensors, in_values, out_avals,
                 out_treedef):
        self.name = name
        self.exec_key = exec_key
        self.call = call
        self.in_tensors = in_tensors
        hooks = _saved_tensor_hooks
        if hooks is not None:
            self.in_values = [hooks[0](v) for v in in_values]
            self.unpack_hook = hooks[1]
        else:
            self.in_values = in_values
            self.unpack_hook = None
        self.out_avals = out_avals
        self.out_treedef = out_treedef
        _node_counter[0] += 1
        self.id = _node_counter[0]


# Cache of jitted vjp executors, keyed by the op's exec_key.
_vjp_cache: Dict[Any, Callable] = {}

# create_graph path: recorded-vjp closures, keyed by (exec_key, diff_slots)
# so run_op sees a stable fn identity (stable jit cache key) across steps.
_recorded_vjp_cache: Dict[Any, Callable] = {}


def _vjp_executor(node: GradNode) -> Callable:
    fn = _vjp_cache.get(node.exec_key)
    if fn is None:
        call = node.call
        treedef = node.out_treedef

        def run(in_values, cts_flat):
            out, vjp = jax.vjp(call, in_values)
            del out
            cts = jax.tree.unflatten(treedef, cts_flat)
            (grads,) = vjp(cts)
            return grads

        from .flags import FLAGS
        fn = jax.jit(run) if FLAGS.eager_op_jit else run
        _vjp_cache[node.exec_key] = fn
    return fn


def _accumulate(slot: Optional[jax.Array], g: jax.Array) -> jax.Array:
    return g if slot is None else slot + g


def _node_grads_recorded(node: "GradNode", cts_flat):
    """create_graph=True: compute ``node``'s input cotangents THROUGH the
    eager dispatcher so the vjp computation is itself recorded on the tape
    (recorded-vjp recursion — the TPU-native analog of the reference's
    double-grad nodes, eager/general_grad.h).  Returns a list aligned with
    node.in_tensors; None at non-differentiable slots."""
    from .dispatch import run_op
    from .tensor import Tensor

    n_in = len(node.in_values)
    diff_slots = tuple(
        i for i, (t, v) in enumerate(zip(node.in_tensors, node.in_values))
        if t is not None and jnp.issubdtype(jnp.asarray(v).dtype,
                                            jnp.inexact))
    if not diff_slots:
        return [None] * n_in
    # cache the closure by exec_key so run_op's fn-identity jit key repeats
    # across steps (a fresh closure per backward would re-jit every grad op
    # every iteration and grow the jit caches unboundedly)
    cache_key = (node.exec_key, diff_slots) if node.exec_key is not None \
        else None
    vjp_fn = _recorded_vjp_cache.get(cache_key) if cache_key else None
    if vjp_fn is None:
        call = node.call
        treedef = node.out_treedef

        def vjp_fn(*flat):
            in_vals = list(flat[:n_in])
            cts = jax.tree.unflatten(treedef, list(flat[n_in:]))
            _, vjp = jax.vjp(call, in_vals)
            (gs,) = vjp(cts)
            return tuple(gs[i] for i in diff_slots)

        if cache_key is not None:
            _recorded_vjp_cache[cache_key] = vjp_fn

    args = [t if t is not None else v
            for t, v in zip(node.in_tensors, node.in_values)]
    args.extend(cts_flat)
    out = run_op(node.name + "_grad", vjp_fn, tuple(args), {})
    if isinstance(out, Tensor):
        out = (out,)
    grads: List[Optional["Tensor"]] = [None] * n_in
    for slot, g in zip(diff_slots, out):
        grads[slot] = g
    return grads


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             create_graph: bool = False) -> None:
    """Run reverse-mode accumulation from ``tensors`` (usually a scalar loss),
    writing ``.grad`` on reachable leaf tensors with ``stop_gradient=False``.

    Mirrors ``egr::Backward`` (eager/backward.cc:439): seed output grads with
    ones, BFS the node graph in reverse, per-slot accumulation.
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # node id -> list of output cotangents (flat, per out leaf)
    pending: Dict[int, List[Optional[jax.Array]]] = {}
    nodes: Dict[int, GradNode] = {}

    def seed(t: "Tensor", g: Optional[jax.Array]):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            g = jnp.ones(t.shape, t.dtype)
        if create_graph and not isinstance(g, Tensor):
            g = Tensor(g, stop_gradient=True)
        node, idx = t._node, t._out_index
        if node is None:
            if not t.stop_gradient:
                t._accumulate_grad(g)
            return
        nodes[node.id] = node
        slots = pending.setdefault(node.id, [None] * len(node.out_avals))
        slots[idx] = _accumulate(slots[idx], g)

    if create_graph:
        retain_graph = True          # grads-of-grads revisit saved primals

    for t, g in zip(tensors, grad_tensors):
        if create_graph:
            seed(t, g)               # keep Tensor boxes (graph-linked)
        else:
            seed(t, g._value if isinstance(g, Tensor) else g)

    # Reverse creation order is a valid topological order for a define-by-run
    # DAG (producers always have smaller ids than consumers).
    while pending:
        nid = max(pending)
        node = nodes.pop(nid)
        cts = pending.pop(nid)
        cts_flat = [
            c if c is not None else jnp.zeros(a.shape, a.dtype)
            for c, a in zip(cts, node.out_avals)
        ]
        if node.unpack_hook is not None and node.in_values is not None:
            node.in_values = [node.unpack_hook(v) for v in node.in_values]
            node.unpack_hook = None
        if create_graph:
            grads = _node_grads_recorded(node, cts_flat)
        else:
            grads = _vjp_executor(node)(node.in_values, cts_flat)
        for t, g in zip(node.in_tensors, grads):
            if t is None or g is None:
                continue
            gv = g._value if isinstance(g, Tensor) else g
            if getattr(gv, "dtype", None) is not None and \
                    gv.dtype == jax.dtypes.float0:
                continue
            if t._node is not None:
                prod = t._node
                nodes[prod.id] = prod
                slots = pending.setdefault(prod.id, [None] * len(prod.out_avals))
                slots[t._out_index] = _accumulate(slots[t._out_index], g)
                if t._retain_grads and not t.stop_gradient:
                    t._accumulate_grad(g)
            elif not t.stop_gradient:
                t._accumulate_grad(g)
        if not retain_graph:
            node.in_values = None  # free saved primals


def grad(outputs, inputs, grad_outputs=None, retain_graph: bool = False,
         create_graph: bool = False, allow_unused: bool = False):
    """``paddle.grad``-style: returns grads of ``outputs`` wrt ``inputs``
    without touching ``.grad`` slots (reference: GeneralGrad,
    eager/general_grad.h).  With ``create_graph=True`` the vjp computations
    are themselves recorded on the tape (recorded-vjp recursion), so the
    returned grads are differentiable — double-grad / gradient-penalty
    training works exactly like the reference's eager double grad."""
    from .tensor import Tensor

    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    saved = [(t.grad, t._retain_grads, t.stop_gradient) for t in inputs]
    try:
        for t in inputs:
            t.grad = None
            t._retain_grads = True
            t.stop_gradient = False
        backward(outputs, grad_outputs, retain_graph=retain_graph,
                 create_graph=create_graph)
        out = []
        for t in inputs:
            if t.grad is None and not allow_unused:
                raise RuntimeError(
                    f"input tensor {t.name!r} unused in graph "
                    "(pass allow_unused=True to get None)")
            out.append(t.grad)
        return out
    finally:
        for t, (g, r, sg) in zip(inputs, saved):
            t.grad = g
            t._retain_grads = r
            t.stop_gradient = sg
