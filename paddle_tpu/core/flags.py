"""Global runtime flag registry.

TPU-native analog of the reference's single flag registry
(/root/reference/paddle/common/flags.cc — 173 ``PHI_DEFINE_EXPORTED_*`` flags,
surfaced to Python as ``FLAGS_*`` and settable via env / ``paddle.set_flags``).

Here flags are plain typed Python entries, settable via environment variables
(``PT_FLAGS_<name>`` or ``FLAGS_<name>``), :func:`set_flags`, or attribute
access on the :data:`FLAGS` singleton.  XLA-level knobs pass through to
``XLA_FLAGS`` (see :func:`set_xla_flag`).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

__all__ = ["FLAGS", "define_flag", "set_flags", "get_flags", "set_xla_flag"]


@dataclass
class _FlagDef:
    name: str
    default: Any
    type: type
    help: str
    on_change: Optional[Callable[[Any], None]] = None


_BOOL_TRUE = {"1", "true", "yes", "on"}


def _coerce(value: Any, ty: type) -> Any:
    if ty is bool:
        if isinstance(value, str):
            return value.strip().lower() in _BOOL_TRUE
        return bool(value)
    if value is None:
        return None
    return ty(value)


class _Flags:
    """Process-global flag table (thread-safe for writes)."""

    def __init__(self) -> None:
        object.__setattr__(self, "_defs", {})
        object.__setattr__(self, "_values", {})
        object.__setattr__(self, "_lock", threading.Lock())

    # -- registry ---------------------------------------------------------
    def define(self, name: str, default: Any, help: str = "",
               type: Optional[type] = None,
               on_change: Optional[Callable[[Any], None]] = None) -> None:
        ty = type or (bool if isinstance(default, bool) else default.__class__)
        d = _FlagDef(name, default, ty, help, on_change)
        with self._lock:
            self._defs[name] = d
            env = os.environ.get(f"PT_FLAGS_{name}", os.environ.get(f"FLAGS_{name}"))
            self._values[name] = _coerce(env, ty) if env is not None else default

    # -- access -----------------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(f"undefined flag {name!r}") from None

    def __setattr__(self, name: str, value: Any) -> None:
        self.set(name, value)

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._defs:
                raise KeyError(f"undefined flag {name!r}; define_flag() it first")
            d = self._defs[name]
            self._values[name] = _coerce(value, d.type)
        if d.on_change is not None:
            d.on_change(self._values[name])

    def get(self, name: str) -> Any:
        return self._values[name]

    def defined(self) -> Dict[str, Any]:
        return dict(self._values)

    def describe(self) -> Dict[str, _FlagDef]:
        return dict(self._defs)


FLAGS = _Flags()


def define_flag(name: str, default: Any, help: str = "", **kw: Any) -> None:
    FLAGS.define(name, default, help, **kw)


def set_flags(flags: Dict[str, Any]) -> None:
    """Paddle-compatible ``set_flags({'FLAGS_x': v})`` (prefix optional)."""
    for k, v in flags.items():
        if k.startswith("FLAGS_"):
            k = k[len("FLAGS_"):]
        FLAGS.set(k, v)


def get_flags(names) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    out = {}
    for k in names:
        key = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        out[k] = FLAGS.get(key)
    return out


def set_xla_flag(flag: str) -> None:
    """Append a flag to XLA_FLAGS (effective for processes started after, and
    for lazily-initialized backends)."""
    cur = os.environ.get("XLA_FLAGS", "")
    if flag not in cur.split():
        os.environ["XLA_FLAGS"] = (cur + " " + flag).strip()


# ---------------------------------------------------------------------------
# Core flags (analogs of the reference's hot-path flags, flags.cc)
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Check every eager op output for NaN/Inf")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; 1: warn")
define_flag("eager_op_jit", True, "jit-compile eager per-op executions (cached)")
define_flag("default_dtype", "float32", "default floating dtype for creation ops")
define_flag("use_donated_buffers", True, "donate param/opt buffers in jitted train steps")
define_flag("allocator_strategy", "xla", "memory allocator strategy (informational on TPU)")
define_flag("pallas_interpret", False, "force pallas kernels to run in interpret mode")
define_flag("pallas_force_compile", False,
            "force pallas kernels onto the Mosaic compile path even off-TPU "
            "(cross-platform lowering/export, e.g. jax.export platforms=['tpu'])")
define_flag("use_autotune", False,
            "Time Pallas block-size candidates per shape and cache the "
            "fastest (reference FLAGS_use_autotune)")
define_flag("autotune_cache_file", "",
            "Optional JSON file persisting autotune winners across processes")
define_flag("enable_async_trace", False, "record collective timing/debug traces")
define_flag("log_level", 1, "framework log verbosity (0=quiet)")
