"""Enforce-style structured errors (VERDICT r3 item 9).

Reference: common/enforce.h — PADDLE_ENFORCE macros throw ``EnforceNotMet``
carrying the failing condition, an error-type tag, the op context, and a
rendered hint block.  TPU-native analog: :func:`op_error_context` wraps
every :func:`run_op` execution; a failure raises :class:`EnforceNotMet`
whose message carries the op name, execution mode (eager / traced), and
each input's shape/dtype — the three things a raw jax traceback makes the
user reconstruct by hand.

Trace-control exceptions (jax concretization/tracer errors) pass through
UNWRAPPED: dy2static's graph-break fallback and user-level ``full_graph``
handling dispatch on their concrete types.
"""

from __future__ import annotations

from typing import Any, List

import jax
import numpy as np

__all__ = ["EnforceNotMet", "summarize_leaf", "op_error_context"]

# exceptions that are control-flow signals for jax tracing machinery —
# wrapping them would break isinstance dispatch upstream
_PASSTHROUGH = (
    jax.errors.TracerBoolConversionError,
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerIntegerConversionError,
    jax.errors.ConcretizationTypeError,
    KeyboardInterrupt,
    SystemExit,
)


class EnforceNotMet(RuntimeError):
    """Structured op-failure error (reference enforce.h:155
    ``EnforceNotMet``): op name + mode + per-input shape/dtype + cause.

    Raised instances are built via :func:`_make` as a DYNAMIC subclass of
    both ``EnforceNotMet`` and the original exception's type, so existing
    ``except ValueError`` / ``pytest.raises(TypeError)`` call sites keep
    working while gaining the structured message."""

    def __init__(self, op_name: str, mode: str, inputs: List[str],
                 cause: BaseException):
        self.op_name = op_name
        self.mode = mode
        self.input_summaries = inputs
        self.cause_type = type(cause).__name__
        ins = "\n".join(f"    [{i}] {s}" for i, s in enumerate(inputs)) \
            or "    (none)"
        msg = (
            f"(PreconditionNotMet) op `{op_name}` failed in {mode} mode.\n"
            f"  inputs:\n{ins}\n"
            f"  error: {self.cause_type}: {cause}\n"
            f"  [Hint: shapes/dtypes above are the op's dynamic operands; "
            f"check them against `{op_name}`'s contract.]")
        RuntimeError.__init__(self, msg)


_HYBRID_CACHE: dict = {}


def _make(op_name: str, mode: str, inputs: List[str],
          cause: BaseException) -> "EnforceNotMet":
    base = type(cause)
    cls = _HYBRID_CACHE.get(base)
    if cls is None:
        if issubclass(base, EnforceNotMet):
            cls = base
        else:
            try:
                cls = type(f"EnforceNotMet[{base.__name__}]",
                           (EnforceNotMet, base), {})
            except TypeError:      # incompatible layout (rare C exts)
                cls = EnforceNotMet
        _HYBRID_CACHE[base] = cls
    try:
        return cls(op_name, mode, inputs, cause)
    except Exception:
        return EnforceNotMet(op_name, mode, inputs, cause)


def summarize_leaf(v: Any) -> str:
    """One input rendered as shape/dtype (never materializes data)."""
    from .tensor import Tensor
    if isinstance(v, Tensor):
        v = v._value
    if isinstance(v, jax.core.Tracer):
        return f"Tracer(shape={tuple(np.shape(v))}, dtype={v.dtype})"
    if isinstance(v, (jax.Array, np.ndarray)):
        return (f"Tensor(shape={tuple(v.shape)}, "
                f"dtype={np.dtype(v.dtype).name})")
    if isinstance(v, np.generic):
        return f"scalar({np.dtype(v.dtype).name})"
    r = repr(v)
    return r if len(r) <= 40 else r[:37] + "..."


def op_error_context(name: str, dyn_values: List[Any], mode: str,
                     exc: BaseException) -> BaseException:
    """Map an op-execution failure to the error to raise: trace-control
    exceptions and already-wrapped errors pass through; everything else
    becomes :class:`EnforceNotMet` chained to the cause."""
    if isinstance(exc, _PASSTHROUGH) or isinstance(exc, EnforceNotMet):
        return exc
    try:
        summaries = [summarize_leaf(v) for v in dyn_values]
    except Exception:
        summaries = ["<unavailable>"]
    return _make(name, mode, summaries, exc)
