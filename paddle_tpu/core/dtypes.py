"""Dtype handling and binary-op type promotion.

Analog of the reference's dtype/Scalar value layer
(/root/reference/paddle/phi/common/data_type.h and the type-promotion logic
embedded in generated dygraph forwards, eager_gen.py).  On TPU the dtype set
is the JAX one; bfloat16 is first-class (MXU-native).
"""

from __future__ import annotations

from typing import Any, Union

import jax.numpy as jnp
import numpy as np

__all__ = [
    "bool_", "uint8", "int8", "int16", "int32", "int64",
    "float8_e4m3fn", "float8_e5m2", "bfloat16", "float16", "float32",
    "float64", "complex64", "complex128",
    "canonical_dtype", "default_float_dtype", "promote_types",
    "is_floating", "is_integer", "is_complex", "finfo", "iinfo",
]

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2
bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "int": jnp.int64,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float16": jnp.float16,
    "fp16": jnp.float16,
    "half": jnp.float16,
    "float32": jnp.float32,
    "fp32": jnp.float32,
    "float": jnp.float32,
    "float64": jnp.float64,
    "fp64": jnp.float64,
    "double": jnp.float64,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

DTypeLike = Union[str, type, np.dtype, Any]


def canonical_dtype(dtype: DTypeLike):
    """Resolve a user dtype spec (string alias / np dtype / jnp type) to a
    numpy dtype object (what jnp operations accept).

    64-bit policy (VERDICT r2 weak #6): with JAX x64 disabled (the TPU
    default — fp32/bf16 compute, int32 index math is what the hardware
    units do), requesting int64/uint64/float64/complex128 canonicalizes to
    the 32/64-bit-halved type EXPLICITLY here instead of warning-and-
    truncating at every op.  Indices are safe while dims stay < 2**31
    (checked at the embedding/vocab entry points); enable
    ``jax.config.update("jax_enable_x64", True)`` before first device use
    for true 64-bit."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            dtype = _ALIASES[dtype.lower()]
        except KeyError:
            raise ValueError(f"unknown dtype {dtype!r}") from None
    dt = jnp.dtype(dtype)
    import jax
    if not jax.config.jax_enable_x64:
        down = {"int64": jnp.int32, "uint64": jnp.uint32,
                "float64": jnp.float32, "complex128": jnp.complex64}
        repl = down.get(dt.name)
        if repl is not None:
            return jnp.dtype(repl)
    return dt


def index_dtype():
    """Integer dtype for index math under the 64-bit policy above."""
    import jax
    return jnp.dtype(jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)


def default_float_dtype():
    from .flags import FLAGS
    return canonical_dtype(FLAGS.default_dtype)


def set_default_dtype(dtype: DTypeLike) -> None:
    from .flags import FLAGS
    FLAGS.default_dtype = str(canonical_dtype(dtype))


def get_default_dtype() -> str:
    from .flags import FLAGS
    return FLAGS.default_dtype


def promote_types(a: DTypeLike, b: DTypeLike):
    return jnp.promote_types(canonical_dtype(a), canonical_dtype(b))


def is_floating(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(canonical_dtype(dtype), jnp.floating)


def is_integer(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(canonical_dtype(dtype), jnp.integer)


def is_complex(dtype: DTypeLike) -> bool:
    return jnp.issubdtype(canonical_dtype(dtype), jnp.complexfloating)


def finfo(dtype: DTypeLike):
    return jnp.finfo(canonical_dtype(dtype))


def iinfo(dtype: DTypeLike):
    return jnp.iinfo(canonical_dtype(dtype))
