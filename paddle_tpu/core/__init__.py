from . import autograd, device, dispatch, dtypes, flags, rng  # noqa: F401
from .autograd import backward, grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401
from .flags import FLAGS, set_flags, get_flags  # noqa: F401
from .rng import seed, get_rng_state, set_rng_state  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
