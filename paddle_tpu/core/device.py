"""Device ("place") management.

Analog of the reference's Place/Backend identity layer
(/root/reference/paddle/phi/common/place.h:58, backend.h:40) and the
DeviceContext pool (phi/core/device_context.h:37).  On TPU, streams/contexts
dissolve into XLA; a Place here is a thin wrapper over a ``jax.Device``.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "set_device", "get_device",
    "device_count", "is_compiled_with_tpu", "memory_stats",
    "memory_allocated", "max_memory_allocated",
]


class Place:
    """Device identity: ``Place('tpu', 0)`` / ``Place('cpu')``."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _platform_matches(d, self.device_type)]
        if not devs:
            # graceful fallback: whatever the default backend exposes
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def __repr__(self) -> str:
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, Place) and other.device_type == self.device_type
                and other.device_id == self.device_id)

    def __hash__(self) -> int:
        return hash((self.device_type, self.device_id))


def _platform_matches(dev: jax.Device, device_type: str) -> bool:
    p = dev.platform.lower()
    t = device_type.lower()
    if t in ("tpu", "axon"):
        return p in ("tpu", "axon")
    return p == t


def CPUPlace() -> Place:
    return Place("cpu")


def TPUPlace(device_id: int = 0) -> Place:
    return Place("tpu", device_id)


_current_place: Optional[Place] = None


def set_device(device: Union[str, Place]) -> Place:
    """``set_device('tpu:0')`` — sets the default placement for new tensors."""
    global _current_place
    if isinstance(device, str):
        if ":" in device:
            ty, idx = device.split(":", 1)
            device = Place(ty, int(idx))
        else:
            device = Place(device)
    _current_place = device
    jax.config.update("jax_default_device", device.jax_device())
    return device


def get_device() -> str:
    if _current_place is not None:
        return f"{_current_place.device_type}:{_current_place.device_id}"
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def default_place() -> Place:
    if _current_place is not None:
        return _current_place
    d = jax.devices()[0]
    return Place(d.platform, d.id)


def device_count(device_type: Optional[str] = None) -> int:
    if device_type is None:
        return jax.device_count()
    return len([d for d in jax.devices() if _platform_matches(d, device_type)])


def is_compiled_with_tpu() -> bool:
    try:
        return any(d.platform.lower() in ("tpu", "axon") for d in jax.devices())
    except RuntimeError:
        return False


def memory_stats(device: Optional[Union[str, "Place"]] = None) -> dict:
    """Device memory statistics (reference: phi/core/memory/stats.cc
    DEVICE_MEMORY_STAT / paddle.device.cuda.memory_* APIs).

    TPU-native: surfaces the PJRT allocator's live counters
    (``jax.Device.memory_stats()``) under the reference's key names.
    ``device`` accepts a Place or a 'tpu:1'-style string; default is the
    current ``set_device`` place."""
    if isinstance(device, str):
        if ":" in device:
            ty, idx = device.split(":", 1)
            device = Place(ty, int(idx))
        else:
            device = Place(device)
    elif device is None:
        device = default_place()
    dev = device.jax_device()
    raw = dev.memory_stats() or {}
    return {
        "memory.allocated.current": raw.get("bytes_in_use", 0),
        "memory.allocated.peak": raw.get("peak_bytes_in_use", 0),
        "memory.reserved.current": raw.get("bytes_reserved",
                                           raw.get("bytes_in_use", 0)),
        "memory.limit": raw.get("bytes_limit", 0),
        "raw": dict(raw),
    }


def max_memory_allocated(device=None) -> int:
    """Peak bytes allocated (reference paddle.device.cuda
    .max_memory_allocated)."""
    return int(memory_stats(device)["memory.allocated.peak"])


def memory_allocated(device=None) -> int:
    """Current bytes allocated (reference memory_allocated)."""
    return int(memory_stats(device)["memory.allocated.current"])
