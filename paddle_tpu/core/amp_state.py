"""Thread-local AMP state consumed by the dispatcher.

Analog of the reference's thread_local AmpAttrs
(/root/reference/paddle/fluid/imperative/amp_auto_cast.h:87,101) and the AMP
cast block emitted into every generated dygraph forward (eager_gen.py:317).
Here the cast policy is applied generically in core.dispatch.run_op.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp


class _AmpState(threading.local):
    def __init__(self):
        self.level = "O0"          # O0 off, O1 mixed, O2 pure
        self.dtype = jnp.bfloat16  # TPU-native low precision
        self.white = set()
        self.black = set()


_state = _AmpState()

# Default lists (reference: python/paddle/amp/amp_lists.py — white =
# matmul/conv ops, black = numerically sensitive reductions).
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "addmm", "scaled_dot_product_attention",
}
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "prod",
    "softmax", "log_softmax", "cross_entropy", "bce_with_logits",
    "binary_cross_entropy", "nll_loss", "kl_div", "layer_norm", "batch_norm",
    "group_norm", "instance_norm", "rms_norm", "norm", "logsumexp",
    "softmax_with_cross_entropy", "cumsum", "pow", "rsqrt", "sqrt",
}


def get_level() -> str:
    return _state.level


def get_dtype():
    return _state.dtype


def enabled() -> bool:
    return _state.level in ("O1", "O2")


def set_state(level: str, dtype, white=None, black=None):
    prev = (_state.level, _state.dtype, _state.white, _state.black)
    _state.level = level
    _state.dtype = dtype
    _state.white = set(white) if white is not None else set(WHITE_LIST)
    _state.black = set(black) if black is not None else set(BLACK_LIST)
    return prev


def restore_state(prev) -> None:
    _state.level, _state.dtype, _state.white, _state.black = prev


def cast_policy(op_name: str):
    """Return the target dtype for this op's float inputs, or None."""
    if _state.level == "O0":
        return None
    if _state.level == "O2":
        if op_name in _state.black:
            return jnp.float32
        return _state.dtype
    # O1
    if op_name in _state.white:
        return _state.dtype
    if op_name in _state.black:
        return jnp.float32
    return None
