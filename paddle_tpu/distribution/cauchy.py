"""Cauchy (reference python/paddle/distribution/cauchy.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _to_jnp(loc)
        self.scale = _to_jnp(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        return self.loc + self.scale * jax.random.cauchy(
            key, out, self.loc.dtype)

    def _log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -math.log(math.pi) - jnp.log(self.scale) - jnp.log1p(
            jnp.square(z))

    def _entropy(self):
        return jnp.broadcast_to(math.log(4 * math.pi) + jnp.log(self.scale),
                                self.batch_shape)

    def _cdf(self, value):
        return jnp.arctan((value - self.loc) / self.scale) / math.pi + 0.5

    def _icdf(self, value):
        return self.loc + self.scale * jnp.tan(math.pi * (value - 0.5))
