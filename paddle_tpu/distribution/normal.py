"""Normal / LogNormal (reference python/paddle/distribution/normal.py,
lognormal.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _to_jnp(loc)
        self.scale = _to_jnp(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(jnp.square(self.scale),
                                      self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(self.scale, self.batch_shape))

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        return self.loc + self.scale * jax.random.normal(
            key, out, self.loc.dtype)

    def _log_prob(self, value):
        var = jnp.square(self.scale)
        return (-jnp.square(value - self.loc) / (2 * var)
                - jnp.log(self.scale) - _HALF_LOG_2PI)

    def _entropy(self):
        return jnp.broadcast_to(
            0.5 + _HALF_LOG_2PI + jnp.log(self.scale), self.batch_shape)

    def _cdf(self, value):
        return 0.5 * (1 + jax.scipy.special.erf(
            (value - self.loc) / (self.scale * math.sqrt(2))))

    def _icdf(self, value):
        return self.loc + self.scale * math.sqrt(2) * \
            jax.scipy.special.erfinv(2 * value - 1)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _to_jnp(loc)
        self.scale = _to_jnp(scale)
        self._base = Normal(loc, scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.exp(self.loc + jnp.square(self.scale) / 2))

    @property
    def variance(self):
        s2 = jnp.square(self.scale)
        return _wrap((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def _rsample(self, shape, key):
        return jnp.exp(self._base._rsample(shape, key))

    def _log_prob(self, value):
        return self._base._log_prob(jnp.log(value)) - jnp.log(value)

    def _entropy(self):
        return self._base._entropy() + self.loc
