"""kl_divergence dispatch registry (reference
python/paddle/distribution/kl.py — register_kl :40, dispatch by most-derived
(p,q) class pair)."""

from __future__ import annotations

import math
from typing import Callable, Dict, Tuple, Type

import jax.numpy as jnp
from jax.scipy.special import betaln, digamma, gammaln

from .bernoulli import Bernoulli, Geometric
from .beta import Beta
from .categorical import Categorical
from .dirichlet import Dirichlet
from .distribution import Distribution, _wrap
from .gamma import Gamma
from .gumbel import Gumbel
from .laplace import Laplace
from .normal import LogNormal, Normal
from .poisson import Poisson
from .uniform import Uniform

_REGISTRY: Dict[Tuple[Type, Type], Callable] = {}


def register_kl(p_cls: Type[Distribution], q_cls: Type[Distribution]):
    def deco(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def _dispatch(p_cls, q_cls):
    matches = [(pc, qc) for (pc, qc) in _REGISTRY
               if issubclass(p_cls, pc) and issubclass(q_cls, qc)]
    if not matches:
        raise NotImplementedError(
            f"no KL registered for ({p_cls.__name__}, {q_cls.__name__})")
    # most-derived match: minimal by (mro distance)
    def depth(pair):
        pc, qc = pair
        return (p_cls.__mro__.index(pc) + q_cls.__mro__.index(qc))
    return _REGISTRY[min(matches, key=depth)]


def kl_divergence(p: Distribution, q: Distribution):
    return _wrap(_dispatch(type(p), type(q))(p, q))


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    result = jnp.log((q.high - q.low) / (p.high - p.low))
    return jnp.where((q.low <= p.low) & (p.high <= q.high), result, jnp.inf)


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    import jax
    lp = jax.nn.log_softmax(p.logits, -1)
    lq = jax.nn.log_softmax(q.logits, -1)
    return jnp.sum(jnp.exp(lp) * (lp - lq), -1)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    a, b = p.probs_param, q.probs_param
    return a * (jnp.log(a) - jnp.log(b)) + (1 - a) * (
        jnp.log1p(-a) - jnp.log1p(-b))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    return (betaln(q.alpha, q.beta) - betaln(p.alpha, p.beta)
            + (p.alpha - q.alpha) * digamma(p.alpha)
            + (p.beta - q.beta) * digamma(p.beta)
            + (q.alpha - p.alpha + q.beta - p.beta)
            * digamma(p.alpha + p.beta))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    pc, qc = p.concentration, q.concentration
    p0 = jnp.sum(pc, -1)
    return (gammaln(p0) - jnp.sum(gammaln(pc), -1)
            - gammaln(jnp.sum(qc, -1)) + jnp.sum(gammaln(qc), -1)
            + jnp.sum((pc - qc) * (digamma(pc)
                                   - digamma(p0[..., None])), -1))


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    pa, pb, qa, qb = p.concentration, p.rate, q.concentration, q.rate
    return ((pa - qa) * digamma(pa) - gammaln(pa) + gammaln(qa)
            + qa * (jnp.log(pb) - jnp.log(qb))
            + pa * (qb / pb - 1))


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_abs = jnp.abs(p.loc - q.loc) / q.scale
    return (-jnp.log(scale_ratio) + scale_ratio
            * jnp.exp(-loc_abs / scale_ratio) + loc_abs - 1)


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    return p.rate * (jnp.log(p.rate) - jnp.log(q.rate)) \
        - p.rate + q.rate


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    a, b = p.probs_param, q.probs_param
    return (jnp.log(a) - jnp.log(b)
            + (1 - a) / a * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    # E_p[log p - log q]; closed form via Gumbel moments
    euler = 0.57721566490153286
    beta_ratio = p.scale / q.scale
    dloc = (p.loc - q.loc) / q.scale
    return (jnp.log(q.scale) - jnp.log(p.scale)
            + euler * (beta_ratio - 1) + dloc
            + jnp.exp(-dloc + gammaln(1 + beta_ratio)) - 1)
