"""Gumbel (reference python/paddle/distribution/gumbel.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap

_EULER = 0.57721566490153286


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _to_jnp(loc)
        self.scale = _to_jnp(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(self.loc + self.scale * _EULER)

    @property
    def variance(self):
        return _wrap(jnp.square(math.pi * self.scale) / 6)

    @property
    def stddev(self):
        return _wrap(math.pi * self.scale / math.sqrt(6))

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        return self.loc + self.scale * jax.random.gumbel(
            key, out, self.loc.dtype)

    def _log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(self.scale) + 1 + _EULER,
                                self.batch_shape)

    def _cdf(self, value):
        return jnp.exp(-jnp.exp(-(value - self.loc) / self.scale))
