"""Laplace (reference python/paddle/distribution/laplace.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _to_jnp(loc)
        self.scale = _to_jnp(scale)
        batch = jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(2 * jnp.square(self.scale))

    @property
    def stddev(self):
        return _wrap(math.sqrt(2) * self.scale)

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        u = jax.random.uniform(key, out, self.loc.dtype,
                               minval=-0.5 + 1e-7, maxval=0.5)
        return self.loc - self.scale * jnp.sign(u) * jnp.log1p(
            -2 * jnp.abs(u))

    def _log_prob(self, value):
        return -jnp.abs(value - self.loc) / self.scale \
            - jnp.log(2 * self.scale)

    def _entropy(self):
        return jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                self.batch_shape)

    def _cdf(self, value):
        z = (value - self.loc) / self.scale
        return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

    def _icdf(self, value):
        t = value - 0.5
        return self.loc - self.scale * jnp.sign(t) * jnp.log1p(
            -2 * jnp.abs(t))
