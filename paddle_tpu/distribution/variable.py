"""Random-variable descriptors: event dimensionality + support constraint
(reference: python/paddle/distribution/variable.py)."""

from __future__ import annotations

from . import constraint


class Variable:
    def __init__(self, is_discrete=False, event_rank=0, constraint=None):
        self._is_discrete = is_discrete
        self._event_rank = event_rank
        self._constraint = constraint

    @property
    def is_discrete(self):
        return self._is_discrete

    @property
    def event_rank(self):
        return self._event_rank

    def constraint(self, value):
        return self._constraint(value)


class Real(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, constraint.real)


class Positive(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, constraint.positive)


class Independent(Variable):
    """Reinterprets batch dims of a base variable as event dims
    (reference: variable.py:72)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__(
            base.is_discrete,
            base.event_rank + reinterpreted_batch_rank)

    def constraint(self, value):
        return self._base.constraint(value)


class Stack(Variable):
    def __init__(self, vars, axis=0):
        self._vars = vars
        self._axis = axis
        super().__init__(
            any(v.is_discrete for v in vars),
            max(v.event_rank for v in vars))

    @property
    def is_discrete(self):
        return self._is_discrete

    def constraint(self, value):
        import jax.numpy as jnp
        from .distribution import _to_jnp, _wrap
        v = _to_jnp(value)
        parts = jnp.split(v, len(self._vars), axis=self._axis)
        outs = [_to_jnp(var.constraint(p))
                for var, p in zip(self._vars, parts)]
        return _wrap(jnp.concatenate(outs, axis=self._axis))


real = Real()
positive = Positive()
