"""MultivariateNormal (reference:
python/paddle/distribution/multivariate_normal.py).

Parameterized by one of covariance_matrix / precision_matrix / scale_tril;
internally everything routes through the Cholesky factor L (TPU-friendly:
triangular solves + one matmul per op, no explicit inverse).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap

_LOG_2PI = math.log(2.0 * math.pi)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError("Exactly one of covariance_matrix / "
                             "precision_matrix / scale_tril must be given")
        self.loc = _to_jnp(loc)
        if self.loc.ndim < 1:
            raise ValueError("loc must be at least 1-D")
        d = self.loc.shape[-1]

        if scale_tril is not None:
            st = _to_jnp(scale_tril)
            self._unbroadcasted_scale_tril = jnp.tril(st)
            self.scale_tril = st
        elif covariance_matrix is not None:
            cov = _to_jnp(covariance_matrix)
            self._unbroadcasted_scale_tril = jnp.linalg.cholesky(cov)
            self.covariance_matrix = cov
        else:
            prec = _to_jnp(precision_matrix)
            # chol(P^-1) via the flipped-Cholesky identity: if P = U Uᵀ with
            # U upper-tri (from reversing chol of reversed P), then
            # Σ = P⁻¹ = U⁻ᵀ U⁻¹ and L = U⁻ᵀ is lower-tri.
            lp = jnp.linalg.cholesky(prec[..., ::-1, ::-1])[..., ::-1, ::-1]
            eye = jnp.eye(d, dtype=prec.dtype)
            self._unbroadcasted_scale_tril = jnp.linalg.solve(
                jnp.swapaxes(lp, -1, -2), eye)
            self.precision_matrix = prec

        batch = jnp.broadcast_shapes(
            self.loc.shape[:-1], self._unbroadcasted_scale_tril.shape[:-2])
        self.loc = jnp.broadcast_to(self.loc, batch + (d,))
        self._unbroadcasted_scale_tril = jnp.broadcast_to(
            self._unbroadcasted_scale_tril, batch + (d, d))
        super().__init__(batch, (d,))

    # -- moments ----------------------------------------------------------
    @property
    def mean(self):
        return _wrap(self.loc)

    @property
    def variance(self):
        return _wrap(jnp.sum(jnp.square(self._unbroadcasted_scale_tril),
                             axis=-1))

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(jnp.sum(
            jnp.square(self._unbroadcasted_scale_tril), axis=-1)))

    # -- sampling ---------------------------------------------------------
    def _rsample(self, shape, key):
        out = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(key, out, self.loc.dtype)
        return self.loc + jnp.einsum(
            "...ij,...j->...i", self._unbroadcasted_scale_tril, eps)

    # -- density ----------------------------------------------------------
    def _mahalanobis_sq(self, value):
        diff = value - self.loc
        L = jnp.broadcast_to(self._unbroadcasted_scale_tril,
                             diff.shape[:-1] + self._unbroadcasted_scale_tril
                             .shape[-2:])
        z = jax.scipy.linalg.solve_triangular(L, diff[..., None], lower=True)
        return jnp.sum(jnp.square(z[..., 0]), axis=-1)

    def _half_log_det(self):
        return jnp.sum(jnp.log(jnp.diagonal(
            self._unbroadcasted_scale_tril, axis1=-2, axis2=-1)), axis=-1)

    def _log_prob(self, value):
        d = self.event_shape[0]
        return (-0.5 * (d * _LOG_2PI + self._mahalanobis_sq(value))
                - self._half_log_det())

    def _entropy(self):
        d = self.event_shape[0]
        return jnp.broadcast_to(
            0.5 * d * (1.0 + _LOG_2PI) + self._half_log_det(),
            self.batch_shape)

    def kl_divergence(self, other):
        """KL(self || other) for two MVNs (reference
        multivariate_normal.py kl_divergence)."""
        if not isinstance(other, MultivariateNormal):
            raise TypeError("kl_divergence expects MultivariateNormal")
        d = self.event_shape[0]
        l_p = self._unbroadcasted_scale_tril
        l_q = other._unbroadcasted_scale_tril
        # tr(Σq⁻¹ Σp) = ||Lq⁻¹ Lp||_F²
        m = jax.scipy.linalg.solve_triangular(l_q, l_p, lower=True)
        tr = jnp.sum(jnp.square(m), axis=(-2, -1))
        mah = other._mahalanobis_sq(self.loc)
        logdet = 2.0 * (other._half_log_det() - self._half_log_det())
        return _wrap(0.5 * (tr + mah - d + logdet))
