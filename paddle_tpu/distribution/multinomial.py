"""Multinomial / Binomial (reference
python/paddle/distribution/{multinomial,binomial}.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln, xlogy

from .distribution import Distribution, _to_jnp, _wrap


class Multinomial(Distribution):
    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)
        p = _to_jnp(probs)
        self.probs_param = p / jnp.sum(p, -1, keepdims=True)
        super().__init__(p.shape[:-1], p.shape[-1:])

    @property
    def probs(self):
        return _wrap(self.probs_param)

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs_param)

    @property
    def variance(self):
        p = self.probs_param
        return _wrap(self.total_count * p * (1 - p))

    def _sample(self, shape, key):
        logits = jnp.log(self.probs_param)
        k = logits.shape[-1]
        draws = jax.random.categorical(
            key, logits, axis=-1,
            shape=(self.total_count,) + tuple(shape) + self.batch_shape)
        onehot = jax.nn.one_hot(draws, k, dtype=self.probs_param.dtype)
        return jnp.sum(onehot, axis=0)

    def _log_prob(self, value):
        logits = jnp.log(self.probs_param)
        return (gammaln(jnp.asarray(self.total_count + 1.0))
                - jnp.sum(gammaln(value + 1.0), -1)
                + jnp.sum(xlogy(value, self.probs_param), -1))

    def _entropy(self):
        # exact entropy has no closed form; reference computes it by
        # summing over the support for small n — use the standard
        # approximation-free formula via samples is unstable, so follow
        # the reference's support-sum only for scalar batch & small n.
        raise NotImplementedError(
            "Multinomial.entropy has no closed form")


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _to_jnp(total_count)
        self.probs_param = _to_jnp(probs)
        batch = jnp.broadcast_shapes(self.total_count.shape,
                                     self.probs_param.shape)
        super().__init__(batch, ())

    @property
    def probs(self):
        return _wrap(self.probs_param)

    @property
    def mean(self):
        return _wrap(self.total_count * self.probs_param)

    @property
    def variance(self):
        p = self.probs_param
        return _wrap(self.total_count * p * (1 - p))

    def _sample(self, shape, key):
        # sum of n Bernoullis via binomial sampling
        return jax.random.binomial(
            key, self.total_count, self.probs_param,
            shape=tuple(shape) + self.batch_shape).astype(jnp.float32)

    def _log_prob(self, value):
        n, p = self.total_count, self.probs_param
        return (gammaln(n + 1) - gammaln(value + 1)
                - gammaln(n - value + 1)
                + xlogy(value, p) + xlogy(n - value, 1 - p))

    def _entropy(self):
        # support-sum: H = -sum_k P(k) log P(k); support is static given
        # concrete total_count
        n = int(jnp.max(self.total_count))
        ks = jnp.arange(n + 1, dtype=jnp.float32)
        shape = (n + 1,) + tuple(1 for _ in self.batch_shape)
        ks = ks.reshape(shape)
        lp = self._log_prob(ks)
        valid = ks <= self.total_count
        return -jnp.sum(jnp.where(valid, jnp.exp(lp) * lp, 0.0), axis=0)
