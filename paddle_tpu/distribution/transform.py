"""Bijective transforms (reference python/paddle/distribution/transform.py —
Transform base :96, AbsTransform, AffineTransform, ChainTransform,
ExpTransform, IndependentTransform, PowerTransform, ReshapeTransform,
SigmoidTransform, SoftmaxTransform, StackTransform,
StickBreakingTransform, TanhTransform)."""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import _to_jnp, _wrap

__all__ = ["Transform", "AbsTransform", "AffineTransform", "ChainTransform",
           "ExpTransform", "IndependentTransform", "PowerTransform",
           "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
           "StackTransform", "StickBreakingTransform", "TanhTransform"]


class Transform:
    """y = f(x); exposes forward/inverse/log-det-Jacobian.  The `_` hooks
    work on raw jnp arrays; public methods accept/return Tensors."""

    _event_rank = 0  # rank of the event the jacobian determinant covers

    def forward(self, x):
        return _wrap(self._forward(_to_jnp(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_to_jnp(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._forward_log_det_jacobian(_to_jnp(x)))

    def inverse_log_det_jacobian(self, y):
        y = _to_jnp(y)
        return _wrap(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _to_jnp(loc)
        self.scale = _to_jnp(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _to_jnp(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """Not bijective on R^n; log-det undefined (matches reference which
    omits it)."""

    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)


class StickBreakingTransform(Transform):
    _event_rank = 1

    def _forward(self, x):
        # R^{K-1} -> simplex^K
        offset = x.shape[-1] - jnp.cumsum(
            jnp.ones_like(x), -1) + 1
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.pad(z, [(0, 0)] * (x.ndim - 1) + [(0, 1)],
                       constant_values=1.0)
        one_minus = jnp.cumprod(1 - z, -1)
        om_pad = jnp.pad(one_minus, [(0, 0)] * (x.ndim - 1) + [(1, 0)],
                         constant_values=1.0)
        return zpad * om_pad

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = y_crop.shape[-1] - jnp.cumsum(
            jnp.ones_like(y_crop), -1) + 1
        denom = 1 - jnp.cumsum(y_crop, -1) + y_crop
        z = y_crop / denom
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        offset = x.shape[-1] - jnp.cumsum(jnp.ones_like(x), -1) + 1
        z = jax.nn.sigmoid(x - jnp.log(offset))
        one_minus = jnp.cumprod(1 - z, -1)
        om_pad = jnp.pad(one_minus[..., :-1],
                         [(0, 0)] * (x.ndim - 1) + [(1, 0)],
                         constant_values=1.0)
        # dy_k/dx_k = z*(1-z) * prod_{j<k}(1-z_j); offset only shifts the
        # sigmoid argument and does not scale the Jacobian
        detj = jnp.log(z) + jnp.log1p(-z) + jnp.log(om_pad)
        return jnp.sum(detj, -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._event_rank = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:-n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:-n]) + self.in_event_shape


class IndependentTransform(Transform):
    """Reinterpret `reinterpreted_batch_rank` batch dims as event dims: the
    log-det sums over them."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._event_rank = base._event_rank + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x)
        return jnp.sum(ldj, axis=tuple(range(-self.rank, 0)))


class ChainTransform(Transform):
    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)
        self._event_rank = max((t._event_rank for t in self.transforms),
                               default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            ldj = t._forward_log_det_jacobian(x)
            # reduce finer-grained jacobians to this chain's event rank
            extra = self._event_rank - t._event_rank
            if extra > 0 and jnp.ndim(ldj) >= extra:
                ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
            total = total + ldj
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class StackTransform(Transform):
    """Apply transforms[i] to slice i along `axis`."""

    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        self.transforms = list(transforms)
        self.axis = axis

    def _split(self, x):
        return [jnp.squeeze(s, self.axis) for s in
                jnp.split(x, len(self.transforms), self.axis)]

    def _forward(self, x):
        return jnp.stack([t._forward(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)

    def _inverse(self, y):
        return jnp.stack([t._inverse(s) for t, s in
                          zip(self.transforms, self._split(y))], self.axis)

    def _forward_log_det_jacobian(self, x):
        return jnp.stack([t._forward_log_det_jacobian(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)
