"""Distribution base class (reference
python/paddle/distribution/distribution.py) + shared helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_rng_key
from ..core.tensor import Tensor

__all__ = ["Distribution", "ExponentialFamily", "_to_jnp", "_wrap",
           "_shape_tuple"]


def _to_jnp(x, dtype=None):
    """Accept Tensor / ndarray / python scalar, return jnp array."""
    if isinstance(x, Tensor):
        v = x._value
    else:
        v = x
    arr = jnp.asarray(v)
    if dtype is not None:
        arr = arr.astype(dtype)
    elif arr.dtype in (jnp.int32, jnp.int64) and not jnp.issubdtype(
            arr.dtype, jnp.floating):
        arr = arr.astype(jnp.float32)
    return arr


def _wrap(v) -> Tensor:
    return Tensor(v, stop_gradient=True)


def _shape_tuple(shape) -> Tuple[int, ...]:
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Base API (sample/rsample/prob/log_prob/entropy/kl_divergence),
    mirroring the reference Distribution (distribution.py:40)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape_tuple(batch_shape)
        self._event_shape = _shape_tuple(event_shape)

    @property
    def batch_shape(self) -> Tuple[int, ...]:
        return self._batch_shape

    @property
    def event_shape(self) -> Tuple[int, ...]:
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    # -- sampling ---------------------------------------------------------
    def _next_key(self):
        return next_rng_key()

    def sample(self, shape=()):
        return _wrap(jax.lax.stop_gradient(
            self._sample(_shape_tuple(shape), self._next_key())))

    def rsample(self, shape=()):
        return _wrap(self._rsample(_shape_tuple(shape), self._next_key()))

    def _sample(self, shape, key):
        return self._rsample(shape, key)

    def _rsample(self, shape, key):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement rsample")

    # -- densities --------------------------------------------------------
    def prob(self, value):
        return _wrap(jnp.exp(self._log_prob(_to_jnp(value))))

    def log_prob(self, value):
        return _wrap(self._log_prob(_to_jnp(value)))

    def _log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        return _wrap(self._entropy())

    def _entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        return _wrap(self._cdf(_to_jnp(value)))

    def _cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        return _wrap(self._icdf(_to_jnp(value)))

    def _icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution"):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self._batch_shape}, "
                f"event_shape={self._event_shape})")


class ExponentialFamily(Distribution):
    """Exponential-family base: generic entropy via Bregman identity is not
    needed on TPU — subclasses give closed forms; kept for API parity
    (reference exponential_family.py)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError
