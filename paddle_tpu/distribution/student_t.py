"""StudentT (reference python/paddle/distribution/student_t.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma

from .distribution import Distribution, _to_jnp, _wrap


class StudentT(Distribution):
    def __init__(self, df, loc, scale, name=None):
        self.df = _to_jnp(df)
        self.loc = _to_jnp(loc)
        self.scale = _to_jnp(scale)
        batch = jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                     self.scale.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(jnp.where(self.df > 1,
                               jnp.broadcast_to(self.loc, self.batch_shape),
                               jnp.nan))

    @property
    def variance(self):
        v = jnp.square(self.scale) * self.df / (self.df - 2)
        return _wrap(jnp.where(self.df > 2, v,
                               jnp.where(self.df > 1, jnp.inf, jnp.nan)))

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        return self.loc + self.scale * jax.random.t(
            key, self.df, out, self.loc.dtype)

    def _log_prob(self, value):
        # lgamma((d+1)/2) - lgamma(d/2) - 0.5*log(d*pi) collapses to
        # -betaln(d/2, 1/2) - 0.5*log(d) since B(a,1/2)=G(a)G(1/2)/G(a+1/2)
        z = (value - self.loc) / self.scale
        d = self.df
        return (-0.5 * (d + 1) * jnp.log1p(jnp.square(z) / d)
                - 0.5 * jnp.log(d)
                - betaln(0.5 * d, jnp.asarray(0.5)) - jnp.log(self.scale))

    def _entropy(self):
        d = self.df
        return (0.5 * (d + 1) * (digamma(0.5 * (d + 1)) - digamma(0.5 * d))
                + 0.5 * jnp.log(d) + betaln(0.5 * d, jnp.asarray(0.5))
                + jnp.log(self.scale))
