"""paddle.distribution parity (reference python/paddle/distribution/ — 30
files: Distribution base, ~20 concrete distributions, transforms,
kl_divergence registry).

TPU-first: every density/sample is pure jnp (jit-safe under ``to_static``);
sampling draws keys from the framework RNG (core/rng.py) so seeding via
``paddle_tpu.seed`` is reproducible.
"""

from .distribution import Distribution, ExponentialFamily  # noqa: F401
from .normal import LogNormal, Normal  # noqa: F401
from .uniform import Uniform  # noqa: F401
from .categorical import Categorical  # noqa: F401
from .bernoulli import Bernoulli, ContinuousBernoulli, Geometric  # noqa: F401
from .beta import Beta  # noqa: F401
from .dirichlet import Dirichlet  # noqa: F401
from .gamma import Chi2, Exponential, Gamma  # noqa: F401
from .laplace import Laplace  # noqa: F401
from .multinomial import Binomial, Multinomial  # noqa: F401
from .cauchy import Cauchy  # noqa: F401
from .gumbel import Gumbel  # noqa: F401
from .poisson import Poisson  # noqa: F401
from .student_t import StudentT  # noqa: F401
from .independent import Independent  # noqa: F401
from .transformed_distribution import TransformedDistribution  # noqa: F401
from .transform import (  # noqa: F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    Transform,
)
from .kl import kl_divergence, register_kl  # noqa: F401
from .multivariate_normal import MultivariateNormal  # noqa: F401
from .lkj_cholesky import LKJCholesky  # noqa: F401
from . import constraint  # noqa: F401
from . import variable  # noqa: F401

__all__ = [
    "Distribution", "ExponentialFamily", "Normal", "LogNormal", "Uniform",
    "Categorical", "Bernoulli", "ContinuousBernoulli", "Geometric", "Beta",
    "Dirichlet", "Gamma", "Chi2", "Exponential", "Laplace", "Multinomial",
    "Binomial", "Cauchy", "Gumbel", "Poisson", "StudentT", "Independent",
    "TransformedDistribution", "Transform", "AbsTransform",
    "AffineTransform", "ChainTransform", "ExpTransform",
    "IndependentTransform", "PowerTransform", "ReshapeTransform",
    "SigmoidTransform", "SoftmaxTransform", "StackTransform",
    "StickBreakingTransform", "TanhTransform", "kl_divergence",
    "register_kl", "MultivariateNormal", "LKJCholesky",
]
