"""Poisson (reference python/paddle/distribution/poisson.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln, xlogy

from .distribution import ExponentialFamily, _to_jnp, _wrap


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _to_jnp(rate)
        super().__init__(self.rate.shape, ())

    @property
    def mean(self):
        return _wrap(self.rate)

    @property
    def variance(self):
        return _wrap(self.rate)

    def _sample(self, shape, key):
        out = self._extend_shape(shape)
        return jax.random.poisson(key, self.rate, out).astype(
            self.rate.dtype)

    def _log_prob(self, value):
        return xlogy(value, self.rate) - self.rate - gammaln(value + 1)

    def _entropy(self):
        # support-sum truncated at rate + 10*sqrt(rate) + 20 terms
        n = int(jnp.max(self.rate) + 10 * jnp.sqrt(jnp.max(self.rate)) + 20)
        ks = jnp.arange(n + 1, dtype=jnp.float32)
        ks = ks.reshape((n + 1,) + tuple(1 for _ in self.batch_shape))
        lp = self._log_prob(ks)
        return -jnp.sum(jnp.exp(lp) * lp, axis=0)
