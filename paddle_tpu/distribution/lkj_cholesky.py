"""LKJCholesky (reference: python/paddle/distribution/lkj_cholesky.py):
distribution over Cholesky factors of correlation matrices, LKJ (2009).

Sampling uses the onion method ("onion" is also the reference's default);
log_prob follows the standard LKJ density on Cholesky factors:
log p(L) ∝ Σ_i (dim - i - 1 + 2(η - 1)) · log L_ii, plus the
concentration-dependent normalizer.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap


def _mvlgamma(a, p):
    """Multivariate log-gamma Γ_p(a)."""
    out = (p * (p - 1) / 4.0) * math.log(math.pi)
    for j in range(p):
        out = out + jax.scipy.special.gammaln(a - j / 2.0)
    return out


def _log_normalizer(conc, dim):
    """log C(η, d) of the LKJ-Cholesky density: with α = η + (d−1)/2,
    C = π^{(d−1)/2} · Γ_{d−1}(α − 1/2) / Γ(α)^{d−1} (LKJ 2009 eq. 16)."""
    dm1 = dim - 1
    alpha = conc + 0.5 * dm1
    return (0.5 * dm1 * math.log(math.pi)
            + _mvlgamma(alpha - 0.5, dm1)
            - dm1 * jax.scipy.special.gammaln(alpha))


class LKJCholesky(Distribution):
    def __init__(self, dim, concentration=1.0,
                 sample_method="onion", name=None):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError("sample_method must be 'onion' or 'cvine'")
        self.dim = int(dim)
        self.concentration = _to_jnp(concentration).astype(jnp.float32)
        self.sample_method = sample_method
        batch = self.concentration.shape
        super().__init__(batch, (self.dim, self.dim))

    def _rsample(self, shape, key):
        """Onion method (LKJ 2009 §3.2; same algorithm family as the
        reference's _onion)."""
        d = self.dim
        batch = tuple(shape) + self.batch_shape
        conc = jnp.broadcast_to(self.concentration, batch)
        k_beta, k_norm = jax.random.split(key)

        # marginal beta draws control each row's radius
        L = jnp.zeros(batch + (d, d), jnp.float32)
        L = L.at[..., 0, 0].set(1.0)
        offset = jnp.arange(d - 1, dtype=jnp.float32)
        beta_conc1 = offset / 2.0 + 0.5
        beta_conc0 = conc[..., None] + (d - 2) / 2.0 - offset / 2.0
        # y_i ~ Beta(i/2 + 1/2, η + (d-2)/2 - i/2), i = row-1
        y = jax.random.beta(k_beta, beta_conc1, beta_conc0,
                            batch + (d - 1,))
        # row directions: uniform on the sphere via normalized gaussians
        u = jax.random.normal(k_norm, batch + (d - 1, d - 1))
        rows = []
        for i in range(1, d):
            vec = u[..., i - 1, :i]
            vec = vec / jnp.linalg.norm(vec, axis=-1, keepdims=True)
            r = jnp.sqrt(y[..., i - 1])
            w = r[..., None] * vec
            diag = jnp.sqrt(jnp.clip(1.0 - jnp.square(r), 1e-12, None))
            rows.append((w, diag))
        for i, (w, diag) in enumerate(rows, start=1):
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(diag)
        return L

    def _log_prob(self, value):
        d = self.dim
        diag = jnp.diagonal(value, axis1=-2, axis2=-1)[..., 1:]
        order = jnp.arange(2, d + 1, dtype=jnp.float32)
        exponents = 2.0 * (self.concentration[..., None] - 1.0) + d - order
        unnorm = jnp.sum(exponents * jnp.log(diag), axis=-1)
        return unnorm - _log_normalizer(self.concentration, d)

    @property
    def mean(self):
        raise NotImplementedError("LKJCholesky has no closed-form mean")
