"""Uniform (reference python/paddle/distribution/uniform.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _to_jnp(low)
        self.high = _to_jnp(high)
        batch = jnp.broadcast_shapes(self.low.shape, self.high.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap((self.low + self.high) / 2)

    @property
    def variance(self):
        return _wrap(jnp.square(self.high - self.low) / 12)

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        u = jax.random.uniform(key, out, self.low.dtype)
        return self.low + (self.high - self.low) * u

    def _log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def _entropy(self):
        return jnp.broadcast_to(jnp.log(self.high - self.low),
                                self.batch_shape)

    def _cdf(self, value):
        return jnp.clip((value - self.low) / (self.high - self.low), 0., 1.)

    def _icdf(self, value):
        return self.low + (self.high - self.low) * value
