"""TransformedDistribution (reference
python/paddle/distribution/transformed_distribution.py)."""

from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    def __init__(self, base: Distribution, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = ChainTransform(list(transforms))
        shape = self.transform.forward_shape(
            base.batch_shape + base.event_shape)
        # event rank grows to at least the chain's event rank
        ev = max(len(base.event_shape), self.transform._event_rank)
        super().__init__(shape[:len(shape) - ev],
                         shape[len(shape) - ev:])

    def _sample(self, shape, key):
        return self.transform._forward(self.base._sample(shape, key))

    def _rsample(self, shape, key):
        return self.transform._forward(self.base._rsample(shape, key))

    def _log_prob(self, value):
        x = self.transform._inverse(value)
        lp = self.base._log_prob(x)
        ldj = self.transform._forward_log_det_jacobian(x)
        out = lp - ldj
        # reduce over event dims the transform's jacobian did not cover
        red = len(self.event_shape) - self.transform._event_rank \
            - len(self.base.event_shape)
        if red > 0:
            out = jnp.sum(out, axis=tuple(range(-red, 0)))
        return out
