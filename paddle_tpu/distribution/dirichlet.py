"""Dirichlet (reference python/paddle/distribution/dirichlet.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from .distribution import ExponentialFamily, _to_jnp, _wrap


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = _to_jnp(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        c = self.concentration
        return _wrap(c / jnp.sum(c, -1, keepdims=True))

    @property
    def variance(self):
        c = self.concentration
        c0 = jnp.sum(c, -1, keepdims=True)
        m = c / c0
        return _wrap(m * (1 - m) / (c0 + 1))

    def _rsample(self, shape, key):
        return jax.random.dirichlet(key, self.concentration,
                                    tuple(shape) + self.batch_shape)

    def _log_prob(self, value):
        c = self.concentration
        return (jnp.sum((c - 1) * jnp.log(value), -1)
                + gammaln(jnp.sum(c, -1)) - jnp.sum(gammaln(c), -1))

    def _entropy(self):
        c = self.concentration
        c0 = jnp.sum(c, -1)
        k = c.shape[-1]
        return (jnp.sum(gammaln(c), -1) - gammaln(c0)
                + (c0 - k) * digamma(c0)
                - jnp.sum((c - 1) * digamma(c), -1))
