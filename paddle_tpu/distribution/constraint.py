"""Value constraints for distribution supports (reference:
python/paddle/distribution/constraint.py)."""

from __future__ import annotations

import jax.numpy as jnp

from .distribution import _to_jnp, _wrap


class Constraint:
    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        v = _to_jnp(value)
        return _wrap(v == v)


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper

    def __call__(self, value):
        v = _to_jnp(value)
        return _wrap((self._lower <= v) & (v <= self._upper))


class Positive(Constraint):
    def __call__(self, value):
        return _wrap(_to_jnp(value) >= 0.0)


class Simplex(Constraint):
    def __call__(self, value):
        v = _to_jnp(value)
        return _wrap(jnp.all(v >= 0, axis=-1)
                     & (jnp.abs(v.sum(-1) - 1.0) < 1e-6))


real = Real()
positive = Positive()
simplex = Simplex()
