"""Bernoulli / ContinuousBernoulli / Geometric (reference
python/paddle/distribution/{bernoulli,continuous_bernoulli,geometric}.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import ExponentialFamily, Distribution, _to_jnp, _wrap

_EPS = 1e-7


def _clip_p(p):
    return jnp.clip(p, _EPS, 1 - _EPS)


class Bernoulli(ExponentialFamily):
    def __init__(self, probs, name=None):
        self.probs_param = _clip_p(_to_jnp(probs))
        super().__init__(self.probs_param.shape, ())

    @property
    def probs(self):
        return _wrap(self.probs_param)

    @property
    def logits(self):
        p = self.probs_param
        return _wrap(jnp.log(p) - jnp.log1p(-p))

    @property
    def mean(self):
        return _wrap(self.probs_param)

    @property
    def variance(self):
        return _wrap(self.probs_param * (1 - self.probs_param))

    def _sample(self, shape, key):
        out = self._extend_shape(shape)
        return jax.random.bernoulli(
            key, self.probs_param, out).astype(self.probs_param.dtype)

    def _log_prob(self, value):
        p = self.probs_param
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p)

    def _entropy(self):
        p = self.probs_param
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

    def _cdf(self, value):
        p = self.probs_param
        return jnp.where(value < 0, 0.0,
                         jnp.where(value < 1, 1 - p, 1.0))


class ContinuousBernoulli(Distribution):
    """Continuous Bernoulli on [0,1] (Loaiza-Ganem & Cunningham 2019)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs_param = _clip_p(_to_jnp(probs))
        self._lims = lims
        super().__init__(self.probs_param.shape, ())

    def _outside(self):
        lo, hi = self._lims
        return (self.probs_param < lo) | (self.probs_param > hi)

    def _log_norm_const(self):
        # C(p) = log |2 atanh(1-2p) / (1-2p)| for p != 0.5, else log 2
        p = self.probs_param
        safe = jnp.where(self._outside(), p, 0.4)
        x = 1 - 2 * safe
        log_c = jnp.log(jnp.abs(2 * jnp.arctanh(x))) - jnp.log(jnp.abs(x))
        # Taylor around p=0.5: log 2 + log(1 + x^2/3 + ...)
        t = 1 - 2 * p
        taylor = jnp.log(2.0) + (4.0 / 3) * jnp.square(t) / 2
        return jnp.where(self._outside(), log_c, taylor)

    @property
    def mean(self):
        p = self.probs_param
        safe = jnp.where(self._outside(), p, 0.4)
        x = 1 - 2 * safe
        m = safe / x + 1 / (2 * jnp.arctanh(x))
        return _wrap(jnp.where(self._outside(), m,
                               0.5 + (p - 0.5) / 3))

    @property
    def variance(self):
        # numeric: var = E[v^2]-mean^2 via quadrature is overkill; use the
        # closed form v = p(1-p)/x^2 + 1/(2 atanh(x))^2 with Taylor fallback
        p = self.probs_param
        safe = jnp.where(self._outside(), p, 0.4)
        x = 1 - 2 * safe
        v = safe * (1 - safe) / jnp.square(x) \
            + 1 / jnp.square(2 * jnp.arctanh(x))
        return _wrap(jnp.where(self._outside(), v,
                               1.0 / 12 - jnp.square(p - 0.5) / 15))

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        u = jax.random.uniform(key, out, self.probs_param.dtype,
                               minval=_EPS, maxval=1 - _EPS)
        return self._icdf(u)

    def _log_prob(self, value):
        p = self.probs_param
        return (value * jnp.log(p) + (1 - value) * jnp.log1p(-p)
                + self._log_norm_const())

    def _cdf(self, value):
        p = self.probs_param
        safe = jnp.where(self._outside(), p, 0.4)
        num = (jnp.power(safe, value) * jnp.power(1 - safe, 1 - value)
               + safe - 1)
        cdf = num / (2 * safe - 1)
        return jnp.clip(jnp.where(self._outside(), cdf, value), 0., 1.)

    def _icdf(self, value):
        p = self.probs_param
        safe = jnp.where(self._outside(), p, 0.4)
        ratio = jnp.log1p(-safe) - jnp.log(safe)
        x = (jnp.log1p(value * (2 * safe - 1) / (1 - safe))) / (-ratio)
        return jnp.where(self._outside(), x, value)

    def _entropy(self):
        p = self.probs_param
        m = jnp.asarray(self.mean._value)
        return -(m * jnp.log(p) + (1 - m) * jnp.log1p(-p)
                 + self._log_norm_const())


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs_param = _clip_p(_to_jnp(probs))
        super().__init__(self.probs_param.shape, ())

    @property
    def mean(self):
        return _wrap((1 - self.probs_param) / self.probs_param)

    @property
    def variance(self):
        p = self.probs_param
        return _wrap((1 - p) / jnp.square(p))

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(jnp.asarray(self.variance._value)))

    def _sample(self, shape, key):
        out = self._extend_shape(shape)
        u = jax.random.uniform(key, out, self.probs_param.dtype,
                               minval=_EPS, maxval=1 - _EPS)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_param))

    def _log_prob(self, value):
        p = self.probs_param
        return value * jnp.log1p(-p) + jnp.log(p)

    def _entropy(self):
        p = self.probs_param
        return -((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p

    def _cdf(self, value):
        return 1 - jnp.power(1 - self.probs_param, jnp.floor(value) + 1)
