"""Beta (reference python/paddle/distribution/beta.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma

from .distribution import ExponentialFamily, _to_jnp, _wrap


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _to_jnp(alpha)
        self.beta = _to_jnp(beta)
        batch = jnp.broadcast_shapes(self.alpha.shape, self.beta.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(self.alpha * self.beta / (jnp.square(s) * (s + 1)))

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        return jax.random.beta(key, self.alpha, self.beta, out)

    def _log_prob(self, value):
        return ((self.alpha - 1) * jnp.log(value)
                + (self.beta - 1) * jnp.log1p(-value)
                - betaln(self.alpha, self.beta))

    def _entropy(self):
        a, b = self.alpha, self.beta
        return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))
