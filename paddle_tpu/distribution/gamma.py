"""Gamma / Exponential / Chi2 (reference
python/paddle/distribution/{gamma,exponential,chi2}.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammainc, gammaln

from .distribution import ExponentialFamily, _to_jnp, _wrap


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _to_jnp(concentration)
        self.rate = _to_jnp(rate)
        batch = jnp.broadcast_shapes(self.concentration.shape,
                                     self.rate.shape)
        super().__init__(batch, ())

    @property
    def mean(self):
        return _wrap(self.concentration / self.rate)

    @property
    def variance(self):
        return _wrap(self.concentration / jnp.square(self.rate))

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        return jax.random.gamma(key, self.concentration, out) / self.rate

    def _log_prob(self, value):
        a, b = self.concentration, self.rate
        return (a * jnp.log(b) + (a - 1) * jnp.log(value) - b * value
                - gammaln(a))

    def _entropy(self):
        a, b = self.concentration, self.rate
        return a - jnp.log(b) + gammaln(a) + (1 - a) * digamma(a)

    def _cdf(self, value):
        return gammainc(self.concentration, self.rate * value)


class Exponential(Gamma):
    def __init__(self, rate, name=None):
        rate = _to_jnp(rate)
        super().__init__(jnp.ones_like(rate), rate)

    def _rsample(self, shape, key):
        out = self._extend_shape(shape)
        return jax.random.exponential(key, out, self.rate.dtype) / self.rate

    def _icdf(self, value):
        return -jnp.log1p(-value) / self.rate


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _to_jnp(df)
        self.df = df
        super().__init__(df / 2, jnp.full_like(df, 0.5))
