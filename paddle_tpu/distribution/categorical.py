"""Categorical (reference python/paddle/distribution/categorical.py).

Paddle's Categorical takes unnormalized ``logits`` and normalizes by the
sum of probabilities."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _to_jnp, _wrap


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _to_jnp(logits)
        super().__init__(self.logits.shape[:-1], ())

    @property
    def probs_array(self):
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def probs(self):
        return _wrap(self.probs_array)

    @property
    def mean(self):
        p = self.probs_array
        k = jnp.arange(p.shape[-1], dtype=p.dtype)
        return _wrap(jnp.sum(p * k, -1))

    @property
    def variance(self):
        p = self.probs_array
        k = jnp.arange(p.shape[-1], dtype=p.dtype)
        m = jnp.sum(p * k, -1, keepdims=True)
        return _wrap(jnp.sum(p * jnp.square(k - m), -1))

    def _sample(self, shape, key):
        return jax.random.categorical(
            key, jax.nn.log_softmax(self.logits, -1),
            shape=tuple(shape) + self.batch_shape)

    def _log_prob(self, value):
        lp = jax.nn.log_softmax(self.logits, -1)
        idx = value.astype(jnp.int32)
        return jnp.take_along_axis(
            jnp.broadcast_to(lp, idx.shape + lp.shape[-1:]),
            idx[..., None], axis=-1)[..., 0]

    def _entropy(self):
        lp = jax.nn.log_softmax(self.logits, -1)
        return -jnp.sum(jnp.exp(lp) * lp, -1)
