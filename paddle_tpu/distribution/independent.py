"""Independent (reference python/paddle/distribution/independent.py):
reinterpret trailing batch dims of a base distribution as event dims."""

from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, _wrap


class Independent(Distribution):
    def __init__(self, base: Distribution, reinterpreted_batch_rank: int):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        if self.rank > len(base.batch_shape):
            raise ValueError(
                "reinterpreted_batch_rank exceeds base batch rank")
        split = len(base.batch_shape) - self.rank
        super().__init__(base.batch_shape[:split],
                         base.batch_shape[split:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def _sample(self, shape, key):
        return self.base._sample(shape, key)

    def _rsample(self, shape, key):
        return self.base._rsample(shape, key)

    def _log_prob(self, value):
        lp = self.base._log_prob(value)
        if self.rank == 0:
            return lp
        return jnp.sum(lp, axis=tuple(range(-self.rank, 0)))

    def _entropy(self):
        ent = self.base._entropy()
        if self.rank == 0:
            return ent
        return jnp.sum(ent, axis=tuple(range(-self.rank, 0)))
