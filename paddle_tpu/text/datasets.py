"""Text datasets (reference python/paddle/text/datasets/*.py — Imdb,
Imikolov, Movielens, UCIHousing, WMT14, WMT16, Conll05st).

The reference downloads from paddle-dataset URLs; this environment has zero
egress, so every class requires the archive via ``data_file=`` (same
contract as the reference's cached-download path — the parsing/iteration
logic is faithful)."""

from __future__ import annotations

import gzip
import os
import re
import tarfile
from typing import List, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st"]


def _require(data_file: Optional[str], name: str) -> str:
    if data_file is None or not os.path.exists(data_file):
        raise RuntimeError(
            f"{name}: automatic download is unavailable (no network); pass "
            f"data_file= pointing at the standard archive")
    return data_file


class UCIHousing(Dataset):
    """506 rows x (13 features, 1 target); file = whitespace floats
    (reference text/datasets/uci_housing.py)."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train", download=False):
        path = _require(data_file, "UCIHousing")
        raw = np.loadtxt(path).astype(np.float32)
        feats = raw[:, :-1]
        # feature normalization exactly like the reference (max/min/avg)
        maxs, mins, avgs = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avgs) / (maxs - mins + 1e-9)
        n_train = int(len(raw) * 0.8)
        if mode == "train":
            self.x, self.y = feats[:n_train], raw[:n_train, -1:]
        else:
            self.x, self.y = feats[n_train:], raw[n_train:, -1:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class Imdb(Dataset):
    """IMDB sentiment; archive = aclImdb tar.gz (reference imdb.py:
    tokenize, build word dict, label pos=0/neg=1)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        path = _require(data_file, "Imdb")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                match = pat.match(m.name)
                if not match:
                    continue
                text = tf.extractfile(m).read().decode(
                    "utf-8", "ignore").lower()
                toks = re.findall(r"[a-z]+", text)
                docs.append(toks)
                labels.append(0 if match.group(1) == "pos" else 1)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        kept = [w for w, c in sorted(freq.items(),
                                     key=lambda kv: (-kv[1], kv[0]))
                if c >= cutoff]
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(t, unk) for t in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Imikolov(Dataset):
    """PTB n-gram dataset (reference imikolov.py): yields n-grams as
    (w0..w_{n-2}, w_{n-1})."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        path = _require(data_file, "Imikolov")
        fname = f"./simple-examples/data/ptb.{mode}.txt"
        freq = {}
        lines = []
        with tarfile.open(path) as tf:
            f = tf.extractfile(fname)
            for ln in f.read().decode().splitlines():
                toks = ln.strip().split()
                lines.append(toks)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        kept = sorted((w for w, c in freq.items()
                       if c >= min_word_freq and w != "<unk>"))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        n = window_size
        for toks in lines:
            ids = [self.word_idx.get(t, unk) for t in toks]
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - n + 1):
                    self.data.append(np.array(ids[i:i + n], np.int64))
            else:  # SEQ
                self.data.append(np.array(ids, np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class Movielens(Dataset):
    """ml-1m ratings (reference movielens.py): (user feats, movie feats,
    rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        path = _require(data_file, "Movielens")
        users, movies, ratings = {}, {}, []
        with tarfile.open(path) as tf:
            base = "ml-1m"
            for ln in tf.extractfile(f"{base}/users.dat").read().decode(
                    "latin1").splitlines():
                uid, gender, age, job, _zip = ln.split("::")
                users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                   int(job))
            for ln in tf.extractfile(f"{base}/movies.dat").read().decode(
                    "latin1").splitlines():
                mid, title, genres = ln.split("::")
                movies[int(mid)] = (title, genres.split("|"))
            for ln in tf.extractfile(f"{base}/ratings.dat").read().decode(
                    "latin1").splitlines():
                uid, mid, rate, _ts = ln.split("::")
                ratings.append((int(uid), int(mid), float(rate)))
        rng = np.random.default_rng(rand_seed)
        mask = rng.random(len(ratings)) < test_ratio
        self.samples = []
        for i, (uid, mid, rate) in enumerate(ratings):
            if (mode == "test") != bool(mask[i]):
                continue
            if uid not in users or mid not in movies:
                continue
            g, a, j = users[uid]
            self.samples.append((
                np.array([uid, g, a, j], np.int64),
                np.array([mid], np.int64),
                np.array([rate], np.float32)))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]


class _ParallelCorpus(Dataset):
    """Shared WMT loader: source/target token-id sequences with
    <s>/<e>/<unk> handling (reference wmt14.py/wmt16.py)."""

    BOS, EOS, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, pairs: List, dict_size: int):
        freq = {}
        for src, trg in pairs:
            for t in src + trg:
                freq[t] = freq.get(t, 0) + 1
        kept = [w for w, _ in sorted(freq.items(),
                                     key=lambda kv: (-kv[1], kv[0]))]
        vocab = [self.BOS, self.EOS, self.UNK] + kept[:max(dict_size - 3, 0)]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = self.word_idx[self.UNK]
        self.src_ids, self.trg_ids, self.trg_next = [], [], []
        for src, trg in pairs:
            s = [self.word_idx.get(t, unk) for t in src]
            t_in = [self.word_idx[self.BOS]] + [
                self.word_idx.get(t, unk) for t in trg]
            t_out = [self.word_idx.get(t, unk) for t in trg] + [
                self.word_idx[self.EOS]]
            self.src_ids.append(np.array(s, np.int64))
            self.trg_ids.append(np.array(t_in, np.int64))
            self.trg_next.append(np.array(t_out, np.int64))

    def __len__(self):
        return len(self.src_ids)

    def __getitem__(self, i):
        return self.src_ids[i], self.trg_ids[i], self.trg_next[i]


class WMT14(_ParallelCorpus):
    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=False):
        path = _require(data_file, "WMT14")
        pairs = []
        sub = {"train": "train/", "test": "test/", "gen": "gen/"}[mode]
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if sub in m.name and m.isfile():
                    for ln in tf.extractfile(m).read().decode(
                            "utf-8", "ignore").splitlines():
                        parts = ln.split("\t")
                        if len(parts) >= 2:
                            pairs.append((parts[0].split(),
                                          parts[1].split()))
        super().__init__(pairs, dict_size)


class WMT16(_ParallelCorpus):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=False):
        path = _require(data_file, "WMT16")
        pairs = []
        with tarfile.open(path) as tf:
            name = f"wmt16/{mode}"
            for m in tf.getmembers():
                if m.name.startswith(name) and m.isfile():
                    for ln in tf.extractfile(m).read().decode(
                            "utf-8", "ignore").splitlines():
                        parts = ln.split("\t")
                        if len(parts) >= 2:
                            pairs.append((parts[0].split(),
                                          parts[1].split()))
        super().__init__(pairs, max(src_dict_size, trg_dict_size))


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference conll05.py): (word_ids, ctx, ...,
    label_ids) per proposition.  Requires the combined test archive."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None,
                 download=False):
        path = _require(data_file, "Conll05st")
        self.sentences = []
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", errors="ignore") as f:
            words, labels = [], []
            for ln in f:
                ln = ln.strip()
                if not ln:
                    if words:
                        self.sentences.append((words, labels))
                    words, labels = [], []
                    continue
                parts = ln.split()
                words.append(parts[0])
                labels.append(parts[-1] if len(parts) > 1 else "O")
            if words:
                self.sentences.append((words, labels))
        vocab = sorted({w for ws, _ in self.sentences for w in ws})
        tags = sorted({t for _, ts in self.sentences for t in ts})
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.label_idx = {t: i for i, t in enumerate(tags)}

    def __len__(self):
        return len(self.sentences)

    def __getitem__(self, i):
        ws, ts = self.sentences[i]
        return (np.array([self.word_idx[w] for w in ws], np.int64),
                np.array([self.label_idx[t] for t in ts], np.int64))
