"""paddle.text parity (reference python/paddle/text/ — datasets Imdb,
Imikolov, Movielens, UCIHousing, WMT14/16, Conll05 + viterbi_decode,
ViterbiDecoder from paddle.text.viterbi_decode).

Dataset classes share the reference's contract (len/getitem over
numpy-encoded samples) but generate/load from local files — the image has
zero egress, so the download path raises with a clear message unless the
data file is already present (data_file=... like the reference's cached
mode).
"""

from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st", "viterbi_decode", "ViterbiDecoder"]
