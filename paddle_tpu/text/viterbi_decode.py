"""Viterbi decoding (reference python/paddle/text/viterbi_decode.py →
viterbi_decode op).  Pure lax.scan dynamic program — jit-compiled once."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import primitive
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


@primitive("viterbi_decode", differentiable=False)
def _viterbi(potentials, transition, lengths, *, include_bos_eos_tag):
    """potentials: [B, T, N]; transition: [N, N]; lengths: [B].
    Returns (scores [B], paths [B, T])."""
    B, T, N = potentials.shape

    if include_bos_eos_tag:
        # last two tags are BOS(=N-2)/EOS(=N-1) per the reference contract
        bos, eos = N - 2, N - 1
        init = potentials[:, 0] + transition[bos][None, :]
    else:
        init = potentials[:, 0]

    def body(carry, t):
        alpha, = carry
        # alpha: [B, N]; scores of best path ending in each tag
        trans = alpha[:, :, None] + transition[None, :, :]  # [B, from, to]
        best_prev = jnp.argmax(trans, axis=1)               # [B, N]
        alpha_new = jnp.max(trans, axis=1) + potentials[:, t]
        # only advance rows still inside their length
        active = (t < lengths)[:, None]
        alpha_out = jnp.where(active, alpha_new, alpha)
        bp = jnp.where(active, best_prev, jnp.arange(N)[None, :])
        return (alpha_out,), bp

    (alpha,), bps = jax.lax.scan(body, (init,), jnp.arange(1, T))
    if include_bos_eos_tag:
        alpha = alpha + transition[:, N - 1][None, :]

    last_tag = jnp.argmax(alpha, axis=-1)            # [B]
    scores = jnp.max(alpha, axis=-1)

    def backtrack(carry, bp_t):
        tag = carry
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan: ys[i] = tag at step i+1, final carry = tag at step 0
    first_tag, path_tail = jax.lax.scan(backtrack, last_tag, bps,
                                        reverse=True)
    paths = jnp.concatenate([first_tag[None, :], path_tail], axis=0).T
    return scores, paths


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    return _viterbi(potentials, transition_params, lengths,
                    include_bos_eos_tag=include_bos_eos_tag)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
