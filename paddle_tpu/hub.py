"""paddle.hub parity (reference python/paddle/hub.py): load/list/help
over a ``hubconf.py`` in a LOCAL directory.  The github/gitee sources
require network egress this environment doesn't have — they raise a
documented guard; local-source repos (the reference's ``source='local'``)
work fully."""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_builtin_list = list


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str):
    if source not in ("local",):
        raise NotImplementedError(
            f"hub source {source!r} needs network egress; use "
            "source='local' with a checked-out repo directory "
            "(reference hub.py github/gitee download path)")


def list(repo_dir: str, source: str = "github", force_reload: bool = False):
    """Entrypoint names exposed by the repo's hubconf.py."""
    _check_source(source if os.path.isdir(repo_dir) is False else "local")
    mod = _load_hubconf(repo_dir)
    return _builtin_list(
        n for n in dir(mod)
        if callable(getattr(mod, n)) and not n.startswith("_"))


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False):
    _check_source(source if os.path.isdir(repo_dir) is False else "local")
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"hubconf has no entrypoint {model!r}")
    return fn.__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    _check_source(source if os.path.isdir(repo_dir) is False else "local")
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None:
        raise ValueError(f"hubconf has no entrypoint {model!r}")
    return fn(**kwargs)
