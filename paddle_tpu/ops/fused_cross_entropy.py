"""Logits-free fused cross-entropy head.

Every training path used to materialize the full ``[B, S, V]`` fp32
logits tensor before taking the loss — at GPT-125M bench shape
(b8×s1024, V≈50k) that is ~1.6 GB of activations plus the same again for
the softmax backward.  :func:`linear_cross_entropy` fuses the LM-head
matmul with the softmax-CE reduction: it streams over vocab chunks
keeping only O(T) running accumulators (online max / logsumexp / label
logit), wrapped in a ``jax.custom_vjp`` whose backward *recomputes* the
chunked softmax rows and emits grads w.r.t. both the activations and the
(possibly tied) head weight — ``[T, V]`` is never stored.

Three tiers behind one API:

* pure-XLA ``lax.scan`` chunking (works everywhere, incl. the CPU tier-1
  lane) — the default off-TPU;
* a Pallas TPU kernel (``ops/pallas/linear_ce.py``) with block sizes
  selected through ``ops/pallas/autotune`` — the default on TPU;
* a vocab-parallel variant (``axis_name=...``) for mp-sharded heads that
  fuses the two-pass ``parallel/manual.py:vocab_parallel_nll``
  all-reduces (max, then sum-exp + label pick) into the chunk loop: one
  ``pmax`` plus ONE ``psum`` of the stacked accumulators per call, and
  the backward's dx all-reduce replaces the ``mp_copy`` VJP psum.

:func:`softmax_nll_chunked` applies the same chunked reduction to
*already materialized* logits (the large-vocab 3-D ``F.cross_entropy``
case): the fp32 log-prob copy and its softmax residual are never built —
the backward recomputes probabilities chunk by chunk.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["linear_cross_entropy", "softmax_nll_chunked",
           "default_chunk", "naive_peak_bytes", "chunked_peak_bytes"]

NEG = -1e30

# F.cross_entropy routes 3-D hard-label losses through the chunked path
# when the class dim is at least this wide (module attr so tests/users
# can tune it; small vocabs lose more to the scan than they save).
MIN_FUSED_VOCAB = 16384


def default_chunk(vocab: int) -> int:
    """Vocab-chunk width: full vocab when small, else 2048 — a [T, chunk]
    fp32 buffer per scan step (64 MB at the bench's T=8192) while keeping
    the per-chunk matmul MXU-shaped."""
    return vocab if vocab <= 2048 else 2048


def naive_peak_bytes(tokens: int, vocab: int) -> int:
    """Activation bytes of the naive head: fp32 logits + the softmax
    (log-prob) residual the backward keeps."""
    return 2 * tokens * vocab * 4


def chunked_peak_bytes(tokens: int, vocab: int, chunk: Optional[int] = None
                       ) -> int:
    """Activation bytes of the chunked head: two live [T, chunk] buffers
    (logits + exp) plus the four [T] running accumulators and saved lse."""
    c = chunk or default_chunk(vocab)
    return 2 * tokens * c * 4 + 5 * tokens * 4


class _Meta(NamedTuple):
    """Hashable static config for the custom_vjp (nondiff arg)."""
    chunk: int
    w_layout: str               # "vh" ([V, H]) or "hv" ([H, V])
    ignore_index: Optional[int]
    label_smoothing: float
    axis_name: Optional[str]    # vocab-parallel mesh axis (inside shard_map)
    vocab_global: int           # full vocab across all shards


def _slice_w(w, c0, width, meta: _Meta):
    axis = 0 if meta.w_layout == "vh" else 1
    return lax.dynamic_slice_in_dim(w, c0, width, axis=axis)


def _logits_chunk(x2, w_c, meta: _Meta):
    """[T, C] fp32 logits for one vocab chunk."""
    eq = "th,ch->tc" if meta.w_layout == "vh" else "th,hc->tc"
    return jnp.einsum(eq, x2, w_c, preferred_element_type=jnp.float32)


def _fwd_stats(carry, c0, w_c, x2, labels2, off, meta: _Meta):
    """Online-update the (m, s, zl, sz) accumulators with one chunk.

    m: running max; s: sum-exp rescaled to m; zl: raw label logit;
    sz: sum of raw logits (only tracked under label smoothing).
    """
    m, s, zl, sz = carry
    z = _logits_chunk(x2, w_c, meta)                       # [T, C]
    width = z.shape[1]
    cols = off + c0 + jnp.arange(width)                    # global class ids
    m_new = jnp.maximum(m, jnp.max(z, axis=-1))
    s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(z - m_new[:, None]), -1)
    hit = labels2[:, None] == cols[None, :]
    zl = zl + jnp.sum(jnp.where(hit, z, 0.0), -1)
    if meta.label_smoothing > 0.0:
        sz = sz + jnp.sum(z, -1)
    return (m_new, s, zl, sz)


def _scan_chunks(step, carry, w, v_local, meta: _Meta):
    """Run ``step(carry, c0, w_chunk)`` over the whole local vocab:
    a lax.scan over the evenly divisible prefix plus one static epilogue
    chunk for the remainder (uneven V needs no padding or masking)."""
    chunk = min(meta.chunk, v_local)
    nc = v_local // chunk
    rem = v_local - nc * chunk

    if nc == 1 and rem == 0:
        return step(carry, 0, _slice_w(w, 0, v_local, meta))

    def body(c, i):
        c0 = i * chunk
        return step(c, c0, _slice_w(w, c0, chunk, meta)), None

    carry, _ = lax.scan(body, carry, jnp.arange(nc))
    if rem:
        carry = step(carry, nc * chunk, _slice_w(w, nc * chunk, rem, meta))
    return carry


def _rank_offset(w, meta: _Meta):
    v_local = w.shape[0] if meta.w_layout == "vh" else w.shape[1]
    if meta.axis_name is None:
        return v_local, jnp.zeros((), jnp.int32)
    return v_local, (lax.axis_index(meta.axis_name) * v_local).astype(
        jnp.int32)


def _lse_and_terms(x2, w, labels2, meta: _Meta):
    """Shared forward reduction: returns (lse, zl, sz) — all [T] fp32,
    globally reduced when vocab-parallel."""
    T = x2.shape[0]
    v_local, off = _rank_offset(w, meta)
    carry = (jnp.full((T,), NEG, jnp.float32), jnp.zeros((T,), jnp.float32),
             jnp.zeros((T,), jnp.float32), jnp.zeros((T,), jnp.float32))
    step = functools.partial(_fwd_stats, x2=x2, labels2=labels2, off=off,
                             meta=meta)
    m, s, zl, sz = _scan_chunks(step, carry, w, v_local, meta)
    if meta.axis_name is not None:
        # fuse the reference's two-pass all-reduces: one pmax for the
        # global max, then ONE psum carrying sum-exp, label logit and
        # (optionally) the smoothing sum together.
        m_g = lax.pmax(m, meta.axis_name)
        packed = jnp.stack([s * jnp.exp(m - m_g), zl, sz])
        packed = lax.psum(packed, meta.axis_name)
        s, zl, sz = packed[0], packed[1], packed[2]
        m = m_g
    return jnp.log(s) + m, zl, sz


def _nll_from_terms(lse, zl, sz, labels2, meta: _Meta):
    eps = meta.label_smoothing
    if eps > 0.0:
        nll = lse - (1.0 - eps) * zl - (eps / meta.vocab_global) * sz
    else:
        nll = lse - zl
    if meta.ignore_index is not None:
        nll = jnp.where(labels2 != meta.ignore_index, nll, 0.0)
    return nll


def _bwd_step(dx, c0, w_c, x2, labels2, g2, lse, off, meta: _Meta):
    """Recompute one chunk's softmax row; return (dx_acc, dw_chunk)."""
    z = _logits_chunk(x2, w_c, meta)                       # [T, C]
    width = z.shape[1]
    cols = off + c0 + jnp.arange(width)
    p = jnp.exp(z - lse[:, None])                          # softmax chunk
    eps = meta.label_smoothing
    y = (labels2[:, None] == cols[None, :]).astype(jnp.float32)
    if eps > 0.0:
        y = (1.0 - eps) * y + eps / meta.vocab_global
    dz = g2[:, None] * (p - y)                             # [T, C] fp32
    if meta.w_layout == "vh":
        dx = dx + jnp.einsum("tc,ch->th", dz, w_c,
                             preferred_element_type=jnp.float32)
        dw_c = jnp.einsum("tc,th->ch", dz, x2,
                          preferred_element_type=jnp.float32)
    else:
        dx = dx + jnp.einsum("tc,hc->th", dz, w_c,
                             preferred_element_type=jnp.float32)
        dw_c = jnp.einsum("th,tc->hc", x2, dz,
                          preferred_element_type=jnp.float32)
    return dx, dw_c


def _bwd_sweep(step, dx, w, v_local, meta: _Meta):
    """dx via the scan carry; dw chunks as STACKED scan outputs (each
    slot written once — carrying the full [V, H] buffer and
    dynamic-update-slicing it would re-copy it every iteration)."""
    chunk = min(meta.chunk, v_local)
    nc = v_local // chunk
    rem = v_local - nc * chunk
    vocab_axis = 0 if meta.w_layout == "vh" else 1

    if nc == 1 and rem == 0:
        dx, dw = step(dx, 0, _slice_w(w, 0, v_local, meta))
        return dx, dw

    def body(c, i):
        c0 = i * chunk
        return step(c, c0, _slice_w(w, c0, chunk, meta))

    dx, dw_stack = lax.scan(body, dx, jnp.arange(nc))
    if meta.w_layout == "vh":
        dw = dw_stack.reshape(nc * chunk, dw_stack.shape[-1])
    else:
        dw = jnp.moveaxis(dw_stack, 0, 1).reshape(w.shape[0], nc * chunk)
    if rem:
        dx, dw_rem = step(dx, nc * chunk,
                          _slice_w(w, nc * chunk, rem, meta))
        dw = jnp.concatenate([dw, dw_rem], axis=vocab_axis)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _lce(meta: _Meta, x, w, labels):
    nll, _ = _lce_fwd(meta, x, w, labels)
    return nll


def _lce_fwd(meta: _Meta, x, w, labels):
    x2 = x.reshape(-1, x.shape[-1])
    labels2 = labels.reshape(-1)
    lse, zl, sz = _lse_and_terms(x2, w, labels2, meta)
    nll = _nll_from_terms(lse, zl, sz, labels2, meta)
    return nll.reshape(labels.shape), (x, w, labels, lse)


def _lce_bwd(meta: _Meta, res, g):
    x, w, labels, lse = res
    x2 = x.reshape(-1, x.shape[-1])
    labels2 = labels.reshape(-1)
    g2 = g.reshape(-1).astype(jnp.float32)
    if meta.ignore_index is not None:
        g2 = jnp.where(labels2 != meta.ignore_index, g2, 0.0)
    v_local, off = _rank_offset(w, meta)
    step = functools.partial(_bwd_step, x2=x2, labels2=labels2, g2=g2,
                             lse=lse, off=off, meta=meta)
    dx, dw = _bwd_sweep(step, jnp.zeros(x2.shape, jnp.float32), w,
                        v_local, meta)
    if meta.axis_name is not None:
        # each rank saw only its vocab shard of the head matmul: the
        # activation grad is partial over mp (this psum replaces the
        # mp_copy VJP all-reduce of the unfused head); dw stays local.
        dx = lax.psum(dx, meta.axis_name)
    return (dx.astype(x.dtype).reshape(x.shape), dw.astype(w.dtype),
            np.zeros(labels.shape, jax.dtypes.float0))


_lce.defvjp(_lce_fwd, _lce_bwd)


def linear_cross_entropy(x, w, labels, *, w_layout: str = "vh",
                         chunk: Optional[int] = None,
                         ignore_index: Optional[int] = None,
                         label_smoothing: float = 0.0,
                         axis_name: Optional[str] = None,
                         backend: Optional[str] = None):
    """Per-token NLL of ``softmax(x @ head)`` without materializing logits.

    ``x``: [..., H] activations; ``w``: the (tied) head weight — [V, H]
    with ``w_layout="vh"`` (embedding layout) or [H, V] with ``"hv"``
    (Linear layout); ``labels``: [...] int global class ids.  Returns
    fp32 NLL shaped like ``labels`` (``ignore_index`` rows are 0).

    ``axis_name``: set to the mp mesh axis when ``w`` is the LOCAL vocab
    shard inside an all-manual ``shard_map`` — collectives (one pmax, one
    psum forward; one dx psum backward) are fused into the chunk loop.

    ``backend``: "xla" (lax.scan chunking), "pallas" (TPU kernel,
    dense-only), or None = pallas on TPU when eligible, else xla.
    """
    if w_layout not in ("vh", "hv"):
        raise ValueError(f"w_layout must be 'vh' or 'hv', got {w_layout!r}")
    v_local = w.shape[0] if w_layout == "vh" else w.shape[1]
    if backend is None:
        backend = "pallas" if (axis_name is None and _pallas_auto()) \
            else "xla"
    if backend == "pallas":
        if axis_name is not None:
            raise ValueError("backend='pallas' is dense-only; the "
                             "vocab-parallel tier runs the XLA chunk loop")
        from .pallas.linear_ce import linear_cross_entropy_pallas
        w_vh = w if w_layout == "vh" else jnp.swapaxes(w, 0, 1)
        return linear_cross_entropy_pallas(
            x, w_vh, labels, chunk=chunk, ignore_index=ignore_index,
            label_smoothing=label_smoothing)
    if backend != "xla":
        raise ValueError(f"unknown backend {backend!r}")
    n_shards = 1
    if axis_name is not None:
        n_shards = lax.axis_size(axis_name)
    meta = _Meta(chunk=int(chunk or default_chunk(v_local)),
                 w_layout=w_layout, ignore_index=ignore_index,
                 label_smoothing=float(label_smoothing),
                 axis_name=axis_name, vocab_global=v_local * n_shards)
    return _lce(meta, x, w, labels.astype(jnp.int32))


def _pallas_auto() -> bool:
    """Default to the Pallas tier only on real TPU hardware — interpret
    mode off-TPU is a correctness lane, not a perf one (tests opt in
    explicitly via backend="pallas")."""
    try:
        return jax.devices()[0].platform.lower() in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# chunked softmax-CE over ALREADY materialized logits (the 3-D large-vocab
# F.cross_entropy case): saves the fp32 log-prob copy + softmax residual.
# ---------------------------------------------------------------------------
class _SoftmaxMeta(NamedTuple):
    chunk: int
    ignore_index: Optional[int]
    label_smoothing: float


def _logits_terms(z2, labels2, meta: _SoftmaxMeta):
    """(lse, zl, sz) from [T, V] logits via static chunk slices."""
    T, V = z2.shape
    chunk = min(meta.chunk, V)
    m = jnp.full((T,), NEG, jnp.float32)
    s = jnp.zeros((T,), jnp.float32)
    zl = jnp.zeros((T,), jnp.float32)
    sz = jnp.zeros((T,), jnp.float32)
    for c0 in range(0, V, chunk):
        z = z2[:, c0:c0 + chunk].astype(jnp.float32)
        cols = c0 + jnp.arange(z.shape[1])
        m_new = jnp.maximum(m, jnp.max(z, -1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(z - m_new[:, None]), -1)
        m = m_new
        zl = zl + jnp.sum(
            jnp.where(labels2[:, None] == cols[None, :], z, 0.0), -1)
        if meta.label_smoothing > 0.0:
            sz = sz + jnp.sum(z, -1)
    return jnp.log(s) + m, zl, sz


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _softmax_nll(meta: _SoftmaxMeta, logits, labels):
    nll, _ = _softmax_nll_fwd(meta, logits, labels)
    return nll


def _softmax_nll_fwd(meta: _SoftmaxMeta, logits, labels):
    V = logits.shape[-1]
    z2 = logits.reshape(-1, V)
    labels2 = labels.reshape(-1)
    lse, zl, sz = _logits_terms(z2, labels2, meta)
    lmeta = _Meta(meta.chunk, "vh", meta.ignore_index, meta.label_smoothing,
                  None, V)
    nll = _nll_from_terms(lse, zl, sz, labels2, lmeta)
    return nll.reshape(labels.shape), (logits, labels, lse)


def _softmax_nll_bwd(meta: _SoftmaxMeta, res, g):
    logits, labels, lse = res
    V = logits.shape[-1]
    z2 = logits.reshape(-1, V)
    labels2 = labels.reshape(-1)
    g2 = g.reshape(-1).astype(jnp.float32)
    if meta.ignore_index is not None:
        g2 = jnp.where(labels2 != meta.ignore_index, g2, 0.0)
    chunk = min(meta.chunk, V)
    eps = meta.label_smoothing
    parts = []
    # the cotangent itself is [T, V] (unavoidable — logits are an input),
    # but the softmax is recomputed per chunk instead of stored.
    for c0 in range(0, V, chunk):
        z = z2[:, c0:c0 + chunk].astype(jnp.float32)
        cols = c0 + jnp.arange(z.shape[1])
        p = jnp.exp(z - lse[:, None])
        y = (labels2[:, None] == cols[None, :]).astype(jnp.float32)
        if eps > 0.0:
            y = (1.0 - eps) * y + eps / V
        parts.append((g2[:, None] * (p - y)).astype(logits.dtype))
    dz = jnp.concatenate(parts, axis=-1) if len(parts) > 1 else parts[0]
    return (dz.reshape(logits.shape),
            np.zeros(labels.shape, jax.dtypes.float0))


_softmax_nll.defvjp(_softmax_nll_fwd, _softmax_nll_bwd)


def softmax_nll_chunked(logits, labels, *, chunk: Optional[int] = None,
                        ignore_index: Optional[int] = None,
                        label_smoothing: float = 0.0):
    """Per-token NLL over materialized logits via the chunked reduction:
    forward keeps O(T) accumulators (no fp32 log-prob copy), backward
    recomputes softmax chunks from the saved lse."""
    V = logits.shape[-1]
    meta = _SoftmaxMeta(chunk=int(chunk or default_chunk(V)),
                        ignore_index=ignore_index,
                        label_smoothing=float(label_smoothing))
    return _softmax_nll(meta, logits, labels.astype(jnp.int32))
