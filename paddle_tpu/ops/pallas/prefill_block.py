"""Pallas TPU megakernel for the fused chunked-prefill transformer block.

The chunked-prefill twin of ``decode_block.py`` (ISSUE 18, ROADMAP
item 3): one kernel invocation runs ONE layer for one ``[chunk, H]``
tile of prompt tokens of ONE sequence — norm → qkv projection → RoPE at
the tile's absolute positions → flash-style CAUSAL attention over the
sequence's committed KV pages plus the in-chunk tokens → out-projection
+ residual → norm → FFN → residual.  The residual tile, the projected
q/k/v, and the online-softmax state live in VMEM scratch for the whole
layer; the only HBM traffic is the weights (streamed once), the KV
pages the attention DMA-gathers through the block table, and the tile's
read + write-back — versus ~8 full round-trips of the ``[chunk, H]``
stream per layer in the per-op chain (docs/performance.md).

Shape of the kernel:

* grid ``(nt,)`` — ``nt`` page-chunks of the sequence's block-table
  row; the whole ``[chunk, H]`` tile is resident at every step.
* the prologue at chunk 0 runs norm/qkv/rope for all ``chunk`` tokens,
  writing q and the tile's (quantize-round-tripped, when the pool is
  int8) k/v to scratch; pages DMA-copy through the same revolving
  TWO-SLOT staging buffer as the decode kernel — each grid step starts
  the NEXT page-chunk's copies before waiting on its own
  (``cost.DMA_STAGING_SLOTS``) — and fold into the causal online
  softmax (committed positions ``t < start`` only); the epilogue folds
  the IN-CHUNK tokens under the causal mask (the pool scatter happens
  host-side after the kernel, so pool semantics match the per-op
  tier's positional ``.at[blk, off].set``), then runs out-proj, norm,
  FFN and both residual adds.
* pages per chunk is the autotuned knob (``"prefill_block"`` key in
  ``ops/pallas/autotune``), candidates filtered through
  ``cost.prefill_block_vmem`` with the SAME floor convention as the
  decode kernel (``decode_block._floor_candidates``).

Limits (the ``ops/decode_block.prefill_block`` dispatch falls back to
the reference tier outside them, or raises the typed
``PrefillBlockUnsupportedError`` when the kernel is forced): the
layer's full weight set plus the double-buffered page staging plus the
chunk-tile scratch must fit the shared VMEM budget, and ``head_dim``
is capped — both read from ``analysis/kernel/cost.py``, never a local
constant.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...analysis.kernel import cost
from ..paged_kv import (KV_SCALE_EPS, QuantizedKVPool, is_quantized_pool,
                        quantize_kv)
from .common import NEG_INF, use_interpret
from .decode_block import (DEFAULT_PAGES, MAX_HEAD_DIM, VMEM_BUDGET_BYTES,
                           _PAGE_CANDIDATES, _floor_candidates, _mmw,
                           _norm_rows, _param_keys, _pool_itemsize,
                           _rot_half)

__all__ = ["prefill_block_pallas", "tune_prefill_block",
           "unsupported_reason"]


class _Meta(NamedTuple):
    hidden: int
    num_heads: int
    kv_heads: int
    head_dim: int
    block_size: int
    norm: str
    activation: str
    eps: float
    rope: bool
    fused_qkv: bool
    bias: bool
    pages: int           # pages staged per attention chunk
    nt: int              # number of page-chunks (grid length)
    mb: int              # block-table width
    chunk: int           # resident prompt-tile length (Ts)
    scale: float
    weight_dtype: Optional[str] = None
    group_size: int = -1
    kv_quant: bool = False
    param_keys: Tuple[str, ...] = ()


def _vmem_total(spec, pages: int, chunk: int, wbytes: int,
                pool_itemsize: int, x_itemsize: int,
                kv_quant: bool = False) -> int:
    """One layer invocation's VMEM bytes — the shared cost model's
    number (analysis/kernel/cost.py), never a local formula."""
    return cost.prefill_block_vmem(
        hidden=spec.hidden, num_heads=spec.num_heads,
        kv_heads=spec.kv_heads, head_dim=spec.head_dim,
        block_size=spec.block_size, pages=pages, chunk=chunk,
        weight_bytes=wbytes, pool_itemsize=pool_itemsize,
        x_itemsize=x_itemsize, kv_quant=kv_quant)["total"]


def unsupported_reason(spec, lp, pool_k, chunk: int) -> Optional[str]:
    """None when this layer + chunk length fits the kernel, else the
    reason (the ``ops/decode_block.prefill_block`` dispatch signal).
    Layout checks (a dense layer dict) live here; every byte/cap limit
    is delegated to the shared cost model so the static analysis and
    this runtime gate cannot drift."""
    keys = _param_keys(spec)
    missing = [n for n in keys if n not in lp]
    if missing:
        return (f"layer dict lacks {missing} — not a dense "
                f"{spec.activation} block"
                + (" in the quantized export layout"
                   if getattr(spec, "weight_dtype", None) else
                   " (MoE FFNs run the reference tier)"))
    wbytes = sum(lp[n].size * lp[n].dtype.itemsize for n in keys)
    return cost.prefill_block_unsupported_reason(
        hidden=spec.hidden, num_heads=spec.num_heads,
        kv_heads=spec.kv_heads, head_dim=spec.head_dim,
        block_size=spec.block_size, chunk=int(chunk), rope=spec.rope,
        weight_bytes=wbytes, pool_itemsize=_pool_itemsize(pool_k),
        x_itemsize=lp[keys[0]].dtype.itemsize,
        kv_quant=is_quantized_pool(pool_k),
        budget=VMEM_BUDGET_BYTES)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------
def _kernel(*refs, meta: _Meta):
    nw = len(meta.param_keys)
    np_ = 4 if meta.kv_quant else 2
    start_ref, bt_ref, x_ref, cos_ref, sin_ref = refs[:5]
    w = dict(zip(meta.param_keys, refs[5:5 + nw]))
    pool_refs = refs[5 + nw:5 + nw + np_]
    x_out_ref, kn_ref, vn_ref = refs[5 + nw + np_:8 + nw + np_]
    if meta.kv_quant:
        pool_k_ref, pool_v_ref, pool_ks_ref, pool_vs_ref = pool_refs
        (q_scr, kn_scr, vn_scr, m_scr, l_scr, acc_scr, kbuf, vbuf,
         ksbuf, vsbuf, sem) = refs[8 + nw + np_:]
    else:
        pool_k_ref, pool_v_ref = pool_refs
        (q_scr, kn_scr, vn_scr, m_scr, l_scr, acc_scr, kbuf, vbuf,
         sem) = refs[8 + nw + np_:]

    jt = pl.program_id(0)
    Hq, Hkv, D = meta.num_heads, meta.kv_heads, meta.head_dim
    G = Hq // Hkv
    P, BS, Ts = meta.pages, meta.block_size, meta.chunk
    start = start_ref[0]

    # ---- prologue: norm1 + qkv + rope for the whole tile, once -------
    @pl.when(jt == 0)
    def _pro():
        x = x_ref[:].astype(jnp.float32)                    # [Ts, H]
        y = _norm_rows(x, w["ln1_w"][:],
                       w["ln1_b"][:] if meta.fused_qkv else None, meta)
        if meta.fused_qkv:
            z = _mmw(y, w, "qkv_w", meta) + w["qkv_b"][:][None, :]
            z = z.reshape(Ts, Hq, 3 * D)
            q, k, v = z[..., :D], z[..., D:2 * D], z[..., 2 * D:]
        else:
            q = _mmw(y, w, "q_w", meta).reshape(Ts, Hq, D)
            k = _mmw(y, w, "k_w", meta).reshape(Ts, Hkv, D)
            v = _mmw(y, w, "v_w", meta).reshape(Ts, Hkv, D)
        if meta.rope:
            cos = cos_ref[:].astype(jnp.float32)[:, None, :]
            sin = sin_ref[:].astype(jnp.float32)[:, None, :]
            q = q * cos + _rot_half(q) * sin
            k = k * cos + _rot_half(k) * sin
        q_scr[:] = q.transpose(1, 0, 2)                     # [Hq, Ts, D]
        if meta.kv_quant:
            # attend the int8-ROUND-TRIPPED in-chunk k/v: the host-side
            # scatter quantizes these rows into the pool, so attending
            # the stored value keeps this fill consistent with the XLA
            # tier (which gathers its own freshly-quantized pages) and
            # with what every future step reads back
            ks = jnp.maximum(jnp.max(jnp.abs(k), axis=-1,
                                     keepdims=True),
                             KV_SCALE_EPS) / 127.0
            vs = jnp.maximum(jnp.max(jnp.abs(v), axis=-1,
                                     keepdims=True),
                             KV_SCALE_EPS) / 127.0
            kn_scr[:] = (jnp.clip(jnp.round(k / ks), -127, 127)
                         * ks).transpose(1, 0, 2)
            vn_scr[:] = (jnp.clip(jnp.round(v / vs), -127, 127)
                         * vs).transpose(1, 0, 2)
        else:
            kn_scr[:] = k.transpose(1, 0, 2)                # [Hkv, Ts, D]
            vn_scr[:] = v.transpose(1, 0, 2)
        kn_ref[:] = k.astype(kn_ref.dtype)                  # [Ts, Hkv, D]
        vn_ref[:] = v.astype(vn_ref.dtype)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # ---- attention page-chunk: double-buffered DMA (chunk jt's copies
    # started one grid step earlier; start jt+1's into the other slot
    # before waiting) then fold COMMITTED positions (t < start) into the
    # online softmax of every in-chunk query --------------------------
    def _page_copies(ct, slot):
        copies = []
        for p in range(P):
            idx = jnp.minimum(ct * P + p, meta.mb - 1)
            phys = jnp.maximum(bt_ref[idx], 0)
            copies += [pltpu.make_async_copy(pool_k_ref.at[phys],
                                             kbuf.at[slot, p],
                                             sem.at[slot, p, 0]),
                       pltpu.make_async_copy(pool_v_ref.at[phys],
                                             vbuf.at[slot, p],
                                             sem.at[slot, p, 1])]
            if meta.kv_quant:
                copies += [pltpu.make_async_copy(pool_ks_ref.at[phys],
                                                 ksbuf.at[slot, p],
                                                 sem.at[slot, p, 2]),
                           pltpu.make_async_copy(pool_vs_ref.at[phys],
                                                 vsbuf.at[slot, p],
                                                 sem.at[slot, p, 3])]
        return copies

    slot = jax.lax.rem(jt, 2)

    @pl.when(jt == 0)
    def _warm_dma():
        for c in _page_copies(0, 0):
            c.start()

    @pl.when(jt + 1 < meta.nt)
    def _start_next():
        for c in _page_copies(jt + 1, jax.lax.rem(jt + 1, 2)):
            c.start()

    for c in _page_copies(jt, slot):
        c.wait()

    if meta.kv_quant:
        k_all = (kbuf[slot].astype(jnp.float32)
                 * ksbuf[slot].astype(jnp.float32)[..., None])
        v_all = (vbuf[slot].astype(jnp.float32)
                 * vsbuf[slot].astype(jnp.float32)[..., None])
        k_all = k_all.reshape(P * BS, Hkv, D)
        v_all = v_all.reshape(P * BS, Hkv, D)
    else:
        k_all = kbuf[slot].reshape(P * BS, Hkv, D).astype(jnp.float32)
        v_all = vbuf[slot].reshape(P * BS, Hkv, D).astype(jnp.float32)
    t_pos = jt * (P * BS) + jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, P * BS), 2)                       # [1, 1, T]
    valid = t_pos < start
    for kv in range(Hkv):
        sl = slice(kv * G, (kv + 1) * G)
        qh = q_scr[sl]                                      # [G, Ts, D]
        s = jax.lax.dot_general(qh, k_all[:, kv, :],
                                (((2,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = jnp.where(valid, s * meta.scale, NEG_INF)       # [G, Ts, T]
        m_prev = m_scr[sl]                                  # [G, Ts]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        pw = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[sl] = alpha * l_scr[sl] + jnp.sum(pw, axis=-1)
        acc_scr[sl] = acc_scr[sl] * alpha[..., None] + jax.lax.dot_general(
            pw, v_all[:, kv, :], (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[sl] = m_new

    # ---- epilogue: fold the IN-CHUNK tokens under the causal mask,
    # then proj/norm/FFN for the whole tile ---------------------------
    @pl.when(jt == meta.nt - 1)
    def _epi():
        qi = jax.lax.broadcasted_iota(jnp.int32, (Ts, Ts), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (Ts, Ts), 1)
        causal = (ki <= qi)[None, :, :]                     # [1, Ts, Ts]
        heads = []
        for kv in range(Hkv):
            sl = slice(kv * G, (kv + 1) * G)
            qh = q_scr[sl]                                  # [G, Ts, D]
            s = jax.lax.dot_general(qh, kn_scr[kv],
                                    (((2,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = jnp.where(causal, s * meta.scale, NEG_INF)  # [G, Ts, Ts]
            m_prev = m_scr[sl]
            m_f = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            pw = jnp.exp(s - m_f[..., None])
            alpha = jnp.exp(m_prev - m_f)
            l_f = alpha * l_scr[sl] + jnp.sum(pw, axis=-1)
            acc_f = acc_scr[sl] * alpha[..., None] \
                + jax.lax.dot_general(pw, vn_scr[kv],
                                      (((2,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            heads.append(acc_f / jnp.maximum(l_f, 1e-30)[..., None])
        attn = jnp.concatenate(heads, axis=0)               # [Hq, Ts, D]
        attn = attn.transpose(1, 0, 2).reshape(Ts, Hq * D)
        x = x_ref[:].astype(jnp.float32)                    # [Ts, H]
        proj = _mmw(attn, w,
                    "proj_w" if meta.fused_qkv else "o_w", meta)
        if meta.bias:
            proj = proj + w["proj_b"][:][None, :]
        x2 = x + proj
        y2 = _norm_rows(x2, w["ln2_w"][:],
                        w["ln2_b"][:] if meta.fused_qkv else None, meta)
        if meta.activation == "swiglu":
            f = jax.nn.silu(_mmw(y2, w, "gate_w", meta)) \
                * _mmw(y2, w, "up_w", meta)
            o = _mmw(f, w, "down_w", meta)
        else:
            h = jax.nn.gelu(_mmw(y2, w, "fc1_w", meta)
                            + w["fc1_b"][:][None, :], approximate=True)
            o = _mmw(h, w, "fc2_w", meta) + w["fc2_b"][:][None, :]
        x_out_ref[:] = (x2 + o).astype(x_out_ref.dtype)


# ---------------------------------------------------------------------------
# host wrapper + autotune
# ---------------------------------------------------------------------------
def _fitting_candidates(spec, chunk: int, mb: int, pool_itemsize: int,
                        wbytes: int, x_itemsize: int,
                        kv_quant: bool = False) -> Tuple[int, ...]:
    """Page-chunk candidates the cost model says can fit this chunk
    length — provably-overflowing ones never reach the tuner; the floor
    convention is the decode kernel's (``_floor_candidates``)."""
    cands = tuple(
        p for p in _PAGE_CANDIDATES
        if p <= max(mb, 1)
        and _vmem_total(spec, p, chunk, wbytes, pool_itemsize,
                        x_itemsize, kv_quant) <= VMEM_BUDGET_BYTES)
    return _floor_candidates(cands)


def _tuned_pages(spec, lp, pool_k, mb: int, chunk: int, args) -> int:
    from .autotune import FLAGS, lookup, pick
    keys = _param_keys(spec)
    wbytes = sum(lp[n].size * lp[n].dtype.itemsize for n in keys)
    x_isz = lp[keys[0]].dtype.itemsize
    kvq = is_quantized_pool(pool_k)
    p_isz = _pool_itemsize(pool_k)
    pool_dt = ("int8+scale" if kvq else str(pool_k.dtype))
    cands = _fitting_candidates(spec, chunk, mb, p_isz, wbytes, x_isz,
                                kvq)
    default = max(p for p in cands if p <= DEFAULT_PAGES)
    key = (chunk, spec.hidden, spec.num_heads, spec.kv_heads,
           spec.head_dim, spec.block_size, mb, spec.activation, pool_dt,
           getattr(spec, "weight_dtype", None),
           getattr(spec, "group_size", -1))
    if not FLAGS.use_autotune:
        return default
    if isinstance(args[0], jax.core.Tracer):
        return lookup("prefill_block", key, default)

    def run(cand):
        return jax.jit(functools.partial(_call, spec=spec,
                                         pages=int(cand)))

    return int(pick("prefill_block", key, cands, run, args, default,
                    valid=lambda p: _vmem_total(
                        spec, int(p), chunk, wbytes, p_isz, x_isz, kvq)
                    <= VMEM_BUDGET_BYTES))


def _call(x, lp, pool_k, pool_v, bt_row, start, cos, sin, *, spec,
          pages: int, scale: Optional[float] = None):
    """Build + invoke the pallas_call for a fixed page-chunk size;
    returns (x_out [Ts, H], k_new, v_new [Ts, Hkv, D]) — the pool
    scatter happens in :func:`prefill_block_pallas` so pool semantics
    match the per-op tier exactly."""
    _, Ts, H = x.shape
    Hq, Hkv, D = spec.num_heads, spec.kv_heads, spec.head_dim
    BS = spec.block_size
    mb = bt_row.shape[0]
    nt = -(-mb // pages)
    keys = _param_keys(spec)
    kvq = is_quantized_pool(pool_k)
    meta = _Meta(hidden=H, num_heads=Hq, kv_heads=Hkv, head_dim=D,
                 block_size=BS, norm=spec.norm,
                 activation=spec.activation, eps=spec.eps,
                 rope=spec.rope, fused_qkv=spec.fused_qkv,
                 bias=spec.bias, pages=pages, nt=nt, mb=mb, chunk=Ts,
                 scale=(scale if scale is not None
                        else 1.0 / (D ** 0.5)),
                 weight_dtype=getattr(spec, "weight_dtype", None),
                 group_size=getattr(spec, "group_size", -1),
                 kv_quant=kvq, param_keys=keys)

    def wspec(arr):
        if arr.ndim == 1:
            return pl.BlockSpec((arr.shape[0],), lambda j: (0,))
        return pl.BlockSpec(arr.shape, lambda j: (0,) * arr.ndim)

    n_pool = 4 if kvq else 2
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),       # start (prefix len)
        pl.BlockSpec(memory_space=pltpu.SMEM),       # block-table row
        pl.BlockSpec((Ts, H), lambda j: (0, 0)),     # residual tile
        pl.BlockSpec((Ts, D), lambda j: (0, 0)),     # cos rows
        pl.BlockSpec((Ts, D), lambda j: (0, 0)),     # sin rows
        *[wspec(lp[n]) for n in keys],
        pl.BlockSpec(memory_space=pltpu.ANY),        # pool_k (codes)
        pl.BlockSpec(memory_space=pltpu.ANY),        # pool_v (codes)
        *[pl.BlockSpec(memory_space=pltpu.ANY)] * (n_pool - 2),
    ]
    # quantized pools output fp32 k/v tiles (the host scatter
    # re-quantizes them, so pool contents match the reference tier's)
    kv_dt = jnp.float32 if kvq else pool_k.dtype
    out_specs = [
        pl.BlockSpec((Ts, H), lambda j: (0, 0)),
        pl.BlockSpec((Ts, Hkv, D), lambda j: (0, 0, 0)),
        pl.BlockSpec((Ts, Hkv, D), lambda j: (0, 0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((Ts, H), x.dtype),
        jax.ShapeDtypeStruct((Ts, Hkv, D), kv_dt),
        jax.ShapeDtypeStruct((Ts, Hkv, D), kv_dt),
    ]
    pool_dt = pool_k.data.dtype if kvq else pool_k.dtype
    scratch = [
        pltpu.VMEM((Hq, Ts, D), jnp.float32),        # q tile
        pltpu.VMEM((Hkv, Ts, D), jnp.float32),       # in-chunk k
        pltpu.VMEM((Hkv, Ts, D), jnp.float32),       # in-chunk v
        pltpu.VMEM((Hq, Ts), jnp.float32),           # running max
        pltpu.VMEM((Hq, Ts), jnp.float32),           # running sum
        pltpu.VMEM((Hq, Ts, D), jnp.float32),        # attn accumulator
        # two revolving DMA slots (cost.DMA_STAGING_SLOTS)
        pltpu.VMEM((2, pages, BS, Hkv, D), pool_dt),
        pltpu.VMEM((2, pages, BS, Hkv, D), pool_dt),
    ]
    if kvq:
        scratch += [
            pltpu.VMEM((2, pages, BS, Hkv), jnp.float32),   # k scales
            pltpu.VMEM((2, pages, BS, Hkv), jnp.float32),   # v scales
        ]
    pools = ((pool_k.data, pool_v.data, pool_k.scale, pool_v.scale)
             if kvq else (pool_k, pool_v))
    cos2 = jnp.zeros((Ts, D), x.dtype) if cos is None else cos
    sin2 = jnp.zeros((Ts, D), x.dtype) if sin is None else sin
    return pl.pallas_call(
        functools.partial(_kernel, meta=meta),
        grid=(nt,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[*scratch,
                        pltpu.SemaphoreType.DMA((2, pages, n_pool))],
        interpret=use_interpret(),
    )(jnp.reshape(jnp.asarray(start, jnp.int32), (1,)),
      jnp.asarray(bt_row, jnp.int32), x[0], cos2, sin2,
      *[lp[n] for n in keys], *pools)


def prefill_block_pallas(x, lp, pool_k, pool_v, blk, off, bt_row, mask,
                         cos, sin, *, spec, start,
                         scale: Optional[float] = None,
                         pages: Optional[int] = None):
    """The megakernel tier of ``ops.decode_block.prefill_block`` —
    returns ``(x_out [1, Ts, H], pool_k, pool_v)`` with the tile's KV
    scattered at ``blk``/``off`` (the scatter runs host-side on the
    kernel's k/v outputs, so pool contents — including the dropped
    out-of-range writes of bucket-padded rows — are IDENTICAL to the
    per-op tier's ``.at[blk, off].set``).  ``mask`` is unused: the
    kernel derives causality from ``start`` and the tile positions."""
    del mask
    if pages is None:
        pages = _tuned_pages(spec, lp, pool_k, bt_row.shape[0],
                             x.shape[1],
                             (x, lp, pool_k, pool_v, bt_row, start,
                              cos, sin))
    x_out, k_new, v_new = _call(x, lp, pool_k, pool_v, bt_row, start,
                                cos, sin, spec=spec, pages=int(pages),
                                scale=scale)
    if is_quantized_pool(pool_k):
        kq, ks = quantize_kv(k_new)
        vq, vs = quantize_kv(v_new)
        pool_k = QuantizedKVPool(data=pool_k.data.at[blk, off].set(kq),
                                 scale=pool_k.scale.at[blk, off].set(ks))
        pool_v = QuantizedKVPool(data=pool_v.data.at[blk, off].set(vq),
                                 scale=pool_v.scale.at[blk, off].set(vs))
    else:
        pool_k = pool_k.at[blk, off].set(k_new.astype(pool_k.dtype))
        pool_v = pool_v.at[blk, off].set(v_new.astype(pool_v.dtype))
    return x_out[None], pool_k, pool_v


def tune_prefill_block(x, lp, pool_k, pool_v, blk, off, bt_row, mask,
                       cos, sin, *, spec, start,
                       scale: Optional[float] = None):
    """Eagerly time the page-chunk candidates for this geometry and
    cache the winner under the ``"prefill_block"`` autotune key
    (FLAGS.use_autotune must be on) — run once at engine warmup; traced
    calls then read the cache."""
    return prefill_block_pallas(x, lp, pool_k, pool_v, blk, off, bt_row,
                                mask, cos, sin, spec=spec, start=start,
                                scale=scale)
