"""Pallas TPU decode attention (MMHA analog) over a KV cache.

Port target: the reference's masked multi-head attention decode kernel
(/root/reference/paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu)
— one new query token per sequence attending to a preallocated KV cache
with a per-sequence valid length.  GQA native (q heads grouped onto kv
heads).  The block/paged variant (block_multi_head_attention_kernel.cu) maps
onto the same kernel via gather-free contiguous caches here; paged KV is
tracked separately.

Layouts (static shapes, XLA-friendly):
    q:        [B, Hq, D]       — the current step's query
    k_cache:  [B, T, Hkv, D]   — rows >= length are ignored
    v_cache:  [B, T, Hkv, D]
    lengths:  [B] int32        — number of valid cache rows per sequence
Returns [B, Hq, D].
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import NEG_INF, use_interpret

__all__ = ["decode_attention", "decode_attention_ref"]

DEFAULT_BLOCK_T = 512


def decode_attention_ref(q, k_cache, v_cache, lengths, scale=None):
    """Dense jnp reference (and CPU fallback)."""
    B, Hq, D = q.shape
    T, Hkv = k_cache.shape[1], k_cache.shape[2]
    G = Hq // Hkv
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    qg = q.reshape(B, Hkv, G, D)
    logits = jnp.einsum("bkgd,btkd->bkgt", qg.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) * s
    mask = jnp.arange(T)[None, None, None, :] < lengths[:, None, None, None]
    logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgt,btkd->bkgd", p,
                     v_cache.astype(jnp.float32))
    return out.reshape(B, Hq, D).astype(q.dtype)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, block_t, nt):
    b = pl.program_id(0)
    jt = pl.program_id(2)

    @pl.when(jt == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    q = q_ref[:]                                   # [G, D]
    k = k_ref[:]                                   # [bt, D]
    v = v_ref[:]                                   # [bt, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    t_pos = jt * block_t + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    s = jnp.where(t_pos < length, s, NEG_INF)
    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = m_new

    @pl.when(jt == nt - 1)
    def _final():
        o_ref[:] = (acc_scr[:]
                    / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _decode_pallas(q, k_cache, v_cache, lengths, scale):
    B, Hq, D = q.shape
    T, Hkv = k_cache.shape[1], k_cache.shape[2]
    G = Hq // Hkv
    bt = min(DEFAULT_BLOCK_T, T)
    pad_t = (-T) % bt
    if pad_t:
        k_cache = jnp.pad(k_cache, ((0, 0), (0, pad_t), (0, 0), (0, 0)))
        v_cache = jnp.pad(v_cache, ((0, 0), (0, pad_t), (0, 0), (0, 0)))
    Tp = T + pad_t
    nt = Tp // bt
    # [B, T, Hkv, D] -> [B, Hkv, T, D];  q -> [B, Hkv, G, D]
    kt = jnp.swapaxes(k_cache, 1, 2)
    vt = jnp.swapaxes(v_cache, 1, 2)
    qg = q.reshape(B, Hkv, G, D)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, block_t=bt, nt=nt),
        grid=(B, Hkv, nt),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # lengths, whole array
            pl.BlockSpec((None, None, G, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((None, None, bt, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((None, None, bt, D), lambda b, h, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, G, D),
                               lambda b, h, j: (b, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
        interpret=use_interpret(),
    )(lengths.astype(jnp.int32), qg, kt, vt)
    return out.reshape(B, Hq, D)


def decode_attention(q, k_cache, v_cache, lengths,
                     scale: Optional[float] = None,
                     use_pallas: Optional[bool] = None):
    """Single-step masked decode attention over a KV cache (MMHA analog).

    Differentiation is not needed on the decode path; this is forward-only.
    """
    B, Hq, D = q.shape
    Hkv = k_cache.shape[2]
    if Hq % Hkv != 0:
        raise ValueError(f"q heads ({Hq}) must be a multiple of kv heads "
                         f"({Hkv})")
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    if use_pallas is None:
        # same dispatch as every other kernel: real accelerator, forced
        # interpret (CPU tests), or forced Mosaic compile (TPU cross-
        # lowering lane)
        from ...core.flags import FLAGS
        if FLAGS.pallas_interpret or FLAGS.pallas_force_compile:
            use_pallas = True
        else:
            try:
                use_pallas = jax.devices()[0].platform.lower() in (
                    "tpu", "axon")
            except Exception:
                use_pallas = False
    if use_pallas:
        return _decode_pallas(q, k_cache, v_cache, lengths, s)
    return decode_attention_ref(q, k_cache, v_cache, lengths, s)
